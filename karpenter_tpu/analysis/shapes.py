"""Pass 6: axis/dtype abstract interpretation over the kernels (ops/, solver/).

The kernels are index arithmetic over named axes — S (scenarios), G
(groups), N (nodes), R (resources), T (types), K (requirement keys), V1
(interned values), nmax (claim slots) — but JAX arrays carry none of those
names: a ``[N, R] * [R, N]`` join broadcasts happily and miscomputes
silently. This pass walks every function with a tiny abstract interpreter:

- **bindings** get an abstract value (axes, dtype) at constructor sites —
  ``jnp.zeros((nmax, R), jnp.float32)`` binds axes ``(nmax, R)`` and dtype
  ``float32``, with axis identity taken from the local *names* used in the
  shape tuple;
- **propagation** runs through elementwise ``jnp`` calls and operators
  (broadcast joins, aligned from the right), indexing (``[:, None]``,
  integer drops, 1-D gathers), reductions with ``axis=``, ``reshape``/
  ``.T``/``astype``, ``one_hot``, and ``einsum`` specs (each spec letter
  must bind one axis name); ``vmap``/``scan`` wrappers and anything else
  degrade to *unknown*, never to a guess;
- **checks** fire only when both sides of a fact are known, so unknown
  values can never false-positive.

Rules:

- SHP600: unparsable file
- SHP601: axis-order mismatch — a broadcast join aligns two *different*
  named axes (or an einsum letter binds two different axes)
- SHP602: silent 64-bit widening — an explicit float64/int64 dtype in
  device code (f32→f64 promotion is a TPU hazard; x64 is off everywhere)
- SHP603: a literal dimension that bypasses the power-of-two bucket
  ladder (compile-cache buster; see PARITY.md §2.3 on bucketing)
- SHP604: a ``NamedSharding``/``PartitionSpec`` partitions an array axis
  whose literal dimension is not a power of two — after the encoder's
  pow2 padding every shardable axis IS a pow2 >= the (pow2) mesh axis it
  divides; a non-pow2 dim under a mesh-axis entry means the buffer skipped
  ``parallel.mesh.pad_args_for_mesh`` and GSPMD will reject or silently
  repad it (constructor sites: ``PartitionSpec(...)`` tuples tracked
  through local names and ``NamedSharding(mesh, spec)``; sinks:
  ``jax.device_put(x, s)`` / ``jax.lax.with_sharding_constraint(x, s)``)

Host-side numpy is out of scope on purpose: only ``jax``/``jax.numpy``
origins construct tracked values, so encode-time ``np.int64`` index math
stays silent.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .astutil import call_name, import_aliases, iter_py_files, parse_file
from .findings import Finding, Severity, SourceFile

RULES = {
    "SHP600": "unparsable file (shape pass)",
    "SHP601": "axis-order mismatch in a broadcast join",
    "SHP602": "silent 64-bit dtype widening in device code",
    "SHP603": "literal dimension bypasses the power-of-two bucket ladder",
    "SHP604": "sharded axis dimension is not shard-divisible after pow2 padding",
}

# axes: tuple of str (named axis) | int (literal dim) | None (unknown dim);
# axes itself None = unknown rank. dtype: canonical string or None.
Axes = Optional[Tuple[object, ...]]


@dataclass(frozen=True)
class AV:
    axes: Axes = None
    dtype: Optional[str] = None


UNKNOWN = AV()
SCALAR = AV(axes=())

_CONSTRUCTORS = {"zeros", "ones", "empty", "full", "arange"}
_LIKE_CONSTRUCTORS = {"zeros_like", "ones_like", "full_like", "empty_like"}
_ELEMENTWISE = {
    "where", "maximum", "minimum", "clip", "add", "subtract", "multiply",
    "divide", "floor_divide", "mod", "power", "logical_and", "logical_or",
    "logical_xor", "logical_not", "abs", "sign", "floor", "ceil", "round",
    "exp", "log", "sqrt", "isinf", "isnan", "equal", "not_equal", "greater",
    "greater_equal", "less", "less_equal",
}
_SHAPE_PRESERVING = {"cumsum", "cumprod", "flip", "sort", "negative", "copy"}
_REDUCTIONS = {
    "sum", "min", "max", "mean", "prod", "any", "all", "argmin", "argmax",
    "count_nonzero", "nanmin", "nanmax",
}
_DTYPE_64 = {"float64", "int64", "uint64", "complex128"}
_DTYPE_NAMES = {
    "float16", "bfloat16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "uint32", "uint64", "bool_", "complex64", "complex128",
}
_WIDTH_PAIRS = {("float32", "float64"), ("int32", "int64")}


def _is_pow2(v: int) -> bool:
    return v >= 0 and (v & (v - 1)) == 0  # 0 and 1 count as bucketed


class _Env:
    def __init__(self, parent: Optional["_Env"] = None):
        self.parent = parent
        self.vals: Dict[str, AV] = {}

    def get(self, name: str) -> AV:
        env: Optional[_Env] = self
        while env is not None:
            if name in env.vals:
                return env.vals[name]
            env = env.parent
        return UNKNOWN

    def set(self, name: str, av: AV) -> None:
        self.vals[name] = av


def _join_axes(a: Axes, b: Axes) -> Tuple[Axes, Optional[Tuple[object, object]]]:
    """Right-aligned broadcast join. Returns (joined, conflict) where
    conflict is the first (dim_a, dim_b) pair of *known, unequal, non-1*
    dims, or None. An unknown-rank operand poisons the join to unknown:
    keeping the known side would manufacture facts about values the
    interpreter lost track of (the false-positive mode this pass must
    never have)."""
    if a is None or b is None:
        return None, None
    out: List[object] = []
    conflict = None
    la, lb = len(a), len(b)
    for i in range(1, max(la, lb) + 1):
        da = a[-i] if i <= la else 1
        db = b[-i] if i <= lb else 1
        if da == 1:
            out.append(db)
        elif db == 1:
            out.append(da)
        elif da is None:
            out.append(db)
        elif db is None:
            out.append(da)
        elif da == db:
            out.append(da)
        else:
            both_named = isinstance(da, str) and isinstance(db, str)
            both_lits = isinstance(da, int) and isinstance(db, int)
            if (both_named or both_lits) and conflict is None:
                conflict = (da, db)
            out.append(None)
    return tuple(reversed(out)), conflict


def _assigned_names(stmt: ast.AST) -> set:
    """Names the statement may bind, without descending into nested
    scopes (defs/lambdas/classes bind in their own frame). Deliberately
    over-approximate — degrading an extra name to unknown is sound."""
    out = set()
    stack = [stmt]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _matmul_axes(
    a: Axes, b: Axes
) -> Tuple[Axes, Optional[Tuple[object, object]]]:
    """``a @ b`` contraction: a's last axis against b's second-to-last
    (or only, for 1-D b). Returns (result_axes, conflict) — conflict is
    the contracted pair when both dims are known and unequal. Batched
    (rank>2 both sides) results degrade to unknown rather than modelling
    the batch-dim broadcast."""
    if a is None or b is None or len(a) == 0 or len(b) == 0:
        return None, None
    ca = a[-1]
    cb = b[-2] if len(b) >= 2 else b[-1]
    conflict = None
    both_named = isinstance(ca, str) and isinstance(cb, str)
    both_lits = isinstance(ca, int) and isinstance(cb, int)
    if (both_named or both_lits) and ca != cb:
        conflict = (ca, cb)
    if len(b) == 1:
        return a[:-1], conflict
    if len(a) == 1:
        return b[:-2] + (b[-1],), conflict
    if len(a) == 2 and len(b) == 2:
        return (a[0], b[-1]), conflict
    return None, conflict


def _join_dtype(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a is None or b is None or a == b:
        return a if a == b else None
    if (a, b) in _WIDTH_PAIRS or (b, a) in _WIDTH_PAIRS:
        return a if a in _DTYPE_64 else b
    return None


class _FunctionChecker(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        aliases: Dict[str, str],
        findings: List[Finding],
        env: _Env,
    ):
        self.path = path
        self.aliases = aliases
        self.findings = findings
        self.env = env
        self._flagged: set = set()
        # names bound to PartitionSpec / NamedSharding values in this
        # frame: name -> partition tuple (mesh-axis str | None per dim)
        self._specs: Dict[str, Tuple[object, ...]] = {}

    # -- reporting --------------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if (line, rule) in self._flagged:
            return
        self._flagged.add((line, rule))
        self.findings.append(
            Finding(rule, Severity.ERROR, self.path, line, message)
        )

    # -- name resolution --------------------------------------------------

    def _origin(self, cname: str) -> str:
        return cname.partition(".")[0]

    def _is_jnp(self, cname: str) -> bool:
        return cname.startswith("jax.numpy.") or cname.startswith("jax.")

    def _dtype_of_node(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and node.attr in _DTYPE_NAMES:
            return node.attr.rstrip("_") if node.attr != "bool_" else "bool"
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value in _DTYPE_NAMES:
                return node.value
        if isinstance(node, ast.Name) and node.id == "bool":
            return "bool"
        return None

    def _axis_of_dim(self, node: ast.AST) -> object:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        return None

    # -- abstract evaluation ----------------------------------------------

    def avof(self, node: ast.AST) -> AV:
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float, bool)):
                return SCALAR
            return UNKNOWN
        if isinstance(node, ast.BinOp):
            a, b = self.avof(node.left), self.avof(node.right)
            if isinstance(node.op, ast.MatMult):
                axes, _ = _matmul_axes(a.axes, b.axes)
            else:
                axes, _ = _join_axes(a.axes, b.axes)
            return AV(axes, _join_dtype(a.dtype, b.dtype))
        if isinstance(node, ast.UnaryOp):
            return self.avof(node.operand)
        if isinstance(node, ast.Compare):
            avs = [self.avof(node.left)] + [self.avof(c) for c in node.comparators]
            axes = avs[0].axes
            for av in avs[1:]:
                axes, _ = _join_axes(axes, av.axes)
            return AV(axes, "bool")
        if isinstance(node, ast.BoolOp):
            return UNKNOWN
        if isinstance(node, ast.IfExp):
            a, b = self.avof(node.body), self.avof(node.orelse)
            return a if a == b else UNKNOWN
        if isinstance(node, ast.Call):
            return self._call_av(node)
        if isinstance(node, ast.Attribute):
            base = self.avof(node.value)
            if node.attr == "T" and base.axes is not None:
                return AV(tuple(reversed(base.axes)), base.dtype)
            if node.attr in ("shape", "ndim", "size", "dtype"):
                return SCALAR
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            return self._subscript_av(node)
        return UNKNOWN

    def _spec_of(self, node: ast.AST) -> Optional[Tuple[object, ...]]:
        """The partition tuple ``node`` denotes, or None when it is not a
        statically-known sharding. Entries: a mesh-axis name (str) for a
        partitioned dim, None for a replicated one. Starred/dynamic
        constructor args poison to None — the pass never guesses."""
        if isinstance(node, ast.Name):
            return self._specs.get(node.id)
        if not isinstance(node, ast.Call):
            return None
        cname = call_name(node, self.aliases)
        if not cname.startswith("jax."):
            return None
        tail = cname.rpartition(".")[2]
        if tail == "NamedSharding" and len(node.args) >= 2:
            return self._spec_of(node.args[1])
        if tail == "PartitionSpec":
            out: List[object] = []
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    out.append(a.value)
                elif isinstance(a, ast.Constant) and a.value is None:
                    out.append(None)
                elif isinstance(a, ast.Tuple) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in a.elts
                ):
                    # multi-axis entry ('data','model'): still partitioned
                    out.append(
                        "+".join(e.value for e in a.elts)  # type: ignore
                    )
                else:
                    return None
            return tuple(out)
        return None

    def _check_shard_divisible(self, node: ast.Call, tail: str) -> None:
        """SHP604 at the array-meets-sharding sinks: every partitioned
        spec entry must sit over a pow2 (or unknown/named) array dim."""
        spec = self._spec_of(node.args[1])
        arr = self.avof(node.args[0])
        if not spec or arr.axes is None:
            return
        for i, entry in enumerate(spec):
            if entry is None or i >= len(arr.axes):
                continue
            dim = arr.axes[i]
            if (
                isinstance(dim, int)
                and not isinstance(dim, bool)
                and dim > 1
                and not _is_pow2(dim)
            ):
                self._flag(
                    "SHP604", node,
                    f"jax.{tail} partitions axis {i} (dim {dim}) over mesh"
                    f" axis '{entry}', but {dim} is not a power of two —"
                    " the buffer skipped the pow2 shard padding"
                    " (parallel.mesh.pad_args_for_mesh) and cannot divide"
                    " the mesh axis",
                )

    def _shape_axes(self, node: ast.AST) -> Axes:
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self._axis_of_dim(e) for e in node.elts)
        dim = self._axis_of_dim(node)
        return (dim,) if dim is not None else None

    def _call_av(self, node: ast.Call) -> AV:
        cname = call_name(node, self.aliases)
        tail = cname.rpartition(".")[2]
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if not self._is_jnp(cname):
            if isinstance(node.func, ast.Attribute):
                return self._method_av(node)
            return UNKNOWN
        if tail in _CONSTRUCTORS:
            dtype_node = kw.get("dtype")
            if dtype_node is None:
                slot = 2 if tail == "full" else 1
                if tail != "arange" and len(node.args) > slot:
                    dtype_node = node.args[slot]
            dtype = self._dtype_of_node(dtype_node) if dtype_node is not None else None
            if tail == "arange":
                if len(node.args) == 1:
                    dim = self._axis_of_dim(node.args[0])
                    return AV((dim,), dtype or "int32")
                return AV(None, dtype or "int32")
            if node.args:
                return AV(self._shape_axes(node.args[0]), dtype)
            return AV(None, dtype)
        if tail in _LIKE_CONSTRUCTORS and node.args:
            base = self.avof(node.args[0])
            dtype = (
                self._dtype_of_node(kw["dtype"]) if "dtype" in kw else base.dtype
            )
            return AV(base.axes, dtype)
        if tail in ("asarray", "array"):
            return AV(None, self._dtype_of_node(kw.get("dtype")) if "dtype" in kw
                      else (self._dtype_of_node(node.args[1])
                            if len(node.args) > 1 else None))
        if tail == "one_hot":
            base = self.avof(node.args[0]) if node.args else UNKNOWN
            dim = self._axis_of_dim(node.args[1]) if len(node.args) > 1 else None
            dtype = self._dtype_of_node(kw.get("dtype")) if "dtype" in kw else None
            if base.axes is not None:
                return AV(base.axes + (dim,), dtype)
            return AV(None, dtype)
        if tail in _DTYPE_NAMES:  # jnp.int32(x)-style cast
            base = self.avof(node.args[0]) if node.args else SCALAR
            return AV(base.axes, tail.rstrip("_") if tail != "bool_" else "bool")
        if tail in _ELEMENTWISE:
            axes: Axes = ()
            dtype: Optional[str] = None
            first = True
            for arg in node.args:
                av = self.avof(arg)
                axes, _ = _join_axes(axes, av.axes)
                dtype = av.dtype if first else _join_dtype(dtype, av.dtype)
                first = False
            if tail in ("isinf", "isnan", "logical_and", "logical_or",
                        "logical_not", "logical_xor"):
                dtype = "bool"
            return AV(axes, dtype)
        if tail in _SHAPE_PRESERVING and node.args:
            return self.avof(node.args[0])
        if tail in _REDUCTIONS and node.args:
            base = self.avof(node.args[0])
            dtype = (
                "int32" if tail in ("argmin", "argmax", "count_nonzero")
                else ("bool" if tail in ("any", "all") else base.dtype)
            )
            if "keepdims" in kw:
                return AV(None, dtype)
            axis_node = kw.get("axis")
            if axis_node is None and len(node.args) > 1:
                axis_node = node.args[1]
            if axis_node is None:
                return AV((), dtype)
            if base.axes is None:
                return AV(None, dtype)
            drops: List[int] = []
            cands = (
                axis_node.elts
                if isinstance(axis_node, (ast.Tuple, ast.List))
                else [axis_node]
            )
            for c in cands:
                v = None
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    v = c.value
                elif (
                    isinstance(c, ast.UnaryOp)
                    and isinstance(c.op, ast.USub)
                    and isinstance(c.operand, ast.Constant)
                ):
                    v = -c.operand.value
                if v is None:
                    return AV(None, dtype)
                drops.append(v % len(base.axes) if base.axes else v)
            kept = tuple(
                d for i, d in enumerate(base.axes) if i not in set(drops)
            )
            return AV(kept, dtype)
        if tail == "einsum":
            return self._einsum_av(node)
        # segment-op vocabulary (the sparse feasibility path,
        # ops/feasibility.py:*_sparse): without these the abstract values
        # degrade to unknown and the pass stops checking downstream joins
        if tail == "segment_sum":
            data = self.avof(node.args[0]) if node.args else UNKNOWN
            num = kw.get("num_segments")
            if num is None and len(node.args) > 2:
                num = node.args[2]
            seg_dim = self._axis_of_dim(num) if num is not None else None
            if data.axes is not None and len(data.axes) >= 1:
                return AV((seg_dim,) + tuple(data.axes[1:]), data.dtype)
            return AV(None, data.dtype)
        if tail == "take_along_axis" and len(node.args) >= 2:
            arr = self.avof(node.args[0])
            idx = self.avof(node.args[1])
            axis = kw.get("axis")
            if axis is None and len(node.args) > 2:
                axis = node.args[2]
            ax = None
            if isinstance(axis, ast.Constant) and isinstance(axis.value, int):
                ax = axis.value
            elif (
                isinstance(axis, ast.UnaryOp)
                and isinstance(axis.op, ast.USub)
                and isinstance(axis.operand, ast.Constant)
            ):
                ax = -axis.operand.value
            if (
                arr.axes is not None
                and idx.axes is not None
                and len(arr.axes) == len(idx.axes)
                and ax is not None
            ):
                out = list(arr.axes)
                out[ax % len(out)] = idx.axes[ax % len(out)]
                return AV(tuple(out), arr.dtype)
            return AV(None, arr.dtype)
        if tail == "take" and len(node.args) >= 2:
            arr = self.avof(node.args[0])
            idx = self.avof(node.args[1])
            axis = kw.get("axis")
            if (
                arr.axes is not None
                and idx.axes is not None
                and isinstance(axis, ast.Constant)
                and isinstance(axis.value, int)
            ):
                ax = axis.value % len(arr.axes)
                return AV(
                    arr.axes[:ax] + idx.axes + arr.axes[ax + 1:], arr.dtype
                )
            return AV(None, arr.dtype)
        if tail == "broadcast_to" and len(node.args) >= 2:
            base = self.avof(node.args[0])
            return AV(self._shape_axes(node.args[1]), base.dtype)
        if tail in ("device_put", "with_sharding_constraint") and node.args:
            # sharding transfers preserve the abstract value; divisibility
            # is checked at the sink (visit_Call -> SHP604)
            return self.avof(node.args[0])
        return UNKNOWN

    def _method_av(self, node: ast.Call) -> AV:
        attr = node.func.attr  # type: ignore[union-attr]
        base = self.avof(node.func.value)  # type: ignore[union-attr]
        if attr == "astype" and node.args:
            dtype = self._dtype_of_node(node.args[0])
            return AV(base.axes, dtype or None)
        if attr == "reshape":
            args = node.args
            if len(args) == 1 and isinstance(args[0], (ast.Tuple, ast.List)):
                return AV(self._shape_axes(args[0]), base.dtype)
            dims = tuple(self._axis_of_dim(a) for a in args)
            return AV(dims if dims else None, base.dtype)
        if attr == "sum" and base.axes is not None:
            return AV((), base.dtype)
        return UNKNOWN

    def _einsum_av(self, node: ast.Call) -> AV:
        if not node.args or not isinstance(node.args[0], ast.Constant):
            return UNKNOWN
        spec = node.args[0].value
        if not isinstance(spec, str) or "..." in spec or "->" not in spec:
            return UNKNOWN
        ins, _, out = spec.partition("->")
        in_specs = [s.strip() for s in ins.split(",")]
        operands = node.args[1:]
        letter_axis: Dict[str, str] = {}
        for op_spec, operand in zip(in_specs, operands):
            av = self.avof(operand)
            if av.axes is None or len(av.axes) != len(op_spec):
                continue
            for letter, dim in zip(op_spec, av.axes):
                if not isinstance(dim, str):
                    continue
                prior = letter_axis.get(letter)
                if prior is not None and prior != dim:
                    self._flag(
                        "SHP601", node,
                        f"einsum {spec!r} binds letter '{letter}' to axis "
                        f"'{prior}' and axis '{dim}' — operand axes are "
                        "transposed or the spec is stale",
                    )
                else:
                    letter_axis[letter] = dim
        out_axes = tuple(letter_axis.get(l) for l in out.strip())
        return AV(out_axes if out.strip() else (), None)

    def _subscript_av(self, node: ast.Subscript) -> AV:
        base = self.avof(node.value)
        if base.axes is None:
            return UNKNOWN
        sl = node.slice
        elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        out: List[object] = []
        pos = 0
        for e in elts:
            if isinstance(e, ast.Slice):
                if pos < len(base.axes):
                    out.append(base.axes[pos])
                pos += 1
            elif isinstance(e, ast.Constant) and e.value is None:
                out.append(1)
            elif isinstance(e, ast.Constant) and isinstance(e.value, int):
                pos += 1  # integer index drops the dim
            elif (
                isinstance(e, ast.UnaryOp)
                and isinstance(e.op, ast.USub)
                and isinstance(e.operand, ast.Constant)
            ):
                pos += 1
            elif isinstance(e, ast.Name):
                av = self.env.get(e.id)
                if av.axes == () or av.axes is None:
                    pos += 1  # scalar (or unknown treated as scalar index)
                elif len(elts) == 1 and len(base.axes) == 1:
                    # 1-D gather: result takes the index's axes
                    return AV(av.axes, base.dtype)
                else:
                    return UNKNOWN
            else:
                return UNKNOWN
        out.extend(base.axes[pos:])
        return AV(tuple(out), base.dtype)

    # -- checks -----------------------------------------------------------

    def _check_literal_dims(self, node: ast.AST, where: str) -> None:
        elts = (
            node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
        )
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                if not isinstance(e.value, bool) and e.value > 1 and not _is_pow2(e.value):
                    self._flag(
                        "SHP603", node,
                        f"literal dimension {e.value} in {where} bypasses "
                        "the power-of-two bucket ladder — every distinct "
                        "size recompiles; route it through the bucketing "
                        "helpers or pad to a power of two",
                    )

    def _check_dtype_64(
        self,
        dtype_node: Optional[ast.AST],
        ctx: str,
        jax_origin_only: bool = False,
    ) -> None:
        """``jax_origin_only`` gates contexts that are not already known to
        be device code (``.astype`` on an arbitrary object): only a dtype
        spelled ``jnp.float64`` flags there — host ``np.float64`` index
        math in the encoder is intentional and out of scope."""
        if dtype_node is None:
            return
        name = None
        if isinstance(dtype_node, ast.Attribute) and dtype_node.attr in _DTYPE_64:
            from .astutil import dotted_name

            dn = dotted_name(dtype_node) or ""
            origin = self.aliases.get(dn.partition(".")[0], dn.partition(".")[0])
            if not jax_origin_only or origin.startswith("jax"):
                name = dtype_node.attr
        elif (
            not jax_origin_only
            and isinstance(dtype_node, ast.Constant)
            and isinstance(dtype_node.value, str)
            and dtype_node.value in _DTYPE_64
        ):
            name = dtype_node.value
        if name is not None:
            self._flag(
                "SHP602", dtype_node,
                f"explicit {name} in {ctx}: 64-bit types silently "
                "downcast (x64 off) or are unsupported on TPU — use the "
                "32-bit twin",
            )

    # -- statement visitors ----------------------------------------------

    def _bind(self, target: ast.AST, av: AV) -> None:
        if isinstance(target, ast.Name):
            self.env.set(target.id, av)
            # every rebind clears a tracked PartitionSpec (visit_Assign
            # re-records it when the new value IS one): a tuple-unpacked
            # reassignment must poison the spec, never keep guessing
            self._specs.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, UNKNOWN)

    # -- path sensitivity --------------------------------------------------
    # The walker is straight-line: a binding made inside only one branch of
    # a conditional (or a loop body that may run zero times) is not a fact
    # on the fall-through path. Each branch is checked against the
    # pre-branch state, and every name the construct assigns degrades to
    # unknown at its exit — the join that can never false-positive.

    def _degrade_assigned(self, *bodies) -> None:
        for body in bodies:
            for stmt in body:
                for name in _assigned_names(stmt):
                    self.env.set(name, UNKNOWN)
                    self._specs.pop(name, None)

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        before = dict(self.env.vals)
        for stmt in node.body:
            self.visit(stmt)
        self.env.vals = dict(before)
        for stmt in node.orelse:
            self.visit(stmt)
        self.env.vals = before
        self._degrade_assigned(node.body, node.orelse)

    def visit_For(self, node: ast.For) -> None:
        self.generic_visit(node)
        self._degrade_assigned([node])

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        self.generic_visit(node)
        self._degrade_assigned([node])

    def visit_Try(self, node: ast.Try) -> None:
        self.generic_visit(node)
        self._degrade_assigned(
            node.body, node.orelse, node.finalbody,
            *[h.body for h in node.handlers],
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        av = self.avof(node.value)
        spec = self._spec_of(node.value)
        for t in node.targets:
            self._bind(t, av)
            if isinstance(t, ast.Name):
                if spec is not None:
                    self._specs[t.id] = spec
                else:
                    self._specs.pop(t.id, None)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None:
            self._bind(node.target, self.avof(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if isinstance(node.target, ast.Name):
            a = self.env.get(node.target.id)
            b = self.avof(node.value)
            axes, conflict = _join_axes(a.axes, b.axes)
            if conflict is not None:
                self._flag(
                    "SHP601", node,
                    f"broadcast join aligns axis '{conflict[0]}' with axis "
                    f"'{conflict[1]}' — operands look transposed",
                )
            self.env.set(node.target.id, AV(axes, _join_dtype(a.dtype, b.dtype)))

    def visit_BinOp(self, node: ast.BinOp) -> None:
        self.generic_visit(node)
        a, b = self.avof(node.left), self.avof(node.right)
        if isinstance(node.op, ast.MatMult):
            # `@` contracts, it does not broadcast: check the contracted
            # pair, not a right-aligned join (which would flag every
            # legitimate [n,k] @ [k,m])
            _, conflict = _matmul_axes(a.axes, b.axes)
            if conflict is not None:
                self._flag(
                    "SHP601", node,
                    f"matmul contracts axis '{conflict[0]}' against axis "
                    f"'{conflict[1]}' — operands look transposed",
                )
        else:
            _, conflict = _join_axes(a.axes, b.axes)
            if conflict is not None:
                self._flag(
                    "SHP601", node,
                    f"broadcast join aligns axis '{conflict[0]}' with axis "
                    f"'{conflict[1]}' — operands look transposed",
                )
        if a.dtype and b.dtype and (
            (a.dtype, b.dtype) in _WIDTH_PAIRS
            or (b.dtype, a.dtype) in _WIDTH_PAIRS
        ):
            self._flag(
                "SHP602", node,
                f"join widens {a.dtype}/{b.dtype} to 64-bit — a TPU "
                "promotion hazard; cast the wide operand down first",
            )

    def visit_Compare(self, node: ast.Compare) -> None:
        self.generic_visit(node)
        prev = self.avof(node.left)
        for comp in node.comparators:
            cur = self.avof(comp)
            _, conflict = _join_axes(prev.axes, cur.axes)
            if conflict is not None:
                self._flag(
                    "SHP601", node,
                    f"broadcast join aligns axis '{conflict[0]}' with axis "
                    f"'{conflict[1]}' — operands look transposed",
                )
            prev = cur

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        cname = call_name(node, self.aliases)
        tail = cname.rpartition(".")[2]
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if self._is_jnp(cname):
            if tail in _CONSTRUCTORS and tail != "arange" and node.args:
                self._check_literal_dims(node.args[0], f"jnp.{tail} shape")
                dtype_node = kw.get("dtype")
                if dtype_node is None:
                    slot = 2 if tail == "full" else 1
                    if len(node.args) > slot:
                        dtype_node = node.args[slot]
                self._check_dtype_64(dtype_node, f"jnp.{tail}")
            elif tail in ("asarray", "array"):
                # dtype is positional arg 1 here — same slot _call_av reads
                dtype_node = kw.get("dtype")
                if dtype_node is None and len(node.args) > 1:
                    dtype_node = node.args[1]
                self._check_dtype_64(dtype_node, f"jnp.{tail}")
            elif tail in ("full_like", "zeros_like", "ones_like",
                          "one_hot", "arange"):
                self._check_dtype_64(kw.get("dtype"), f"jnp.{tail}")
            elif tail in _ELEMENTWISE:
                avs = [self.avof(a) for a in node.args]
                axes: Axes = ()
                for av in avs:
                    axes, conflict = _join_axes(axes, av.axes)
                    if conflict is not None:
                        self._flag(
                            "SHP601", node,
                            f"jnp.{tail} joins axis '{conflict[0]}' with "
                            f"axis '{conflict[1]}' — operands look "
                            "transposed",
                        )
            elif tail == "einsum":
                self._einsum_av(node)  # flags letter conflicts
            elif tail in (
                "device_put", "with_sharding_constraint"
            ) and len(node.args) >= 2:
                self._check_shard_divisible(node, tail)
            elif tail == "segment_sum" and len(node.args) >= 2:
                data = self.avof(node.args[0])
                ids = self.avof(node.args[1])
                if (
                    data.axes is not None
                    and ids.axes is not None
                    and len(ids.axes) == 1
                ):
                    da, ia = data.axes[0], ids.axes[0]
                    both_named = isinstance(da, str) and isinstance(ia, str)
                    both_lits = isinstance(da, int) and isinstance(ia, int)
                    if (both_named or both_lits) and da != ia:
                        self._flag(
                            "SHP601", node,
                            f"segment_sum ids ride axis '{ia}' but the "
                            f"data's segment axis is '{da}' — the "
                            "compacted index and its payload are "
                            "misaligned",
                        )
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "astype" and node.args:
                self._check_dtype_64(
                    node.args[0], ".astype", jax_origin_only=True
                )
            elif node.func.attr == "reshape":
                # only values the interpreter tracked (jnp origins) are
                # device code — host numpy reshape index math is out of
                # scope, same rationale as .astype's jax_origin_only
                recv = self.avof(node.func.value)
                if recv.axes is not None or recv.dtype is not None:
                    for a in node.args:
                        self._check_literal_dims(a, ".reshape")

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        check_function(
            self.path, self.aliases, node, self.findings, parent=self.env
        )

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        env = _Env(parent=self.env)
        for arg in node.args.args + node.args.kwonlyargs:
            env.set(arg.arg, UNKNOWN)
        sub = _FunctionChecker(self.path, self.aliases, self.findings, env)
        sub.visit(node.body)


def check_function(
    path: str,
    aliases: Dict[str, str],
    fn: ast.FunctionDef,
    findings: List[Finding],
    parent: Optional[_Env] = None,
) -> None:
    env = _Env(parent=parent)
    for arg in (
        fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    ):
        env.set(arg.arg, UNKNOWN)
    if fn.args.vararg is not None:
        env.set(fn.args.vararg.arg, UNKNOWN)
    if fn.args.kwarg is not None:
        env.set(fn.args.kwarg.arg, UNKNOWN)
    checker = _FunctionChecker(path, aliases, findings, env)
    for stmt in fn.body:
        checker.visit(stmt)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the axis/dtype pass over files/dirs of Python sources."""
    findings: List[Finding] = []
    sources: Dict[str, SourceFile] = {}
    for path in iter_py_files(paths):
        try:
            src, tree = parse_file(path)
        except (OSError, SyntaxError) as exc:
            findings.append(
                Finding("SHP600", Severity.ERROR, path, 0, f"unparsable: {exc}")
            )
            continue
        sources[path] = src
        aliases = import_aliases(tree)
        # module-level statements run through the same checker (constructor
        # sites like module constants are bindings too)
        env = _Env()
        checker = _FunctionChecker(path, aliases, findings, env)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_function(path, aliases, stmt, findings)
            elif isinstance(stmt, ast.ClassDef):
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        check_function(path, aliases, item, findings)
            else:
                checker.visit(stmt)
    return findings, sources
