"""Pass 7: retry/except hygiene in the fault-handling paths.

The robustness tier (karpenter_tpu/faults/) only works if every seam
either retries through a clock-driven ``Backoff``/``RetryTracker`` or
surfaces its failure where the breaker and the requeue machinery can see
it. Two anti-patterns defeat it structurally, and both are statically
visible:

- **RTY701 — swallowed failure**: an ``except Exception:`` (or bare
  ``except:`` / ``except BaseException:``) whose body is only
  ``pass``/``continue``/``...``. The fault disappears: no event, no
  metric, no backoff, and the chaos soak can never attribute the orphan
  it produces. Typed catches (``except ConflictError: continue``) are the
  designed idiom and are NOT flagged — the type documents exactly which
  transient the level-triggered loop absorbs.
- **RTY702 — unbounded retry loop**: a ``while True`` loop whose
  ``except`` handler keeps looping (``continue``, or a body that just
  falls through) with no visible bound anywhere in the loop — no attempt
  counter, no ``Backoff``/``RetryTracker``/clock call, no
  raise/break/return in the handler. Under a persistent fault such a
  loop spins the reconcile thread forever; ``Backoff.call`` is the
  bounded replacement.

Hosted on the dataflow core's module layer (analysis/core/summaries):
the bound detection reaches through helpers over the module-set call
graph — a loop whose handler calls ``self._pause()`` or a module-level
``_backoff_step()`` that itself touches a Backoff/clock/attempt bound
(directly, or through further helpers) is bounded, where the
first-generation AST matcher only saw the loop's own text and flagged
it (those false positives are why the reach exists; suppressions they
used to require are deleted, not kept). Recursive helper clusters
collapse to "no bound" by SCC — a cycle can't vouch for itself.

The bound detection stays deliberately permissive (any attempt-counter-ish
name comparison, any backoff/clock reference, any escape statement in the
handler counts): the rule exists to catch the *structurally* unbounded
shape, not to lint retry style.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .astutil import dotted_name
from .core.summaries import (
    ModuleInfo,
    SummaryTable,
    build_call_graph,
    load_modules,
    resolve_local,
)
from .findings import Finding, Severity, SourceFile

RULES = {
    "RTY700": "unparsable file (retry pass)",
    "RTY701": "broad exception handler silently swallows the failure",
    "RTY702": "retry loop without a Backoff/attempt/clock bound",
}

_BROAD = {"Exception", "BaseException"}
_SWALLOW_BODY = (ast.Pass, ast.Continue)
_BOUND_NAME_HINTS = ("backoff", "attempt", "retries", "tries", "deadline")
_BOUND_CALL_ATTRS = {"sleep", "delay", "ready", "failure", "call", "retry"}

# summary values for the call-graph helper reach
_NO_BOUND = 0
_HAS_BOUND = 1


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in _BROAD for n in names)


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does nothing but loop/fall through."""
    return all(
        isinstance(stmt, _SWALLOW_BODY)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        for stmt in handler.body
    )


def _ident_chain(node: ast.AST) -> str:
    """Lowercased dotted-ish identifier text of a Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)).lower()


def _own_bound_evidence(node: ast.AST) -> bool:
    """Bound evidence in ``node``'s own text (no helper reach)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            ident = _ident_chain(sub)
            if any(h in ident for h in _BOUND_NAME_HINTS):
                return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr in _BOUND_CALL_ATTRS:
                return True
    return False


def _call_targets(
    node: ast.AST, mod: ModuleInfo, modules: Dict[str, ModuleInfo]
) -> List[Tuple[ModuleInfo, ast.FunctionDef]]:
    """Resolvable helper targets of every call inside ``node``: bare
    names through resolve_local, ``self._helper()`` against every class
    method table in the module (conservative: any method of that name
    counts)."""
    out: List[Tuple[ModuleInfo, ast.FunctionDef]] = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        raw = dotted_name(sub.func)
        if raw is not None and "." not in raw:
            target = resolve_local(mod, raw, modules)
            if target is not None:
                out.append(target)
        elif (
            isinstance(sub.func, ast.Attribute)
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "self"
        ):
            for table in mod.index.methods.values():
                if sub.func.attr in table:
                    out.append((mod, table[sub.func.attr]))
                    break
    return out


def _helper_bound_summary(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    modules: Dict[str, ModuleInfo],
    summaries: SummaryTable,
) -> int:
    """Does the helper carry bound evidence — in its own body, or in any
    helper it reaches over the call graph? Bottom-up through the
    SummaryTable; recursive clusters read _NO_BOUND by SCC collapse (a
    cycle of helpers deferring to each other proves nothing)."""

    def compute() -> int:
        if _own_bound_evidence(fn):
            return _HAS_BOUND
        for t_mod, t_fn in _call_targets(fn, mod, modules):
            if t_fn is fn:
                continue
            if _helper_bound_summary(t_mod, t_fn, modules, summaries):
                return _HAS_BOUND
        return _NO_BOUND

    return summaries.get((mod.path, fn.name), compute)


def _has_bound(
    loop: ast.While,
    mod: Optional[ModuleInfo],
    modules: Dict[str, ModuleInfo],
    summaries: Optional[SummaryTable],
) -> bool:
    """Any structural evidence the loop's retrying is bounded — in the
    loop's own text, or any number of helper hops away on the call
    graph."""
    if _own_bound_evidence(loop):
        return True
    if mod is None or summaries is None:
        return False
    for t_mod, t_fn in _call_targets(loop, mod, modules):
        if _helper_bound_summary(t_mod, t_fn, modules, summaries):
            return True
    return False


def _handler_escapes(handler: ast.ExceptHandler) -> bool:
    """The handler itself can leave the loop (raise/break/return)."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Break, ast.Return)):
            return True
    return False


def _loops_forever(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("RTY700", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    summaries = SummaryTable(default=_NO_BOUND, graph=build_call_graph(modules))
    for path, mod in modules.items():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler):
                if _is_broad(node) and _swallows(node):
                    findings.append(
                        Finding(
                            "RTY701", Severity.ERROR, path, node.lineno,
                            "broad except swallows the failure with no "
                            "event/metric/backoff; catch the specific "
                            "transient type, or record before requeueing",
                        )
                    )
            elif isinstance(node, ast.While) and _loops_forever(node.test):
                retrying = [
                    h
                    for t in ast.walk(node)
                    if isinstance(t, ast.Try)
                    for h in t.handlers
                    if not _handler_escapes(h)
                ]
                if retrying and not _has_bound(node, mod, modules, summaries):
                    findings.append(
                        Finding(
                            "RTY702", Severity.ERROR, path, node.lineno,
                            "while-True retry loop with a non-escaping "
                            "except handler and no visible bound (attempt "
                            "counter, Backoff/RetryTracker, clock); use "
                            "faults.backoff.Backoff.call",
                        )
                    )
    return findings, sources
