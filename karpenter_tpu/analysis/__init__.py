"""Static-analysis tier: AST passes for the hazards the runtime cannot see.

The reference's presubmit leans on ``go vet`` + the race detector; this
package is the Python/JAX analog, purpose-built for this codebase's two
dangerous seams:

- the batched XLA kernels (ops/, solver/), where host Python control flow
  on traced values silently recompiles or miscomputes (tracer.py);
- the threaded store/state layer, where lock-order inversions and
  callbacks invoked under a lock are the deadlock class tests/test_races.py
  can only catch dynamically (locks.py).

Plus two cheaper contract checks: blocking calls in reconcile paths that
must go through the injectable kube/clock.py (blocking.py), and structural
drift between api/schema.py and the checked-in CRD YAML (schema_drift.py).

Run ``python -m karpenter_tpu.analysis`` (or hack/analyze.py); it exits
nonzero on any new finding. Suppress with an inline
``# analysis: ignore[RULE] reason`` on the flagged line (or the line
above), or a baseline entry in hack/analysis_baseline.txt.
"""

from .findings import Finding, Severity, load_baseline, filter_suppressed

__all__ = ["Finding", "Severity", "load_baseline", "filter_suppressed"]
