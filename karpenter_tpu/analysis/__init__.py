"""Static-analysis tier: AST passes for the hazards the runtime cannot see.

The reference's presubmit leans on ``go vet`` + the race detector; this
package is the Python/JAX analog, purpose-built for this codebase's
dangerous seams:

- the batched XLA kernels (ops/, solver/), where host Python control flow
  on traced values silently recompiles or miscomputes (tracer.py), and
  where axis-order/dtype mistakes broadcast instead of erroring
  (shapes.py);
- the three bit-exact kernel twins — pack, pack_classed, and the C++ core
  — whose structural agreement parity.py pins via semantic skeletons and
  ``// parity:`` anchors, so a change landing in only one twin fails
  presubmit instead of a parity suite weeks later;
- the threaded store/state layer, where lock-order inversions and
  callbacks invoked under a lock are the deadlock class tests/test_races.py
  can only catch dynamically (locks.py).

Plus two cheaper contract checks: blocking calls in reconcile paths that
must go through the injectable kube/clock.py (blocking.py), and structural
drift between api/schema.py and the checked-in CRD YAML (schema_drift.py).

Since the dataflow core landed (analysis/core/: intraprocedural CFG +
forward fixpoint + helper summaries, now propagated bottom-up over a
module-set call graph with SCC-collapsed cycles), the flow-shaped
families ride it: tracer.py and retry.py are migrated, and further
families guard the delta-encode roadmap — device.py (DTX9xx: device
values tracked from jnp/device_put/kernel-dispatch origins to host-sync
sinks, with ``jax.device_get`` as the explicitly sanctioned decode
boundary), clock.py (CLK10xx: every timestamp in
controllers/faults/obs/solver must flow from an injected clock or the
documented RealClock seams — the replay-determinism contract,
machine-checked), det.py (DET11xx: values born from unordered sources —
sets, os.environ, unseeded RNG — flagged at order-sensitive sinks on
the determinism surface; the PR 14 PYTHONHASHSEED interning bug, closed
as a class), args_registry.py (ARG12xx: the 56-argument kernel
registry diffed across its six hand-aligned surfaces — encode assembly,
ARG_SPECS, mesh padding, native wrapper, residency delta classes,
scenario batching), guarded.py (GRD13xx: per-class guarded-by inference
over the whole threaded tree with explicitly modeled thread roots —
mixed guarded/lock-free access, guarded state escaping by reference,
locking callbacks published from ``__init__``), and atomicity.py
(ATM14xx: check-then-act split across a lock release, plus the
cross-module lock-order cycles the store-local LCK201 scan cannot
connect — the machine-checked concurrency contract the multi-tenant
solver service ratchets against).

Run ``python -m karpenter_tpu.analysis`` (or hack/analyze.py); it exits
nonzero on any new finding. Suppress with an inline
``# analysis: ignore[RULE] reason`` on the flagged line (or the line
above; ``//`` in C++ sources), or a baseline entry in
hack/analysis_baseline.txt. Documented boundary crossings (the decode
readback, real-wall-time diagnostics) carry
``# analysis: sanctioned[RULE] reason`` instead — counted separately,
never lumped in with suppressions, audited for staleness all the same
(STALE001, ``--prune-baseline``).
"""

from typing import Dict

from .findings import (
    Finding,
    Severity,
    filter_suppressed,
    load_baseline,
    partition_findings,
)


def all_rules() -> Dict[str, str]:
    """Every shipped rule id -> one-line description, aggregated from the
    pass modules. The meta-test in tests/test_analysis.py asserts each has
    a seeded-bad fixture; the SARIF writer uses it for rule metadata."""
    from . import (
        args_registry, atomicity, blocking, clock, det, device, guarded,
        locks, obs, parity, retry, schema_drift, shapes, stale, tracer,
    )

    out: Dict[str, str] = {}
    for mod in (
        tracer, locks, blocking, schema_drift, parity, shapes, retry, obs,
        device, clock, det, args_registry, guarded, atomicity, stale,
    ):
        out.update(getattr(mod, "RULES", {}))
    return out


__all__ = [
    "Finding", "Severity", "load_baseline", "filter_suppressed",
    "partition_findings", "all_rules",
]
