"""Pass: guarded-by inference over the threaded surface (GRD13xx).

The reference leans on Go's race detector; Python has no ``-race``, so
this pass infers the guarded-by relation statically and ratchets it. Per
class that constructs a ``threading.Lock``/``RLock``, it observes every
``self.`` attribute access through a held-lock symbolic walk (the
locks-pass walk, riding the same ``_File``/``_ClassInfo`` harvest):
accesses inside ``with self._lock`` bodies — including through helper
calls, interprocedurally — are *guarded by* that lock; everything else
is lock-free. ``__init__`` is construction time and exempt.

Thread roots are modeled explicitly over the PR-16 call graph:

- ``threading.Thread(target=...)`` targets (the provisioning ticker),
- callables handed to ``Operator._guarded`` (the controller roster —
  each entry is a reconcile loop the operator may thread),
- ``DispatchQueue.submit``/executor ``.submit`` edges (async dispatch),
- gRPC servicer handlers (``grpc.GenericRpcHandler`` subclasses — their
  handler methods run on the server's thread pool).

A lock-owning class's public methods are themselves thread-root
surfaces: the lock IS the class's declaration that entries race, so two
distinct entry methods count as two roots even when no explicit root
reaches them (this is also what lets the lock-deletion mutation pin in
tests/test_analysis.py fire on a standalone copied module).

Rules:
- GRD1300: unparsable file (guarded pass)
- GRD1301: attribute accessed both under its inferred guard and
  lock-free, reachable from ≥2 thread roots, with at least one write —
  the torn-read/lost-update shape
- GRD1302: guarded mutable state escaping by reference (``return
  self._attr`` without a copy wrapper) — the caller mutates or iterates
  it outside the lock
- GRD1303: ``__init__``-published callback that acquires a lock —
  re-entry from the publisher's (unknown) lock context is the ABBA
  window the store layer documents (the PR-1 callback-under-lock rule
  generalized beyond the store)
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .astutil import dotted_name
from .core.summaries import (
    CallGraph,
    ModuleInfo,
    SummaryTable,
    build_call_graph,
    load_modules,
)
from .findings import Finding, Severity, SourceFile
from .locks import _Analyzer, _ClassInfo, _File, _short

RULES = {
    "GRD1300": "unparsable file (guarded pass)",
    "GRD1301": "attribute accessed both under its inferred guard and "
               "lock-free from ≥2 thread roots",
    "GRD1302": "guarded mutable state escapes by reference (no copy)",
    "GRD1303": "__init__-published callback acquires a lock",
}

_MAX_DEPTH = 8

# container-mutating method names: `self._attr.append(x)` is a write
_MUTATORS = frozenset({
    "append", "add", "clear", "pop", "popitem", "update", "setdefault",
    "remove", "extend", "discard", "insert", "popleft", "appendleft",
    "extendleft", "rotate", "sort", "reverse",
})
# wrapping a guarded attr in one of these copies it out — not an escape
_COPY_WRAPPERS = frozenset({
    "list", "dict", "set", "tuple", "sorted", "frozenset", "deepcopy",
    "copy", "len", "sum", "min", "max", "str", "repr", "bool", "iter",
})
# __init__ RHS shapes that make an attribute mutable container state
_MUTABLE_CALLS = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "bytearray",
})
# callee-name fragments that publish a callable to another component
_PUBLISH_HINTS = (
    "watch", "subscribe", "register", "add_handler", "add_listener",
    "on_event", "callback", "observe", "listen", "attach", "hook",
)
# collection names that receiving `x.append(self.m)` counts as publishing
_PUBLISH_COLLECTIONS = (
    "watcher", "handler", "callback", "listener", "observer", "hook",
)

# one attribute access observed by the walk
# (attr, is_write, lock_ident-or-None, line, entry_method)
Access = Tuple[str, bool, Optional[str], int, str]


class _ClassAccess:
    """Accumulated per-class access observations."""

    def __init__(self) -> None:
        self.accesses: List[Access] = []

    def add(self, attr: str, write: bool, lock: Optional[str], line: int,
            entry: str) -> None:
        self.accesses.append((attr, write, lock, line, entry))


class _Walker:
    """Held-lock symbolic walk recording `self.` attribute accesses.

    Mirrors the locks-pass walk (same `with`/contextmanager handling,
    same self-call recursion with a depth/memo guard) but its product is
    the access log, not the acquisition graph."""

    def __init__(self, analyzer: _Analyzer) -> None:
        self.analyzer = analyzer
        self.acc: _ClassAccess = _ClassAccess()
        self._memo: Set[Tuple[str, int, FrozenSet[str]]] = set()

    def walk_entry(self, file: _File, cls: _ClassInfo,
                   fn: ast.FunctionDef) -> None:
        self._walk_fn(file, cls, fn, entry=fn.name, held=(), depth=0)

    def _walk_fn(self, file: _File, cls: _ClassInfo, fn: ast.FunctionDef,
                 entry: str, held: Tuple[str, ...], depth: int) -> None:
        key = (entry, id(fn), frozenset(held))
        if key in self._memo or depth > _MAX_DEPTH:
            return
        self._memo.add(key)
        self._walk_stmts(file, cls, fn.body, entry, held, depth)

    def _walk_stmts(self, file: _File, cls: _ClassInfo,
                    stmts: Sequence[ast.stmt], entry: str,
                    held: Tuple[str, ...], depth: int) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                new_held = held
                for item in stmt.items:
                    ctx = item.context_expr
                    info = self.analyzer.expr_lock(ctx, file, cls)
                    if info is not None:
                        new_held = new_held + (info.ident,)
                        continue
                    if isinstance(ctx, ast.Call):
                        target = self.analyzer._resolve_self_call(
                            ctx, file, cls
                        )
                        if target is not None:
                            t_cls, t_fn, receiver = target
                            for ident in sorted(
                                self.analyzer.cm_held_locks(
                                    t_cls.file, receiver or t_cls, t_fn
                                )
                            ):
                                new_held = new_held + (ident,)
                        self._scan_expr(file, cls, ctx, entry, held, depth)
                self._walk_stmts(file, cls, stmt.body, entry, new_held,
                                 depth)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run in unknown lock context
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    self._scan_store(file, cls, target, entry, held)
                self._scan_expr(file, cls, stmt.value, entry, held, depth)
                continue
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                attr = self._self_attr(stmt.target)
                if attr is not None:
                    # AugAssign reads AND writes the slot
                    if isinstance(stmt, ast.AugAssign):
                        self._record(cls, attr, False, held, stmt.lineno,
                                     entry)
                    self._record(cls, attr, True, held, stmt.lineno, entry)
                else:
                    self._scan_store(file, cls, stmt.target, entry, held)
                if stmt.value is not None:
                    self._scan_expr(file, cls, stmt.value, entry, held,
                                    depth)
                continue
            if hasattr(stmt, "body"):
                for expr in (getattr(stmt, "test", None),
                             getattr(stmt, "iter", None)):
                    if expr is not None:
                        self._scan_expr(file, cls, expr, entry, held, depth)
                for attr_name in ("body", "orelse", "finalbody"):
                    children = getattr(stmt, attr_name, None)
                    if children:
                        self._walk_stmts(file, cls, children, entry, held,
                                         depth)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._walk_stmts(file, cls, handler.body, entry, held,
                                     depth)
                continue
            for expr in ast.iter_child_nodes(stmt):
                if isinstance(expr, ast.expr):
                    self._scan_expr(file, cls, expr, entry, held, depth)

    # -- expression scanning ------------------------------------------------

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        """attr name when node is a bare ``self.attr`` reference."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _store_attr(self, node: ast.AST) -> Optional[str]:
        """attr written by an assignment target: ``self.a``,
        ``self.a[k]``, or ``self.a.b`` (writing through a sub-object
        mutates the attr's referent)."""
        attr = self._self_attr(node)
        if attr is not None:
            return attr
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            return self._self_attr(node.value)
        return None

    def _scan_store(self, file: _File, cls: _ClassInfo, target: ast.AST,
                    entry: str, held: Tuple[str, ...]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._scan_store(file, cls, elt, entry, held)
            return
        attr = self._store_attr(target)
        if attr is not None:
            self._record(cls, attr, True, held, target.lineno, entry)
            return
        # non-self target: its index/value expressions are reads
        for sub in ast.iter_child_nodes(target):
            if isinstance(sub, ast.expr):
                self._scan_expr(file, cls, sub, entry, held, 0)

    def _scan_expr(self, file: _File, cls: _ClassInfo, node: ast.AST,
                   entry: str, held: Tuple[str, ...], depth: int) -> None:
        if isinstance(node, ast.Lambda):
            return  # runs later, in unknown lock context
        if isinstance(node, ast.Call):
            self._scan_call(file, cls, node, entry, held, depth)
            return
        attr = self._self_attr(node)
        if attr is not None:
            self._record(cls, attr, False, held, node.lineno, entry)
            return
        for sub in ast.iter_child_nodes(node):
            self._scan_expr(file, cls, sub, entry, held, depth)

    def _scan_call(self, file: _File, cls: _ClassInfo, node: ast.Call,
                   entry: str, held: Tuple[str, ...], depth: int) -> None:
        func = node.func
        handled_receiver = False
        if isinstance(func, ast.Attribute):
            recv_attr = self._self_attr(func.value)
            if recv_attr is not None:
                # self.attr.m(...): mutator call = write, else read
                self._record(cls, recv_attr, func.attr in _MUTATORS, held,
                             node.lineno, entry)
                handled_receiver = True
            elif self._self_attr(func) is not None:
                # self.helper(...): recurse if resolvable, else it's not
                # an attribute access at all
                target = self.analyzer._resolve_self_call(node, file, cls)
                if target is not None:
                    t_cls, t_fn, receiver = target
                    if (receiver or t_cls) is cls:
                        self._walk_fn(t_cls.file, cls, t_fn, entry, held,
                                      depth + 1)
                handled_receiver = True
        if not handled_receiver and isinstance(func, ast.expr):
            self._scan_expr(file, cls, func, entry, held, depth)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            self._scan_expr(file, cls, arg, entry, held, depth)

    def _record(self, cls: _ClassInfo, attr: str, write: bool,
                held: Tuple[str, ...], line: int, entry: str) -> None:
        if attr in cls.locks or attr in ("lock", "_lock"):
            return  # the guards themselves are not guarded state
        lock = held[-1] if held else None
        self.acc.add(attr, write, lock, line, entry)


# -- thread roots -----------------------------------------------------------


def _thread_roots(modules: Dict[str, ModuleInfo]) -> Dict[Tuple[str, str], str]:
    """(module_path, fn_name) -> root kind, for every explicitly modeled
    thread root in the scanned set."""
    roots: Dict[Tuple[str, str], str] = {}

    def _mark(path: str, name: Optional[str], kind: str) -> None:
        if name:
            roots.setdefault((path, name.rpartition(".")[2]), kind)

    for path, mod in modules.items():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                bases = {dotted_name(b) or "" for b in node.bases}
                if any(
                    b.rpartition(".")[2] in ("GenericRpcHandler", "Servicer")
                    or b.endswith("Servicer")
                    for b in bases
                ):
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef) and \
                                item.name != "__init__":
                            _mark(path, item.name, "grpc-handler")
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            tail = name.rpartition(".")[2]
            if tail == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        _mark(path, dotted_name(kw.value), "thread-target")
            elif tail == "_guarded" and name.startswith("self."):
                # Operator._guarded(name, fn): the roster's reconcile fns
                if len(node.args) >= 2:
                    _mark(path, dotted_name(node.args[1]),
                          "controller-loop")
            elif tail == "submit":
                # DispatchQueue.submit(label, fn) / executor.submit(fn)
                for arg in node.args:
                    target = dotted_name(arg)
                    if target:
                        _mark(path, target, "submit-edge")
                    elif isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg.body):
                            if isinstance(sub, ast.Call):
                                _mark(path, dotted_name(sub.func),
                                      "submit-edge")
            elif any(h in tail.lower() for h in _PUBLISH_HINTS):
                # watch(self._on_event) and friends: the callback runs on
                # the publisher's (informer/server) thread
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    target = dotted_name(arg)
                    if target and target.startswith("self."):
                        _mark(path, target, "watch-callback")
    return roots


def _root_reach(
    roots: Dict[Tuple[str, str], str], graph: CallGraph
) -> Dict[Tuple[str, str], Set[str]]:
    """key -> set of root kinds reaching it (forward BFS per root)."""
    reach: Dict[Tuple[str, str], Set[str]] = {}
    for root, kind in roots.items():
        if root not in graph.edges:
            reach.setdefault(root, set()).add(kind)
            continue
        seen = {root}
        frontier = [root]
        while frontier:
            node = frontier.pop()
            reach.setdefault(node, set()).add(kind)
            for callee in graph.edges.get(node, ()):
                if callee not in seen and callee in graph.edges:
                    seen.add(callee)
                    frontier.append(callee)
    return reach


# -- per-class rule checks --------------------------------------------------


def _mutable_attrs(cls: _ClassInfo) -> Set[str]:
    """Attributes ``__init__`` binds to a mutable container."""
    out: Set[str] = set()
    init = cls.methods.get("__init__")
    if init is None:
        return out
    for stmt in ast.walk(init):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        target = stmt.targets[0]
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            continue
        value = stmt.value
        mutable = isinstance(
            value, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp,
                    ast.SetComp)
        )
        if not mutable and isinstance(value, ast.Call):
            name = (dotted_name(value.func) or "").rpartition(".")[2]
            mutable = name in _MUTABLE_CALLS
        if mutable:
            out.add(target.attr)
    return out


def _is_entry(file: _File, mname: str,
              reach: Dict[Tuple[str, str], Set[str]]) -> bool:
    """Methods walked as their own thread entry: the public/dunder
    surface plus anything an explicit thread root reaches by name.
    Private helpers are analyzed only through recursion from entries —
    a `_stage_locked`-style helper is, by convention and by callers,
    always entered with the lock already held."""
    if mname == "__init__":
        return False  # construction happens-before publication
    if not mname.startswith("_"):
        return True
    if mname.startswith("__") and mname.endswith("__"):
        return True  # dunder: external protocol surface (len/iter/enter)
    return (file.path, mname) in reach


def _check_class(
    file: _File,
    cls: _ClassInfo,
    analyzer: _Analyzer,
    reach: Dict[Tuple[str, str], Set[str]],
    acquires: "_AcquireSummaries",
    findings: List[Finding],
) -> None:
    walker = _Walker(analyzer)
    for mname, method in cls.methods.items():
        if not _is_entry(file, mname, reach):
            continue
        walker.walk_entry(file, cls, method)
    accesses = walker.acc.accesses

    by_attr: Dict[str, List[Access]] = {}
    for rec in accesses:
        by_attr.setdefault(rec[0], []).append(rec)

    mutable = _mutable_attrs(cls)
    for attr in sorted(by_attr):
        recs = by_attr[attr]
        guarded = [r for r in recs if r[2] is not None]
        unguarded = [r for r in recs if r[2] is None]
        writes = [r for r in recs if r[1]]
        if not (guarded and unguarded and writes):
            continue
        entries = {r[4] for r in recs}
        kinds: Set[str] = set()
        for entry in entries:
            kinds |= reach.get((file.path, entry), set())
        if len(entries) < 2 and len(kinds) < 2:
            continue
        lock = max(
            (r[2] for r in guarded),
            key=lambda ident: sum(1 for r in guarded if r[2] == ident),
        )
        site = min(unguarded, key=lambda r: r[3])
        via = f" (thread roots: {', '.join(sorted(kinds))})" if kinds else ""
        findings.append(
            Finding(
                "GRD1301", Severity.ERROR, file.path, site[3],
                f"self.{attr} is guarded by {_short(lock)} in "
                f"{len(guarded)} site(s) but accessed lock-free in "
                f"{site[4]}(); entries {{{', '.join(sorted(entries))}}} "
                f"race on it{via} — hold the lock or sanction the "
                "single-threaded contract",
            )
        )

    # GRD1302: `return self._attr` of guarded mutable state, bare
    guarded_attrs = {r[0] for r in accesses if r[2] is not None}
    for mname, method in cls.methods.items():
        if mname == "__init__":
            continue
        for node in ast.walk(method):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not method:
                continue
            value = None
            if isinstance(node, ast.Return):
                value = node.value
            elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                value = node.value
            if value is None:
                continue
            attr = None
            if (
                isinstance(value, ast.Attribute)
                and isinstance(value.value, ast.Name)
                and value.value.id == "self"
            ):
                attr = value.attr
            if attr is None or attr not in guarded_attrs or \
                    attr not in mutable:
                continue
            findings.append(
                Finding(
                    "GRD1302", Severity.ERROR, file.path, node.lineno,
                    f"guarded mutable self.{attr} escapes {mname}() by "
                    "reference — the caller iterates/mutates it outside "
                    f"the lock; return a copy (list/dict) instead",
                )
            )

    # GRD1303: __init__ publishes a bound method that acquires a lock
    init = cls.methods.get("__init__")
    if init is None:
        return
    for node in ast.walk(init):
        if not isinstance(node, ast.Call):
            continue
        callee = (dotted_name(node.func) or "").lower()
        published: List[Tuple[str, int]] = []
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"
                and arg.attr in cls.methods
            ):
                published.append((arg.attr, node.lineno))
        if not published:
            continue
        tail = callee.rpartition(".")[2]
        is_publish = any(h in tail for h in _PUBLISH_HINTS) or (
            tail == "append"
            and any(h in callee for h in _PUBLISH_COLLECTIONS)
        )
        if not is_publish:
            continue
        for mname, line in published:
            if acquires.method_acquires(file, cls, mname):
                findings.append(
                    Finding(
                        "GRD1303", Severity.ERROR, file.path, line,
                        f"__init__ publishes self.{mname} as a callback "
                        "and it acquires a lock — re-entry from the "
                        "publisher's lock context is an ABBA window; "
                        "publish after construction or drop the lock "
                        "from the callback",
                    )
                )


class _AcquireSummaries:
    """Bottom-up 'does this function acquire any lock?' summaries over
    the call graph (SummaryTable recursion — SCCs read as 0/unknown)."""

    def __init__(self, modules: Dict[str, ModuleInfo], graph: CallGraph):
        self.modules = modules
        self.graph = graph
        self.table = SummaryTable(default=0, graph=graph)

    def _direct(self, fn: ast.FunctionDef) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = dotted_name(item.context_expr) or ""
                    if name.rpartition(".")[2] in ("lock", "_lock",
                                                   "rlock", "_rlock"):
                        return True
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "acquire":
                return True
        return False

    def key_acquires(self, key: Tuple[str, str]) -> bool:
        def compute() -> int:
            mod = self.modules.get(key[0])
            fn = None
            if mod is not None:
                fn = mod.index.functions.get(key[1])
                if fn is None:
                    for table in mod.index.methods.values():
                        if key[1] in table:
                            fn = table[key[1]]
                            break
            if fn is None:
                return 0
            if self._direct(fn):
                return 1
            for callee in self.graph.edges.get(key, ()):
                if callee != key and self.key_acquires(callee):
                    return 1
            return 0

        return bool(self.table.get(key, compute))

    def method_acquires(self, file: _File, cls: _ClassInfo,
                        mname: str) -> bool:
        return self.key_acquires((file.path, mname))


# -- entry ------------------------------------------------------------------


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the guarded-by pass; returns (findings, sources)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("GRD1300", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    files = [_File(m.path, m.src, m.tree) for m in modules.values()]
    analyzer = _Analyzer(files)
    graph = build_call_graph(modules)
    reach = _root_reach(_thread_roots(modules), graph)
    acquires = _AcquireSummaries(modules, graph)
    for f in files:
        for cls in f.classes.values():
            if not any(c.locks for c in analyzer.mro(cls)):
                continue
            _check_class(f, cls, analyzer, reach, acquires, findings)
    # one finding per (rule, site)
    unique: Dict[Tuple[str, str, int], Finding] = {}
    for finding in findings:
        unique.setdefault((finding.rule, finding.path, finding.line), finding)
    return list(unique.values()), sources
