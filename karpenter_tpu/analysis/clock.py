"""Pass 10: clock discipline (CLK10xx) for the determinism contracts.

The PR-5/PR-6 replay contracts — byte-identical decisions, seeded fault
and trace logs — only hold if every timestamp in the decision path flows
from an injected clock. A single raw ``time.time()`` (or a
``time.monotonic`` reference stashed in a variable and called later)
makes a replayed run diverge from its recording in a way no test can
pin. BLK302 already catches direct wall-clock *calls* in the reconcile
targets; this family covers the determinism surface (controllers/,
faults/, obs/, solver/) with real dataflow:

- CLK1001: a wall-clock read — ``time.time``/``monotonic``/
  ``perf_counter`` (and ``_ns`` variants), ``datetime.now``/``utcnow``/
  ``today`` — reached by a direct call, through a variable the analysis
  tracked the function reference into (``f = time.monotonic`` ...
  ``f()``), or through a local helper that RETURNS a wall-clock callable
  (``f = _pick_clock()`` ... ``f()`` — return-kind summaries propagate
  bottom-up over the module-set call graph, core.summaries, with
  recursive clusters collapsed to plain);
- CLK1002: a wall-clock callable escaping as a value (assigned, passed,
  returned) — a hidden clock source the injection seams can't replace.

Sanctioned sources, and nothing else: the documented RealClock seams —
methods of a class named ``RealClock`` (kube/clock.py) or ``PerfClock``
(obs/trace.py) — plus sites carrying an explicit
``# analysis: sanctioned[CLK1001]`` boundary annotation (real-wall-time
diagnostics like the in-flight-solve age gauge measure wall time BY
DESIGN; the sanction documents that, a suppression would hide it).
Everything else threads the injected clock or ``obs.now()``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import call_name, dotted_name
from .core.cfg import Atom, build_cfg
from .core.dataflow import Env, run_forward, sweep
from .core.lattice import Lattice
from .core.summaries import (
    ModuleInfo,
    SummaryTable,
    build_call_graph,
    load_modules,
    resolve_local,
)
from .findings import Finding, Severity, SourceFile

RULES = {
    "CLK1000": "unparsable file (clock-discipline pass)",
    "CLK1001": "wall-clock read outside an injected clock / RealClock seam",
    "CLK1002": "wall-clock callable escapes as a value (hidden clock source)",
}

PLAIN = 0
CLOCKFN = 1  # a wall-clock callable tracked through bindings

LATTICE = Lattice(top=CLOCKFN, default=PLAIN)

_WALL_CLOCK_FNS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns", "time.clock_gettime",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# the documented RealClock seams: the only classes whose methods may
# read the wall clock directly (kube/clock.py, obs/trace.py)
_SEAM_CLASSES = {"RealClock", "PerfClock"}


def _canonical(name: str, aliases: Dict[str, str]) -> str:
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    return origin + ("." + rest if rest else "")


class _ClockAnalysis:
    """One function under the clock lattice: wall-clock function
    references tracked through bindings; calls and escapes flagged."""

    def __init__(
        self,
        mod: ModuleInfo,
        findings: List[Finding],
        modules: Optional[Dict[str, ModuleInfo]] = None,
        summaries: Optional[SummaryTable] = None,
    ):
        self.mod = mod
        self.findings = findings
        self.modules = modules if modules is not None else {mod.path: mod}
        self.summaries = summaries
        self._flagged: Set[Tuple[int, str]] = set()

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if (line, rule) in self._flagged:
            return
        # sanctioned sites still EMIT: partition_findings classifies them
        # into the sanctioned channel (so the CLI can count the boundary
        # and the stale audit can see the marker is live)
        self._flagged.add((line, rule))
        self.findings.append(
            Finding(rule, Severity.ERROR, self.mod.path, line, message)
        )

    # -- classification ---------------------------------------------------

    def _is_wall_clock_ref(self, node: ast.AST) -> bool:
        name = dotted_name(node)
        if name is None:
            return False
        return _canonical(name, self.mod.aliases) in _WALL_CLOCK_FNS

    def kind(self, node: ast.AST, env: Env) -> int:
        if isinstance(node, ast.Name):
            if self._is_wall_clock_ref(node):
                return CLOCKFN
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            if self._is_wall_clock_ref(node):
                return CLOCKFN
            return PLAIN
        if isinstance(node, ast.IfExp):
            return max(self.kind(node.body, env), self.kind(node.orelse, env))
        if isinstance(node, ast.BoolOp):
            # `clock or time.monotonic` keeps the fallback visible
            return max((self.kind(v, env) for v in node.values), default=PLAIN)
        if isinstance(node, ast.NamedExpr):
            return self.kind(node.value, env)
        if isinstance(node, ast.Call):
            # call-graph reach: a bare-name call resolving to a local
            # helper takes the helper's summarized return kind — a
            # helper that hands back time.monotonic makes its call site
            # a clock source (`f = _pick_clock(); ... f()`)
            raw = dotted_name(node.func)
            if (
                self.summaries is not None
                and raw is not None
                and "." not in raw
                and not env.has(raw)
            ):
                hit = resolve_local(self.mod, raw, self.modules)
                if hit is not None:
                    return _return_kind(
                        hit[0], hit[1], self.modules, self.summaries
                    )
            return PLAIN
        return PLAIN

    # -- transfer ---------------------------------------------------------

    def _bind_target(self, target: ast.AST, kind: int, env: Env) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, kind)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, PLAIN, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, kind, env)

    def transfer(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            if isinstance(node, ast.Assign):
                kind = self.kind(node.value, env)
                for target in node.targets:
                    self._bind_target(target, kind, env)
                # `self._now = time.perf_counter` escapes through the
                # instance; attribute stores can't be tracked further
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind_target(
                    node.target, self.kind(node.value, env), env
                )
        elif atom.kind == "for":
            self._bind_target(node.target, PLAIN, env)

    # -- checks -----------------------------------------------------------

    def check(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "def":
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_function(self.mod, node, self.findings, parent=self)
            elif isinstance(node, ast.ClassDef):
                _check_class(self.mod, node, self.findings,
                             modules=self.modules, summaries=self.summaries)
            return
        if atom.kind == "for":
            self._check_expr(node.iter, env)
            return
        if atom.kind == "with":
            self._check_expr(node.context_expr, env)
            return
        if atom.kind == "test":
            self._check_expr(node, env)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._check_expr(child, env)

    def _check_expr(self, node: ast.AST, env: Env) -> None:
        if isinstance(node, ast.Call):
            cname = call_name(node, self.mod.aliases)
            if cname in _WALL_CLOCK_FNS:
                self._flag(
                    "CLK1001", node,
                    f"{cname} reads the wall clock; thread the injected "
                    "clock (kube/clock.py) or obs.now() so replays are "
                    "deterministic",
                )
            elif (
                isinstance(node.func, ast.Name)
                and env.get(node.func.id) == CLOCKFN
            ):
                self._flag(
                    "CLK1001", node,
                    f"{node.func.id}() resolves to a wall-clock function "
                    "bound earlier; thread the injected clock instead",
                )
            # arguments may still smuggle a clock reference out; the
            # callee itself was just checked as a call, so a plain
            # dotted callee is NOT re-checked as an escaping reference
            for child in list(node.args) + [kw.value for kw in node.keywords]:
                self._check_expr(child, env)
            if not isinstance(node.func, (ast.Name, ast.Attribute)):
                self._check_expr(node.func, env)
            return
        if self._is_wall_clock_ref(node):
            # a bare reference in value position: assigned, passed,
            # returned — a clock source injection can't replace
            name = dotted_name(node)
            self._flag(
                "CLK1002", node,
                f"{_canonical(name, self.mod.aliases)} escapes as a "
                "value; inject a Clock (kube/clock.py) so tests and "
                "replays can drive time",
            )
            return
        if isinstance(node, ast.Lambda):
            self._check_expr(node.body, env)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword,
                                  ast.FormattedValue)):
                self._check_expr(child, env)


def _return_kind(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    modules: Dict[str, ModuleInfo],
    summaries: SummaryTable,
) -> int:
    """Does the helper return a wall-clock callable? Joined over every
    return expression, bottom-up over the call graph (a helper returning
    another helper's clock result resolves too); recursive clusters read
    PLAIN by SCC collapse."""

    def compute() -> int:
        analysis = _ClockAnalysis(mod, [], modules=modules, summaries=summaries)
        init = Env(LATTICE)
        cfg = build_cfg(fn.body)
        envs = run_forward(cfg, init, analysis.transfer)
        out = [PLAIN]

        def collect(atom: Atom, env: Env) -> None:
            if (
                atom.kind == "stmt"
                and isinstance(atom.node, ast.Return)
                and atom.node.value is not None
            ):
                out.append(analysis.kind(atom.node.value, env))

        sweep(cfg, envs, init, analysis.transfer, collect)
        return max(out)

    return summaries.get((mod.path, fn.name), compute)


def check_function(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    findings: List[Finding],
    parent: "_ClockAnalysis" = None,
    modules: Optional[Dict[str, ModuleInfo]] = None,
    summaries: Optional[SummaryTable] = None,
) -> None:
    if parent is not None:
        modules = modules if modules is not None else parent.modules
        summaries = summaries if summaries is not None else parent.summaries
    analysis = _ClockAnalysis(mod, findings, modules=modules, summaries=summaries)
    if parent is not None:
        analysis._flagged = parent._flagged
    init = Env(LATTICE)
    cfg = build_cfg(fn.body)
    envs = run_forward(cfg, init, analysis.transfer)
    sweep(cfg, envs, init, analysis.transfer, analysis.check)


def _check_class(
    mod: ModuleInfo,
    cls: ast.ClassDef,
    findings: List[Finding],
    modules: Optional[Dict[str, ModuleInfo]] = None,
    summaries: Optional[SummaryTable] = None,
):
    if cls.name in _SEAM_CLASSES:
        return  # the documented RealClock seams read the wall clock
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            check_function(mod, item, findings, modules=modules,
                           summaries=summaries)
        elif isinstance(item, ast.ClassDef):
            _check_class(mod, item, findings, modules=modules,
                         summaries=summaries)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the clock-discipline pass; returns (findings, sources)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("CLK1000", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    summaries = SummaryTable(default=PLAIN, graph=build_call_graph(modules))
    for mod in modules.values():
        # module body (constants like `_NOW = time.time()`), then every
        # top-level function and class method
        analysis = _ClockAnalysis(mod, findings, modules=modules,
                                  summaries=summaries)
        init = Env(LATTICE)
        cfg = build_cfg(
            [s for s in mod.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))]
        )
        envs = run_forward(cfg, init, analysis.transfer)
        sweep(cfg, envs, init, analysis.transfer, analysis.check)
        for fn in mod.index.functions.values():
            check_function(mod, fn, findings, modules=modules,
                           summaries=summaries)
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                _check_class(mod, node, findings, modules=modules,
                             summaries=summaries)
    return findings, sources
