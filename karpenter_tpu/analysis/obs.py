"""Pass 8: observability hygiene (the obs/ tracing + metrics tiers).

Two anti-patterns structurally defeat the observability layer, and both
are statically visible:

- **OBS801 — span leak**: a ``tracer.span(...)`` / ``obs.span(...)`` call
  whose result is not closed deterministically. A span opened outside a
  ``with`` (and without a ``finally`` that ``.end()``s it) never pops the
  thread-local stack: every later span in that thread parents onto the
  leaked one, the Chrome export carries a dangling subtree, and the phase
  histograms silently miss the phase. Allowed shapes: the direct context
  manager (``with x.span(...)``), returning the span to the caller (a
  factory hands the context manager up — obs.span itself is this shape),
  passing it straight into ``enter_context``, and the assign-then-
  ``finally``-close idiom.
- **OBS802 — per-call metric churn**: a ``Counter``/``Gauge``/
  ``Histogram`` constructed inside a function. Every construction
  registers a NEW metric in the global registry (metrics/registry.py), so
  a per-call construction grows the registry without bound and forks the
  time series the scrape sees. Metrics belong at module scope, created
  once at import. Constructions that pass an explicit ``registry=`` are
  exempt — a scoped registry (tests, a sandboxed dump) is the designed
  way to build metrics dynamically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .astutil import iter_py_files, parse_file
from .findings import Finding, Severity, SourceFile

RULES = {
    "OBS800": "unparsable file (observability pass)",
    "OBS801": "span opened without context-manager or finally close",
    "OBS802": "metric constructed outside module scope (registry churn)",
}

_METRIC_NAMES = {"Counter", "Gauge", "Histogram"}


def _is_span_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr == "span"
    if isinstance(f, ast.Name):
        return f.id == "span"
    return False


def _metric_ctor_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name) and f.id in _METRIC_NAMES:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in _METRIC_NAMES:
        return f.attr
    return ""


def _allowed_span_calls(tree: ast.AST) -> Set[int]:
    """ids of span Call nodes used in one of the allowed closing shapes."""
    allowed: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    allowed.add(id(item.context_expr))
        elif isinstance(node, ast.Return) and isinstance(
            node.value, ast.Call
        ):
            # a factory returning the context manager for the caller's
            # `with` (obs.span itself, helpers that decorate a span)
            allowed.add(id(node.value))
        elif isinstance(node, ast.Call):
            # stack.enter_context(tracer.span(...))
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "enter_context":
                for arg in node.args:
                    if isinstance(arg, ast.Call):
                        allowed.add(id(arg))
    # conditional-expression returns: `return a.span() if c else NOOP`
    for node in ast.walk(tree):
        if isinstance(node, ast.Return) and isinstance(
            node.value, ast.IfExp
        ):
            for side in (node.value.body, node.value.orelse):
                if isinstance(side, ast.Call):
                    allowed.add(id(side))
    return allowed


def _finally_closed_targets(func: ast.AST) -> Set[str]:
    """Variable names ``X`` with ``X.end(...)`` / ``X.__exit__(...)``
    inside some ``finally`` block of ``func``."""
    out: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for stmt in node.finalbody:
            for call in ast.walk(stmt):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in ("end", "__exit__", "close")
                    and isinstance(call.func.value, ast.Name)
                ):
                    out.add(call.func.value.id)
    return out


def _check_module(tree: ast.Module, path: str) -> List[Finding]:
    findings: List[Finding] = []
    allowed = _allowed_span_calls(tree)

    # map every node to its enclosing function (for OBS801's finally
    # idiom and OBS802's module-scope test)
    func_of: Dict[int, ast.AST] = {}
    for func in ast.walk(tree):
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(func):
                # innermost function wins: walk assigns outer first, inner
                # later, so later writes overwrite
                func_of[id(child)] = func

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_span_call(node) and id(node) not in allowed:
            func = func_of.get(id(node))
            target = _assigned_name(node, func)
            if (
                func is not None
                and target
                and target in _finally_closed_targets(func)
            ):
                continue
            findings.append(
                Finding(
                    "OBS801", Severity.ERROR, path, node.lineno,
                    "span opened without `with` or a finally close: the "
                    "thread-local span stack leaks and later spans parent "
                    "onto the leaked one; use `with tracer.span(...)`",
                )
            )
        ctor = _metric_ctor_name(node)
        if ctor and id(node) in func_of:
            if any(kw.arg == "registry" for kw in node.keywords):
                continue  # scoped registry: the designed dynamic shape
            findings.append(
                Finding(
                    "OBS802", Severity.ERROR, path, node.lineno,
                    f"{ctor} constructed inside a function registers a "
                    "new metric in the global registry on every call; "
                    "construct metrics at module scope (or pass an "
                    "explicit registry= for a scoped one)",
                )
            )
    return findings


def _assigned_name(call: ast.Call, func) -> str:
    """The simple name the call's result is bound to in the enclosing
    function, or "" (looks for ``name = <call>``)."""
    if func is None:
        return ""
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Assign)
            and node.value is call
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            return node.targets[0].id
    return ""


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    findings: List[Finding] = []
    sources: Dict[str, SourceFile] = {}
    for path in iter_py_files(paths):
        try:
            src, tree = parse_file(path)
        except (OSError, SyntaxError) as exc:
            findings.append(
                Finding("OBS800", Severity.ERROR, path, 0, f"unparsable: {exc}")
            )
            continue
        sources[path] = src
        findings.extend(_check_module(tree, path))
    return findings, sources
