"""Shared dataflow core for the analysis passes.

The first-generation passes (TRC/BLK/RTY/OBS) were per-function AST
pattern matchers: one sequential walk, one mutable name->kind table, no
notion of control flow or of values crossing a helper call. That shape
cannot see the flows the device-residency (DTX9xx) and clock-discipline
(CLK10xx) contracts are about — a device array threaded through an
``if``/``else`` merge into a truthiness test, a ``time.monotonic``
reference stashed in a variable and called three statements later, a
helper that returns a kernel-dispatch result under a different name.

This package is the replacement substrate, shared by every dataflow-
shaped rule family:

- ``cfg``       — intraprocedural control-flow graph over function bodies
                  (basic blocks of *atoms*: statements, branch tests,
                  loop binds, nested defs), with loop back-edges and
                  conservative exception edges;
- ``lattice``   — small integer join-semilattices with pointwise-join
                  environments (name -> lattice value), including the
                  poison-to-unknown discipline: an analysis that loses
                  track of a value joins it to TOP and never flags it
                  (false negatives over false positives, the same rule
                  shapes.py pinned);
- ``dataflow``  — the forward worklist engine: fixpoint block-entry
                  environments, then a deterministic per-block check
                  sweep re-running the transfer for intra-block
                  precision;
- ``summaries`` — one-level call-graph summaries for same-module
                  helpers (mirroring how PAR5xx resolves shared
                  constants): a bare-name call to a local helper gets
                  the join of the helper's return-expression kinds
                  instead of defaulting to unknown.

Rule families hosted on the core: tracer.py (TRC1xx, migrated),
retry.py (RTY7xx bound detection, migrated), device.py (DTX9xx),
clock.py (CLK10xx). The passes stay parse-only: nothing here imports
the analyzed code.
"""

from .cfg import CFG, Atom, Block, build_cfg
from .dataflow import Env, run_forward, sweep
from .lattice import Lattice
from .summaries import ModuleInfo, ReturnSummaries, load_modules

__all__ = [
    "CFG", "Atom", "Block", "build_cfg",
    "Env", "run_forward", "sweep",
    "Lattice",
    "ModuleInfo", "ReturnSummaries", "load_modules",
]
