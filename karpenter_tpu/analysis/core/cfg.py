"""Intraprocedural CFG over a function body.

Blocks hold *atoms* — the unit the transfer functions consume:

- ``("stmt", node)``   a simple statement (Assign, Expr, Return, ...)
- ``("test", expr)``   a branch condition being evaluated (``label``
                       says which construct: if / while / assert)
- ``("for", node)``    a For header: the iterable is evaluated and the
                       loop target bound once per entry
- ``("with", item)``   one withitem: context expr evaluated, optional
                       ``as`` target bound
- ``("except", h)``    an except handler's name binding
- ``("def", node)``    a nested FunctionDef/AsyncFunctionDef/ClassDef

Edges follow Python's control flow: if/else diamonds, loop back-edges
(with ``break``/``continue`` routed to the loop exit/header), try bodies
with conservative exception edges (every block spawned inside a ``try``
body edges to every handler entry — a may-analysis over-approximation,
since the exception can fire at any point), and ``finally`` blocks on
the join. ``return``/``raise`` terminate their block; ``return`` still
edges into enclosing ``finally`` atoms via the exit path being cut —
the analyses here are flow-insensitive past a return, which is safe for
join-based may-analyses.

Block ids increase in syntactic creation order, so a deterministic
check sweep over ``sorted(blocks)`` reports findings in source order.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


@dataclass
class Atom:
    kind: str  # "stmt" | "test" | "for" | "with" | "except" | "def"
    node: ast.AST
    label: str = ""


@dataclass
class Block:
    id: int
    atoms: List[Atom] = field(default_factory=list)
    succs: List[int] = field(default_factory=list)

    def edge(self, other: "Block") -> None:
        if other.id not in self.succs:
            self.succs.append(other.id)


@dataclass
class CFG:
    entry: int
    blocks: List[Block]

    def block(self, bid: int) -> Block:
        return self.blocks[bid]


_SIMPLE = (
    ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr, ast.Return,
    ast.Raise, ast.Delete, ast.Pass, ast.Global, ast.Nonlocal,
    ast.Import, ast.ImportFrom,
)

_TERMINATORS = (ast.Return, ast.Raise)


class _Builder:
    def __init__(self):
        self.blocks: List[Block] = []
        # (header_block, after_block) per enclosing loop, for continue/break
        self.loops: List[Tuple[Block, Block]] = []

    def new(self) -> Block:
        b = Block(id=len(self.blocks))
        self.blocks.append(b)
        return b

    # -- statement walk ---------------------------------------------------

    def walk(self, stmts: Sequence[ast.stmt], cur: Block) -> Optional[Block]:
        """Append ``stmts`` starting at ``cur``; returns the fall-through
        block, or None when every path terminated (return/raise/break)."""
        for stmt in stmts:
            if cur is None:
                # dead code after a terminator: still walked (findings in
                # unreachable code are findings), rooted in a fresh block
                cur = self.new()
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            cur.atoms.append(Atom("def", stmt))
            return cur
        if isinstance(stmt, _SIMPLE):
            cur.atoms.append(Atom("stmt", stmt))
            if isinstance(stmt, _TERMINATORS):
                return None
            return cur
        if isinstance(stmt, ast.Assert):
            cur.atoms.append(Atom("test", stmt.test, "assert"))
            if stmt.msg is not None:
                cur.atoms.append(Atom("stmt", ast.Expr(value=stmt.msg)))
            return cur
        if isinstance(stmt, ast.If):
            cur.atoms.append(Atom("test", stmt.test, "if"))
            after = self.new()
            then_entry = self.new()
            cur.edge(then_entry)
            then_exit = self.walk(stmt.body, then_entry)
            if then_exit is not None:
                then_exit.edge(after)
            if stmt.orelse:
                else_entry = self.new()
                cur.edge(else_entry)
                else_exit = self.walk(stmt.orelse, else_entry)
                if else_exit is not None:
                    else_exit.edge(after)
            else:
                cur.edge(after)
            return after
        if isinstance(stmt, ast.While):
            header = self.new()
            cur.edge(header)
            header.atoms.append(Atom("test", stmt.test, "while"))
            after = self.new()
            body_entry = self.new()
            header.edge(body_entry)
            header.edge(after)
            self.loops.append((header, after))
            body_exit = self.walk(stmt.body, body_entry)
            self.loops.pop()
            if body_exit is not None:
                body_exit.edge(header)
            return self._loop_else(stmt, header, after)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            header = self.new()
            cur.edge(header)
            header.atoms.append(Atom("for", stmt))
            after = self.new()
            body_entry = self.new()
            header.edge(body_entry)
            header.edge(after)
            self.loops.append((header, after))
            body_exit = self.walk(stmt.body, body_entry)
            self.loops.pop()
            if body_exit is not None:
                body_exit.edge(header)
            return self._loop_else(stmt, header, after)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                cur.atoms.append(Atom("with", item))
            return self.walk(stmt.body, cur)
        if isinstance(stmt, ast.Try):
            first_body_block = len(self.blocks)
            body_entry = self.new()
            cur.edge(body_entry)
            # one block boundary after every body statement: the
            # exception can fire between any two of them, so each
            # partial-execution state must be a block exit the handler
            # edges can observe
            body_exit: Optional[Block] = body_entry
            for s in stmt.body:
                if body_exit is None:
                    body_exit = self.new()
                nxt = self._stmt(s, body_exit)
                if nxt is None:
                    body_exit = None
                else:
                    boundary = self.new()
                    nxt.edge(boundary)
                    body_exit = boundary
            body_blocks = self.blocks[first_body_block:]
            after = self.new()
            # handlers: the exception may fire anywhere in the body, so
            # every body-spawned block (and the pre-try block) edges in
            for handler in stmt.handlers:
                h_entry = self.new()
                h_entry.atoms.append(Atom("except", handler))
                cur.edge(h_entry)
                for b in body_blocks:
                    b.edge(h_entry)
                h_exit = self.walk(handler.body, h_entry)
                if h_exit is not None:
                    h_exit.edge(after)
            if stmt.orelse:
                if body_exit is not None:
                    else_exit = self.walk(stmt.orelse, body_exit)
                    if else_exit is not None:
                        else_exit.edge(after)
            elif body_exit is not None:
                body_exit.edge(after)
            if stmt.finalbody:
                fin_exit = self.walk(stmt.finalbody, after)
                return fin_exit
            return after
        if isinstance(stmt, ast.Break):
            if self.loops:
                cur.edge(self.loops[-1][1])
            return None
        if isinstance(stmt, ast.Continue):
            if self.loops:
                cur.edge(self.loops[-1][0])
            return None
        if isinstance(stmt, ast.Match):
            cur.atoms.append(Atom("test", stmt.subject, "match"))
            after = self.new()
            for case in stmt.cases:
                c_entry = self.new()
                cur.edge(c_entry)
                c_exit = self.walk(case.body, c_entry)
                if c_exit is not None:
                    c_exit.edge(after)
            cur.edge(after)  # no case may match
            return after
        # anything else (future syntax): treat as an opaque statement
        cur.atoms.append(Atom("stmt", stmt))
        return cur

    def _loop_else(self, stmt, header: Block, after: Block) -> Block:
        if stmt.orelse:
            else_entry = self.new()
            header.edge(else_entry)
            else_exit = self.walk(stmt.orelse, else_entry)
            if else_exit is not None:
                else_exit.edge(after)
        return after


def build_cfg(body: Sequence[ast.stmt]) -> CFG:
    """CFG for a statement list (a function body or a module)."""
    builder = _Builder()
    entry = builder.new()
    builder.walk(body, entry)
    return CFG(entry=entry.id, blocks=builder.blocks)


__all__ = ["Atom", "Block", "CFG", "build_cfg"]
