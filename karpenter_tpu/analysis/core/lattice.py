"""Small integer join-semilattices for the dataflow passes.

Every pass models values as small non-negative integers ordered by
``max``: 0 is bottom ("nothing interesting"), the largest level is top.
Two disciplines coexist on that shape:

- **taint-style** (tracer): the interesting kind (TRACED) is the top —
  joining "traced on one path" with "static on the other" yields traced,
  so a sink reachable with a traced value on ANY path flags. Missing
  names default to bottom.
- **poison-to-unknown** (device, clock): UNKNOWN sits ABOVE the
  interesting kind. A merge with a value the analysis lost track of
  poisons the result to unknown, and sinks flag only on the *definite*
  kind — the false-negative-over-false-positive rule shapes.py pinned,
  now a lattice property instead of a convention.

The ``Lattice`` object is a tiny descriptor: the default for unbound
names and the top used for poisoning. Join is always ``max``.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Lattice:
    """Join-semilattice descriptor over {0 .. top} with join = max.

    ``default`` is the value assumed for names with no binding (bottom
    for taint-style lattices, top/unknown for poison-style ones when a
    pass prefers to distrust unbound names).
    """

    top: int
    default: int = 0

    def join(self, a: int, b: int) -> int:
        return a if a >= b else b


__all__ = ["Lattice"]
