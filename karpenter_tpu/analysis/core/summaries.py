"""Module loading and call-graph function summaries.

``load_modules`` parses a file set once into ``ModuleInfo`` handles
(source, tree, import aliases, function index) shared by every rule
family on the core — the same one-read-per-file discipline findings.py's
``SourceFile`` established.

``resolve_local`` resolves a bare callee name used in one module to a
function def anywhere in the scanned set — locally, or through a
``from .x import name`` alias — mirroring how PAR5xx resolves shared
constants across the kernel twins.

``CallGraph`` indexes every resolvable call edge over the scanned module
set up front — bare-name and from-import callees plus the conservative
``self._helper()`` method resolution the retry pass pioneered — and
collapses its strongly connected components (iterative Tarjan).
``SummaryTable`` rides it: ``get(key, compute)`` memoizes per-function
summaries like the old one-level ``ReturnSummaries``, but a client's
``compute`` thunk may now recurse through ``get`` for its own callees,
so flow facts propagate bottom-up through ANY number of helper hops.
Cycle safety is structural, not accidental: every member of a nontrivial
SCC (mutual or self recursion) is pinned to the lattice default before
computation starts, so recursive clusters read as unknown on every path
— deterministically, independent of which member is queried first. The
``_busy`` guard remains as a backstop for edges the graph cannot see
(dynamic dispatch, getattr), where it degrades to the old one-level
behavior instead of looping.

``ReturnSummaries`` (the one-level table) survives as a graph-free
``SummaryTable``: existing callers keep working, and a pass migrates by
building the graph and letting its compute thunks recurse.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from ..astutil import FunctionIndex, dotted_name, import_aliases, iter_py_files, parse_file
from ..findings import SourceFile

# (module path, function name) — the summary/graph node key every
# core-hosted pass already uses
Key = Tuple[str, str]


@dataclass
class ModuleInfo:
    """One parsed module, shared by the core-hosted passes."""

    path: str
    src: SourceFile
    tree: ast.Module
    aliases: Dict[str, str] = field(default_factory=dict)
    index: FunctionIndex = None

    def __post_init__(self):
        if not self.aliases:
            self.aliases = import_aliases(self.tree)
        if self.index is None:
            self.index = FunctionIndex(self.tree)


def load_modules(
    paths: List[str],
) -> Tuple[Dict[str, ModuleInfo], Dict[str, SourceFile], List[Tuple[str, Exception]]]:
    """Parse a file set once: (modules by path, sources by path,
    [(path, error)] for unparsable files — each pass maps those onto its
    own x00 rule)."""
    modules: Dict[str, ModuleInfo] = {}
    sources: Dict[str, SourceFile] = {}
    errors: List[Tuple[str, Exception]] = []
    for path in iter_py_files(paths):
        try:
            src, tree = parse_file(path)
        except (OSError, SyntaxError) as exc:
            errors.append((path, exc))
            continue
        modules[path] = ModuleInfo(path=path, src=src, tree=tree)
        sources[path] = src
    return modules, sources, errors


def resolve_local(
    mod: ModuleInfo, name: str, modules: Dict[str, ModuleInfo]
) -> Optional[Tuple[ModuleInfo, ast.FunctionDef]]:
    """Resolve a bare name used in ``mod`` to a function def in the
    scanned set — locally, or through a ``from .x import name`` alias."""
    if name in mod.index.functions:
        return mod, mod.index.functions[name]
    origin = mod.aliases.get(name)
    if not origin or "." not in origin:
        return None
    mod_part, _, fn_name = origin.rpartition(".")
    base = mod_part.lstrip(".") or ""
    tail = base.rpartition(".")[2] if base else ""
    for other in modules.values():
        stem = os.path.splitext(os.path.basename(other.path))[0]
        if stem == tail and fn_name in other.index.functions:
            return other, other.index.functions[fn_name]
    return None


def _iter_defs(mod: ModuleInfo):
    """(name, FunctionDef) for every module-level function and every
    method, in source order — the call-graph node set. Method names key
    like function names (the convention the pass summary keys use); a
    collision joins their edges, which only widens cycles — safe."""
    for fname, fn in mod.index.functions.items():
        yield fname, fn
    for table in mod.index.methods.values():
        for fname, fn in table.items():
            yield fname, fn


def _callees(
    mod: ModuleInfo, fn: ast.FunctionDef, modules: Dict[str, ModuleInfo]
) -> List[Key]:
    """Resolvable callee keys of ``fn``: bare-name calls through
    ``resolve_local``, plus ``self._helper()`` against every method table
    in the module (conservative, matching the retry pass)."""
    out: List[Key] = []
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        raw = dotted_name(sub.func)
        if raw is not None and "." not in raw:
            hit = resolve_local(mod, raw, modules)
            if hit is not None:
                out.append((hit[0].path, hit[1].name))
        elif (
            isinstance(sub.func, ast.Attribute)
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "self"
        ):
            for table in mod.index.methods.values():
                if sub.func.attr in table:
                    out.append((mod.path, sub.func.attr))
                    break
    return out


class CallGraph:
    """Module-set call graph with SCC collapse.

    ``edges`` maps every function/method key to its resolvable callees;
    ``cycle_members`` is the union of all nontrivial SCCs (size > 1, or a
    self-edge) — the keys a ``SummaryTable`` pins to the lattice default
    so recursion can never observe a half-computed summary.
    """

    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.edges: Dict[Key, Tuple[Key, ...]] = {}
        for path in sorted(modules):
            mod = modules[path]
            for fname, fn in _iter_defs(mod):
                key = (mod.path, fname)
                direct = _callees(mod, fn, modules)
                # a name collision (same key from two defs) joins edges
                self.edges.setdefault(key, ())
                self.edges[key] = tuple(
                    dict.fromkeys(self.edges[key] + tuple(direct))
                )
        self.cycle_members: FrozenSet[Key] = self._collapse()

    def _collapse(self) -> FrozenSet[Key]:
        """Iterative Tarjan; returns members of every nontrivial SCC."""
        index: Dict[Key, int] = {}
        low: Dict[Key, int] = {}
        on_stack: Dict[Key, bool] = {}
        stack: List[Key] = []
        counter = [0]
        cyclic: List[Key] = []

        for root in self.edges:
            if root in index:
                continue
            # explicit DFS stack: (node, iterator over callees)
            work = [(root, iter(self.edges.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack[root] = True
            while work:
                node, it = work[-1]
                advanced = False
                for callee in it:
                    if callee not in self.edges:
                        continue  # resolved into a module outside the set
                    if callee not in index:
                        index[callee] = low[callee] = counter[0]
                        counter[0] += 1
                        stack.append(callee)
                        on_stack[callee] = True
                        work.append((callee, iter(self.edges.get(callee, ()))))
                        advanced = True
                        break
                    if on_stack.get(callee):
                        low[node] = min(low[node], index[callee])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc: List[Key] = []
                    while True:
                        member = stack.pop()
                        on_stack[member] = False
                        scc.append(member)
                        if member == node:
                            break
                    if len(scc) > 1 or node in self.edges.get(node, ()):
                        cyclic.extend(scc)
        return frozenset(cyclic)


def build_call_graph(modules: Dict[str, ModuleInfo]) -> CallGraph:
    """The scanned set's call graph — build once per pass run, share
    across every summary table that run creates."""
    return CallGraph(modules)


class SummaryTable:
    """Memoized function summaries over a call graph.

    Without a graph this is exactly the old one-level ``ReturnSummaries``
    (the ``_busy`` guard returns the default on any re-entry). With a
    graph, clients' compute thunks recurse through ``get`` for their
    callees and summaries propagate bottom-up arbitrarily deep; members
    of a nontrivial SCC are pinned to the default up front, so mutual
    recursion reads as unknown on every query order.
    """

    def __init__(self, default: int, graph: Optional[CallGraph] = None):
        self.default = default
        self.graph = graph
        self._memo: Dict[tuple, int] = {}
        self._busy: set = set()

    def get(self, key: tuple, compute: Callable[[], int]) -> int:
        if key in self._memo:
            return self._memo[key]
        if self.graph is not None and key in self.graph.cycle_members:
            # SCC collapse: recursive clusters are unknown/default by
            # construction, independent of traversal order
            self._memo[key] = self.default
            return self.default
        if key in self._busy:
            return self.default  # edge the graph missed: one level only
        self._busy.add(key)
        try:
            out = compute()
        finally:
            self._busy.discard(key)
        self._memo[key] = out
        return out


class ReturnSummaries(SummaryTable):
    """Backward-compatible one-level table (no graph)."""

    def __init__(self, default: int):
        super().__init__(default, graph=None)


__all__ = [
    "CallGraph",
    "ModuleInfo",
    "ReturnSummaries",
    "SummaryTable",
    "build_call_graph",
    "load_modules",
    "resolve_local",
]
