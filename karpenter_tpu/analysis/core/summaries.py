"""Module loading and one-level call-graph summaries.

``load_modules`` parses a file set once into ``ModuleInfo`` handles
(source, tree, import aliases, function index) shared by every rule
family on the core — the same one-read-per-file discipline findings.py's
``SourceFile`` established.

``resolve_local`` resolves a bare callee name used in one module to a
function def anywhere in the scanned set — locally, or through a
``from .x import name`` alias — mirroring how PAR5xx resolves shared
constants across the kernel twins.

``ReturnSummaries`` memoizes per-function return summaries with a
recursion guard: summaries reach exactly ONE level of same-module
helpers (a helper's own summary is computed with nested helper calls
unresolved), which keeps the interprocedural step predictable and the
fixpoint trivial. Clients supply the compute thunk; the guard hands
back the lattice default on self/mutual recursion.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..astutil import FunctionIndex, import_aliases, iter_py_files, parse_file
from ..findings import SourceFile


@dataclass
class ModuleInfo:
    """One parsed module, shared by the core-hosted passes."""

    path: str
    src: SourceFile
    tree: ast.Module
    aliases: Dict[str, str] = field(default_factory=dict)
    index: FunctionIndex = None

    def __post_init__(self):
        if not self.aliases:
            self.aliases = import_aliases(self.tree)
        if self.index is None:
            self.index = FunctionIndex(self.tree)


def load_modules(
    paths: List[str],
) -> Tuple[Dict[str, ModuleInfo], Dict[str, SourceFile], List[Tuple[str, Exception]]]:
    """Parse a file set once: (modules by path, sources by path,
    [(path, error)] for unparsable files — each pass maps those onto its
    own x00 rule)."""
    modules: Dict[str, ModuleInfo] = {}
    sources: Dict[str, SourceFile] = {}
    errors: List[Tuple[str, Exception]] = []
    for path in iter_py_files(paths):
        try:
            src, tree = parse_file(path)
        except (OSError, SyntaxError) as exc:
            errors.append((path, exc))
            continue
        modules[path] = ModuleInfo(path=path, src=src, tree=tree)
        sources[path] = src
    return modules, sources, errors


def resolve_local(
    mod: ModuleInfo, name: str, modules: Dict[str, ModuleInfo]
) -> Optional[Tuple[ModuleInfo, ast.FunctionDef]]:
    """Resolve a bare name used in ``mod`` to a function def in the
    scanned set — locally, or through a ``from .x import name`` alias."""
    if name in mod.index.functions:
        return mod, mod.index.functions[name]
    origin = mod.aliases.get(name)
    if not origin or "." not in origin:
        return None
    mod_part, _, fn_name = origin.rpartition(".")
    base = mod_part.lstrip(".") or ""
    tail = base.rpartition(".")[2] if base else ""
    for other in modules.values():
        stem = os.path.splitext(os.path.basename(other.path))[0]
        if stem == tail and fn_name in other.index.functions:
            return other, other.index.functions[fn_name]
    return None


class ReturnSummaries:
    """Memoized one-level function summaries with a recursion guard."""

    def __init__(self, default: int):
        self.default = default
        self._memo: Dict[tuple, int] = {}
        self._busy: set = set()

    def get(self, key: tuple, compute: Callable[[], int]) -> int:
        if key in self._memo:
            return self._memo[key]
        if key in self._busy:
            return self.default  # recursion: one level only
        self._busy.add(key)
        try:
            out = compute()
        finally:
            self._busy.discard(key)
        self._memo[key] = out
        return out


__all__ = ["ModuleInfo", "ReturnSummaries", "load_modules", "resolve_local"]
