"""Forward worklist dataflow over the core CFG.

Two phases, both driven by a client-supplied transfer function:

1. ``run_forward`` — fixpoint: block-entry environments computed by
   iterating transfer over atoms and joining into successors until
   nothing changes. Monotone by construction (environments only move up
   the lattice under ``max``-join), so termination is bounded by
   |blocks| x |names| x lattice height.
2. ``sweep`` — the reporting pass: blocks visited in syntactic order,
   each starting from its fixpoint entry environment, re-running
   transfer after the client's per-atom check hook so intra-block
   precision matches a sequential read of the source.

Environments are plain ``name -> int`` dicts wrapped with lattice-aware
join; clients keep richer side tables (helper summaries, flagged lines)
on their own analysis object.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from .cfg import CFG, Atom
from .lattice import Lattice


class Env:
    """name -> lattice value with pointwise join. Missing names read as
    the lattice default."""

    __slots__ = ("lattice", "kinds")

    def __init__(self, lattice: Lattice, kinds: Dict[str, int] = None):
        self.lattice = lattice
        self.kinds: Dict[str, int] = dict(kinds or {})

    def get(self, name: str) -> int:
        return self.kinds.get(name, self.lattice.default)

    def has(self, name: str) -> bool:
        return name in self.kinds

    def set(self, name: str, kind: int) -> None:
        self.kinds[name] = kind

    def clone(self) -> "Env":
        return Env(self.lattice, self.kinds)

    def join_from(self, other: "Env") -> bool:
        """Pointwise join ``other`` into self; True when self changed."""
        changed = False
        for name, kind in other.kinds.items():
            mine = self.kinds.get(name)
            if mine is None:
                self.kinds[name] = kind
                changed = True
            else:
                joined = self.lattice.join(mine, kind)
                if joined != mine:
                    self.kinds[name] = joined
                    changed = True
        return changed


TransferFn = Callable[[Atom, Env], None]
CheckFn = Callable[[Atom, Env], None]


def run_forward(cfg: CFG, init: Env, transfer: TransferFn) -> Dict[int, Env]:
    """Fixpoint block-entry environments for ``cfg`` from ``init``."""
    entry_envs: Dict[int, Env] = {cfg.entry: init.clone()}
    worklist: List[int] = [cfg.entry]
    while worklist:
        bid = worklist.pop(0)
        env = entry_envs[bid].clone()
        for atom in cfg.block(bid).atoms:
            transfer(atom, env)
        for succ in cfg.block(bid).succs:
            known = entry_envs.get(succ)
            if known is None:
                entry_envs[succ] = env.clone()
                worklist.append(succ)
            elif known.join_from(env):
                if succ not in worklist:
                    worklist.append(succ)
    return entry_envs


def sweep(
    cfg: CFG,
    entry_envs: Dict[int, Env],
    init: Env,
    transfer: TransferFn,
    check: CheckFn,
) -> None:
    """Deterministic reporting sweep: every block in id (syntactic)
    order, checks interleaved with transfer for intra-block precision.
    Unreachable blocks (no fixpoint env) run from ``init`` — findings in
    dead code are still findings."""
    for block in cfg.blocks:
        env = entry_envs.get(block.id)
        env = env.clone() if env is not None else init.clone()
        for atom in block.atoms:
            check(atom, env)
            transfer(atom, env)


__all__ = ["Env", "run_forward", "sweep", "TransferFn", "CheckFn"]
