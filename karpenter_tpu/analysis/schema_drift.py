"""Pass 4: structural drift between api/schema.py and the CRD YAML.

The runtime validation tier (api/validation.py) and the checked-in CRD
artifacts (api/crds/*.yaml) are kept in lockstep by a round-trip test that
IMPORTS the schema module; this pass is the static complement — it diffs
the dict-literal structure of api/schema.py against the YAML without
executing anything, so a hand-edited YAML or a schema change that was
never regenerated fails presubmit even when the test suite is skipped.

The evaluator only follows literals: dicts, lists, constants, module-level
literal constants, and zero-arg calls to local ``_*_schema()`` helpers.
Anything else (``sorted(val.SUPPORTED_OPERATORS)``, ``pattern % ...``)
evaluates to a wildcard that matches any YAML value — so the comparison is
exact on structure (property keys, required lists, literal enums) and
agnostic about values sourced from the runtime validator.

Rules:
- SCH401: key present in schema.py but missing from the YAML artifact
- SCH402: key present in the YAML artifact but not in schema.py
- SCH403: literal value mismatch (enums, required lists, scalars)
- SCH404: artifact missing/unparsable, or PyYAML unavailable (warning)
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Tuple

from .astutil import parse_file
from .findings import Finding, Severity, SourceFile

RULES = {
    "SCH400": "unparsable schema module (schema pass)",
    "SCH401": "key present in schema.py but missing from the YAML artifact",
    "SCH402": "key present in the YAML artifact but not in schema.py",
    "SCH403": "literal value mismatch (enums, required lists, scalars)",
    "SCH404": "artifact missing/unparsable, or PyYAML unavailable",
}

WILDCARD = object()

# artifact filename -> schema-building function in the module
DEFAULT_ARTIFACTS = {
    "karpenter_tpu_nodepools.yaml": "nodepool_schema",
    "karpenter_tpu_nodeclaims.yaml": "nodeclaim_schema",
}


class _Evaluator:
    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, ast.FunctionDef] = {
            n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        self._memo: Dict[str, Any] = {}
        self.globals: Dict[str, Any] = {}
        # after _memo: a module-level `X = some_schema()` evaluates through
        # eval_function, which reads the memo
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    self.globals[target.id] = self._eval(node.value)

    def eval_function(self, name: str) -> Any:
        if name in self._memo:
            return self._memo[name]
        fn = self.functions.get(name)
        if fn is None:
            return WILDCARD
        self._memo[name] = WILDCARD  # cycle guard
        result: Any = WILDCARD
        for stmt in fn.body:
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                result = self._eval(stmt.value)
        self._memo[name] = result
        return result

    def _eval(self, node: ast.AST) -> Any:
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Dict):
            out: Dict[Any, Any] = {}
            for k, v in zip(node.keys, node.values):
                if k is None:  # **spread
                    return WILDCARD
                key = self._eval(k)
                if key is WILDCARD or not isinstance(key, str):
                    return WILDCARD
                out[key] = self._eval(v)
            return out
        if isinstance(node, (ast.List, ast.Tuple)):
            return [self._eval(e) for e in node.elts]
        if isinstance(node, ast.Name):
            return self.globals.get(node.id, WILDCARD)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and not node.args and \
                    not node.keywords:
                return self.eval_function(node.func.id)
            return WILDCARD
        return WILDCARD


def _diff(
    expected: Any, actual: Any, path: str, line: int, artifact: str,
    findings: List[Finding], py_path: str,
) -> None:
    if expected is WILDCARD:
        return
    if isinstance(expected, dict):
        if not isinstance(actual, dict):
            findings.append(
                Finding(
                    "SCH403", Severity.ERROR, py_path, line,
                    f"{artifact}: {path or '<root>'} is a mapping in "
                    f"schema.py but {type(actual).__name__} in the YAML",
                )
            )
            return
        for key in expected:
            child = f"{path}.{key}" if path else key
            if key not in actual:
                findings.append(
                    Finding(
                        "SCH401", Severity.ERROR, py_path, line,
                        f"{artifact}: '{child}' is defined in schema.py "
                        "but missing from the YAML artifact — regenerate "
                        "with `python -m karpenter_tpu.api.schema`",
                    )
                )
            else:
                _diff(expected[key], actual[key], child, line, artifact,
                      findings, py_path)
        for key in actual:
            if key not in expected:
                child = f"{path}.{key}" if path else key
                findings.append(
                    Finding(
                        "SCH402", Severity.ERROR, py_path, line,
                        f"{artifact}: '{child}' exists in the YAML artifact "
                        "but not in schema.py — stale artifact or "
                        "hand-edited YAML",
                    )
                )
        return
    if isinstance(expected, list):
        if not isinstance(actual, list):
            findings.append(
                Finding(
                    "SCH403", Severity.ERROR, py_path, line,
                    f"{artifact}: {path} is a list in schema.py but "
                    f"{type(actual).__name__} in the YAML",
                )
            )
            return
        if any(e is WILDCARD for e in expected):
            return
        if all(isinstance(e, (str, int, float, bool)) for e in expected):
            # scalar lists (enums): compare as sets, order-insensitively
            if set(map(str, expected)) != set(map(str, actual or [])):
                findings.append(
                    Finding(
                        "SCH403", Severity.ERROR, py_path, line,
                        f"{artifact}: {path} differs — schema.py has "
                        f"{sorted(map(str, expected))}, YAML has "
                        f"{sorted(map(str, actual or []))}",
                    )
                )
            return
        if len(expected) != len(actual):
            findings.append(
                Finding(
                    "SCH403", Severity.ERROR, py_path, line,
                    f"{artifact}: {path} has {len(expected)} entries in "
                    f"schema.py but {len(actual)} in the YAML",
                )
            )
            return
        for i, (e, a) in enumerate(zip(expected, actual)):
            _diff(e, a, f"{path}[{i}]", line, artifact, findings, py_path)
        return
    if expected != actual:
        findings.append(
            Finding(
                "SCH403", Severity.ERROR, py_path, line,
                f"{artifact}: {path} is {expected!r} in schema.py but "
                f"{actual!r} in the YAML",
            )
        )


def check_schema(
    schema_py: str,
    crd_dir: str,
    artifacts: Optional[Dict[str, str]] = None,
) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    findings: List[Finding] = []
    sources: Dict[str, SourceFile] = {}
    try:
        src, tree = parse_file(schema_py)
    except (OSError, SyntaxError) as exc:
        return (
            [Finding("SCH400", Severity.ERROR, schema_py, 0,
                     f"unparsable: {exc}")],
            sources,
        )
    sources[schema_py] = src
    try:
        import yaml
    except ImportError:
        return (
            [Finding("SCH404", Severity.WARNING, schema_py, 0,
                     "PyYAML unavailable; schema-drift pass skipped")],
            sources,
        )

    evaluator = _Evaluator(tree)
    for artifact, fn_name in (artifacts or DEFAULT_ARTIFACTS).items():
        expected = evaluator.eval_function(fn_name)
        fn = evaluator.functions.get(fn_name)
        line = fn.lineno if fn is not None else 0
        if expected is WILDCARD:
            findings.append(
                Finding(
                    "SCH404", Severity.WARNING, schema_py, line,
                    f"schema function {fn_name}() not statically "
                    "evaluatable; drift check skipped",
                )
            )
            continue
        ypath = os.path.join(crd_dir, artifact)
        try:
            with open(ypath, encoding="utf-8") as fh:
                actual = yaml.safe_load(fh)
        except (OSError, yaml.YAMLError) as exc:
            findings.append(
                Finding(
                    "SCH404", Severity.ERROR, ypath, 0,
                    f"CRD artifact unreadable: {exc}",
                )
            )
            continue
        _diff(expected, actual, "", line, artifact, findings, schema_py)
    return findings, sources
