"""Stale-suppression audit (STALE001).

Suppressions and sanctions rot: the finding they covered gets fixed, the
code moves, the rule gets smarter (the TRC/RTY dataflow migration is
exactly that), and the marker stays behind — a hole a future regression
walks straight through. This audit flags every tolerance that no longer
tolerates anything:

- a baseline entry (hack/analysis_baseline.txt) matching no produced
  finding;
- an inline ``# analysis: ignore[RULE]`` or ``sanctioned[RULE]`` marker
  whose (line, rule) reach covers no produced finding — including rules
  that no longer exist.

Accuracy requires the producing passes to have RUN on the marker's file,
so the CLI only audits on full runs (every pass, no ``--changed-only``)
and only treats a marker rule as stale when the pass owning that rule
actually scanned the file. ``--prune-baseline`` rewrites the baseline
with the stale entries dropped; stale inline markers are reported for
manual deletion (they carry prose a tool shouldn't silently discard).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding, Severity, SourceFile

RULES = {
    "STALE001": "suppression/sanction no longer matches any finding",
}


def audit(
    findings: Iterable[Finding],
    sources: Dict[str, SourceFile],
    baseline: Optional[Set[Tuple[str, str, str]]],
    baseline_path: str,
    scanned_by_rule: Optional[Dict[str, Set[str]]] = None,
) -> Tuple[List[Finding], Set[Tuple[str, str, str]]]:
    """(STALE001 findings, the stale baseline entries).

    ``findings`` is the PRE-filter set (suppressed and sanctioned ones
    included — a marker that still matches its finding is live).
    ``scanned_by_rule`` maps rule id -> set of paths the owning pass
    scanned; marker rules whose pass never saw the file are skipped
    (unknown rule ids are always stale).
    """
    produced_keys = {f.baseline_key() for f in findings}
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)

    out: List[Finding] = []
    stale_entries: Set[Tuple[str, str, str]] = set()
    for entry in sorted(baseline or ()):
        if entry not in produced_keys:
            stale_entries.add(entry)
            rule, path, message = entry
            out.append(
                Finding(
                    "STALE001", Severity.ERROR, baseline_path, 0,
                    f"baseline entry matches no finding: {rule} at {path} "
                    f"({message[:60]!r}); prune with --prune-baseline",
                )
            )

    for path in sorted(sources):
        src = sources[path]
        path_findings = by_path.get(path, [])
        for marker in src.markers:
            for rule in sorted(marker.rules):
                if (
                    scanned_by_rule is not None
                    and rule in scanned_by_rule
                    and path not in scanned_by_rule[rule]
                ):
                    continue  # owning pass didn't scan this file
                # a rule id no pass ships falls through to the liveness
                # check and is always stale (no finding can ever match)
                live = any(
                    f.rule == rule and marker.covers(f.line)
                    for f in path_findings
                )
                if not live:
                    out.append(
                        Finding(
                            "STALE001", Severity.ERROR, path, marker.line,
                            f"inline {marker.dialect}[{rule}] matches no "
                            "finding on its line or the line below; "
                            "delete the marker",
                        )
                    )
    return out, stale_entries


__all__ = ["RULES", "audit"]
