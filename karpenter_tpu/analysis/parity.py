"""Pass 5: cross-implementation parity drift between the kernel twins.

The packing program exists three times — ``ops/packing.py::pack``,
``pack_classed``, and the C++ core ``native/solve_core.cc`` — and the three
must stay bit-exact (tests/test_classed_kernel.py, tests/test_native.py
assert it dynamically, but only for the shapes the fixtures cover). This
pass makes the *structural* agreement a presubmit property: it builds a
"semantic skeleton" from each twin and reports any divergence, so a
cost-model tweak that lands in two of the three twins fails before the
parity suites (or the TPU-only path the fallback grid skips) notice.

A skeleton has five components:

- **phases**: the ordered tier sequence (existing-nodes -> open-claims ->
  fresh-claims), declared with anchor comments in every twin;
- **consts**: the significant shared numeric constants (sentinels like
  ``2**28``/``2**30``, epsilons like ``1e-9``, the proportional-spread
  offset ``0.5``) — derived from the AST on the Python side (literals plus
  module-level constant names like ``_BIGI``, resolved transitively through
  same-module helpers such as ``spread_domain_choice``);
- **dtypes**: the element-type vocabulary (float32/int32/bool);
- **tiebreaks**: the order-sensitive reduction disciplines in use
  (argmin/argmax/searchsorted/cumsum — each encodes a tie-break rule the
  reference's sequential walk implies);
- **state_fields**: the carried-state inventory, pinned to the
  ``PackState`` NamedTuple declaration.

Python skeletons are extracted from parse trees (astutil). The C++ core has
no parser here, so it *declares* its skeleton with anchor comments::

    // parity: phase existing-nodes
    // parity: const 2**28
    // parity: dtype float32
    // parity: tiebreak argmin
    // parity: state c_used, c_npods

Rules:

- PAR500: extraction failure (unparsable file, kernel/state class missing,
  a twin with no anchors at all)
- PAR501: phase-sequence drift between twins
- PAR502: shared-constant drift (present in one twin, absent in another)
- PAR503: dtype-literal drift
- PAR504: tie-break discipline drift
- PAR505: state-field inventory drift (a twin missing a declared field, or
  an anchor naming a field with no Python twin — stale after a rename)
- PAR506: malformed or unknown ``parity:`` anchor

Suppress with ``# analysis: ignore[PAR50x] reason`` (Python) or
``// analysis: ignore[PAR50x] reason`` (C++) on or above the flagged line.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .astutil import call_name, import_aliases, parse_file
from .findings import Finding, Severity, SourceFile

RULES = {
    "PAR500": "parity skeleton extraction failure",
    "PAR501": "phase-sequence drift between kernel twins",
    "PAR502": "shared-constant drift between kernel twins",
    "PAR503": "dtype-literal drift between kernel twins",
    "PAR504": "tie-break discipline drift between kernel twins",
    "PAR505": "state-field inventory drift between kernel twins",
    "PAR506": "malformed or unknown parity anchor",
}

# ints below this magnitude are structural (axis numbers, small offsets),
# not shared semantic constants; non-integral floats always count
_SIG_INT_MIN = 1024

_TIEBREAK_OPS = ("argmin", "argmax", "searchsorted", "cumsum")
_DTYPE_NAMES = {
    "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64", "uint8", "bool_",
}
_DTYPE_BUILTINS = {"bool", "int", "float"}

_ANCHOR_RE = re.compile(r"(?:#|//)\s*parity:\s*(.*?)\s*$")
_SLUG_RE = re.compile(r"^[a-z0-9][a-z0-9_-]*$")


@dataclass
class Skeleton:
    """One twin's semantic skeleton. Element maps carry the line each
    element was first seen at, for finding locations."""

    name: str
    path: str
    line: int = 0  # kernel def line (python) / first anchor line (C++)
    phases: List[Tuple[str, int]] = field(default_factory=list)
    consts: Dict[str, int] = field(default_factory=dict)  # canon value -> line
    dtypes: Dict[str, int] = field(default_factory=dict)
    tiebreaks: Dict[str, int] = field(default_factory=dict)
    state_fields: Dict[str, int] = field(default_factory=dict)

    def phase_slugs(self) -> List[str]:
        return [slug for slug, _ in self.phases]


def _canon_const(value) -> Optional[str]:
    """Canonical comparison key for a numeric constant, or None when the
    value is insignificant (small structural int) or non-finite."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    if isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            return None
        if value == int(value):  # integral float: same significance rule
            value = int(value)
        else:
            return repr(value)
    if abs(value) < _SIG_INT_MIN:
        return None
    return repr(value)


def _eval_const_expr(node: ast.AST, table: Dict[str, object]):
    """Restricted constant-expression evaluator: literals, +,-,*,**,//, /,
    unary minus, and names resolved through ``table``. Raises ValueError
    on anything else; arithmetic on admissible operands may still raise
    ArithmeticError (``1/0``, ``10.0**400``) — callers catch both."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        if isinstance(node.value, bool):
            raise ValueError("bool")
        return node.value
    if isinstance(node, ast.Name):
        if node.id in table:
            return table[node.id]
        raise ValueError(f"unknown name {node.id!r}")
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_eval_const_expr(node.operand, table)
    if isinstance(node, ast.BinOp):
        left = _eval_const_expr(node.left, table)
        right = _eval_const_expr(node.right, table)
        if isinstance(node.op, ast.Pow):
            # bound the exponent: `2**2**30` must not hang the analyzer
            if not isinstance(right, (int, float)) or abs(right) > 256:
                raise ValueError("exponent out of range")
            return left ** right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.FloorDiv):
            return left // right
        if isinstance(node.op, ast.Div):
            return left / right
    raise ValueError(ast.dump(node))


def _module_const_table(tree: ast.Module) -> Dict[str, object]:
    """{name: value} for top-level ``NAME = <const expr>`` assigns."""
    table: Dict[str, object] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        try:
            table[target.id] = _eval_const_expr(node.value, table)
        except (ValueError, ArithmeticError):
            continue
    return table


# ---------------------------------------------------------------------------
# Python-side extraction
# ---------------------------------------------------------------------------


def _transitive_helpers(
    kernel: ast.FunctionDef, functions: Dict[str, ast.FunctionDef]
) -> List[ast.FunctionDef]:
    """The kernel plus every same-module function it (transitively)
    references — shared helpers like spread_domain_choice contribute their
    constants/ops to every caller's skeleton."""
    seen = {kernel.name}
    order = [kernel]
    frontier = [kernel]
    while frontier:
        fn = frontier.pop()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in functions and node.id not in seen:
                    seen.add(node.id)
                    order.append(functions[node.id])
                    frontier.append(functions[node.id])
    return order


def _collect_phase_anchors(
    src: SourceFile, start: int, end: int
) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for i in range(start, min(end, len(src.lines)) + 1):
        m = _ANCHOR_RE.search(src.lines[i - 1])
        if m and m.group(1).startswith("phase"):
            parts = m.group(1).split(None, 1)
            if len(parts) == 2:
                out.append((parts[1].strip(), i))
    return out


def _state_class_fields(tree: ast.Module, state_class: str) -> Dict[str, int]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == state_class:
            fields: Dict[str, int] = {}
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name
                ):
                    fields[item.target.id] = item.lineno
            return fields
    return {}


def _extract_python_skeleton(
    name: str,
    path: str,
    src: SourceFile,
    tree: ast.Module,
    kernel: ast.FunctionDef,
    functions: Dict[str, ast.FunctionDef],
    declared_fields: Dict[str, int],
    aliases: Dict[str, str],
    const_table: Dict[str, object],
) -> Skeleton:
    sk = Skeleton(name=name, path=path, line=kernel.lineno)
    end = getattr(kernel, "end_lineno", kernel.lineno) or kernel.lineno
    sk.phases = _collect_phase_anchors(src, kernel.lineno, end)

    for fn in _transitive_helpers(kernel, functions):
        for node in ast.walk(fn):
            # consts: literals (incl. 2**30-style expressions) and
            # module-constant names
            if isinstance(node, (ast.Constant, ast.BinOp)):
                try:
                    value = _eval_const_expr(node, const_table)
                except (ValueError, ArithmeticError):
                    value = None
                key = _canon_const(value) if value is not None else None
                if key is not None:
                    sk.consts.setdefault(key, node.lineno)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in const_table:
                    key = _canon_const(const_table[node.id])
                    if key is not None:
                        sk.consts.setdefault(key, node.lineno)
            # dtypes: jnp.float32 / dtype=bool style references
            if isinstance(node, ast.Attribute) and node.attr in _DTYPE_NAMES:
                sk.dtypes.setdefault(node.attr.rstrip("_"), node.lineno)
            if isinstance(node, ast.Call):
                cname = call_name(node, aliases)
                tail = cname.rpartition(".")[2]
                if tail in _TIEBREAK_OPS and (
                    cname.startswith("jax.") or "." not in cname
                ):
                    sk.tiebreaks.setdefault(tail, node.lineno)
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Name)
                        and kw.value.id in _DTYPE_BUILTINS
                    ):
                        sk.dtypes.setdefault(kw.value.id, node.lineno)
                # bare bool/float/int in a constructor's dtype slot
                if tail in ("zeros", "ones", "empty", "full", "arange"):
                    slot = 2 if tail == "full" else 1
                    if len(node.args) > slot:
                        arg = node.args[slot]
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in _DTYPE_BUILTINS
                        ):
                            sk.dtypes.setdefault(arg.id, node.lineno)
            # state fields: attribute loads + constructor/_replace kwargs
            if isinstance(node, ast.Attribute) and node.attr in declared_fields:
                sk.state_fields.setdefault(node.attr, node.lineno)
            if isinstance(node, ast.Call):
                fname = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else getattr(node.func, "id", "")
                )
                if fname == "_replace" or fname in ("PackState",):
                    for kw in node.keywords:
                        if kw.arg in declared_fields:
                            sk.state_fields.setdefault(kw.arg, node.lineno)
    return sk


# ---------------------------------------------------------------------------
# C++-side extraction (anchor lexer)
# ---------------------------------------------------------------------------


def extract_cc_skeleton(
    path: str, text: Optional[str] = None
) -> Tuple[Skeleton, List[Finding], SourceFile]:
    """Lex ``// parity:`` anchors out of a C++ source. Malformed anchors
    become PAR506 findings, never crashes."""
    if text is None:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    src = SourceFile(path=path, text=text)
    sk = Skeleton(name="native", path=path)
    findings: List[Finding] = []

    def malformed(lineno: int, why: str) -> None:
        findings.append(
            Finding(
                "PAR506", Severity.ERROR, path, lineno,
                f"malformed parity anchor ({why}); expected "
                "'// parity: phase|const|dtype|tiebreak|state <arg>'",
            )
        )

    for i, line in enumerate(src.lines, start=1):
        m = _ANCHOR_RE.search(line)
        if not m:
            continue
        if sk.line == 0:
            sk.line = i
        body = m.group(1)
        parts = body.split(None, 1)
        if not parts:
            malformed(i, "empty anchor")
            continue
        kind = parts[0]
        arg = parts[1].strip() if len(parts) > 1 else ""
        if not arg:
            malformed(i, f"'{kind}' anchor has no argument")
            continue
        if kind == "phase":
            if not _SLUG_RE.match(arg):
                malformed(i, f"phase slug {arg!r} is not a slug")
                continue
            sk.phases.append((arg, i))
        elif kind == "const":
            try:
                # optional "name =" prefix: `const kBigDom = 2**28`
                expr = arg.rpartition("=")[2].strip() if "=" in arg else arg
                value = _eval_const_expr(ast.parse(expr, mode="eval").body, {})
            except (ValueError, SyntaxError, ArithmeticError):
                # ZeroDivisionError/OverflowError from `1/0`, `10.0**400`
                malformed(i, f"unevaluable const expression {arg!r}")
                continue
            key = _canon_const(value)
            if key is None:
                malformed(i, f"const {arg!r} is not a significant constant")
                continue
            sk.consts.setdefault(key, i)
        elif kind == "dtype":
            sk.dtypes.setdefault(arg.rstrip("_"), i)
        elif kind == "tiebreak":
            if not _SLUG_RE.match(arg):
                malformed(i, f"tiebreak slug {arg!r} is not a slug")
                continue
            sk.tiebreaks.setdefault(arg, i)
        elif kind == "state":
            for fld in (f.strip() for f in arg.split(",")):
                if not fld:
                    continue
                if not _SLUG_RE.match(fld):
                    malformed(i, f"state field {fld!r} is not an identifier")
                    continue
                sk.state_fields.setdefault(fld, i)
        else:
            malformed(i, f"unknown anchor kind {kind!r}")
    return sk, findings, src


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------


def _compare(
    ref: Skeleton, other: Skeleton, findings: List[Finding]
) -> None:
    if ref.phase_slugs() != other.phase_slugs():
        line = other.phases[0][1] if other.phases else other.line
        findings.append(
            Finding(
                "PAR501", Severity.ERROR, other.path, line,
                f"phase sequence drift: {ref.name}="
                f"{ref.phase_slugs()} vs {other.name}={other.phase_slugs()}",
            )
        )
    for label, rule in (
        ("consts", "PAR502"), ("dtypes", "PAR503"), ("tiebreaks", "PAR504")
    ):
        ref_map: Dict[str, int] = getattr(ref, label)
        other_map: Dict[str, int] = getattr(other, label)
        noun = label.rstrip("s").replace("const", "constant")
        for key in sorted(set(ref_map) - set(other_map)):
            findings.append(
                Finding(
                    rule, Severity.ERROR, other.path, other.line,
                    f"{noun} {key} present in {ref.name} but absent from "
                    f"{other.name} — a change may have landed in only one "
                    "twin",
                )
            )
        for key in sorted(set(other_map) - set(ref_map)):
            findings.append(
                Finding(
                    rule, Severity.ERROR, other.path, other_map[key],
                    f"{noun} {key} in {other.name} has no twin in "
                    f"{ref.name}",
                )
            )


def _check_state_fields(
    sk: Skeleton, declared: Dict[str, int], declared_path: str,
    findings: List[Finding],
) -> None:
    for fld in sorted(set(declared) - set(sk.state_fields)):
        findings.append(
            Finding(
                "PAR505", Severity.ERROR, sk.path, sk.line,
                f"state field '{fld}' declared by PackState is never "
                f"carried by {sk.name}",
            )
        )
    for fld in sorted(set(sk.state_fields) - set(declared)):
        findings.append(
            Finding(
                "PAR505", Severity.ERROR, sk.path, sk.state_fields[fld],
                f"state field '{fld}' in {sk.name} has no PackState twin "
                f"in {declared_path} (stale after a rename?)",
            )
        )


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def check_parity(
    py_path: str,
    cc_path: str,
    kernels: Sequence[str] = ("pack", "pack_classed"),
    state_class: str = "PackState",
) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Extract one skeleton per twin and report every divergence. The first
    kernel name is the reference twin the others are compared against."""
    findings: List[Finding] = []
    sources: Dict[str, SourceFile] = {}

    try:
        src, tree = parse_file(py_path)
    except (OSError, SyntaxError) as exc:
        return (
            [Finding("PAR500", Severity.ERROR, py_path, 0, f"unparsable: {exc}")],
            sources,
        )
    sources[py_path] = src
    aliases = import_aliases(tree)
    const_table = _module_const_table(tree)
    functions = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }
    declared = _state_class_fields(tree, state_class)
    if not declared:
        findings.append(
            Finding(
                "PAR500", Severity.ERROR, py_path, 0,
                f"state class {state_class!r} not found — cannot build the "
                "state-field inventory",
            )
        )

    skeletons: List[Skeleton] = []
    for kname in kernels:
        fn = functions.get(kname)
        if fn is None:
            findings.append(
                Finding(
                    "PAR500", Severity.ERROR, py_path, 0,
                    f"kernel {kname!r} not found in {py_path}",
                )
            )
            continue
        sk = _extract_python_skeleton(
            kname, py_path, src, tree, fn, functions, declared, aliases,
            const_table,
        )
        if not sk.phases:
            findings.append(
                Finding(
                    "PAR500", Severity.ERROR, py_path, fn.lineno,
                    f"kernel {kname!r} declares no '# parity: phase' "
                    "anchors — the phase sequence cannot be compared",
                )
            )
        skeletons.append(sk)

    cc_sk = None
    try:
        cc_sk, cc_findings, cc_src = extract_cc_skeleton(cc_path)
        sources[cc_path] = cc_src
        findings.extend(cc_findings)
        if cc_sk.line == 0:
            findings.append(
                Finding(
                    "PAR500", Severity.ERROR, cc_path, 0,
                    "no '// parity:' anchors found — the native twin "
                    "declares no skeleton",
                )
            )
            cc_sk = None
    except OSError as exc:
        findings.append(
            Finding("PAR500", Severity.ERROR, cc_path, 0, f"unreadable: {exc}")
        )

    if skeletons:
        ref = skeletons[0]
        for other in skeletons[1:]:
            _compare(ref, other, findings)
        if cc_sk is not None:
            _compare(ref, cc_sk, findings)
    if declared:
        for sk in skeletons:
            _check_state_fields(sk, declared, py_path, findings)
        if cc_sk is not None:
            _check_state_fields(cc_sk, declared, py_path, findings)
    return findings, sources
