"""Shared AST plumbing for the analysis passes.

Nothing here imports the analyzed code — every pass works on parse trees
only, so intentionally-broken fixtures and accelerator-only modules are
safe to scan on any host.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from .findings import SourceFile


def parse_file(path: str) -> Tuple[SourceFile, ast.Module]:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    return SourceFile(path=path, text=text), ast.parse(text, filename=path)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted origin for module-level imports.

    ``import jax.numpy as jnp`` -> {'jnp': 'jax.numpy'};
    ``from jax import lax`` -> {'lax': 'jax.lax'};
    ``from .packing import pack`` -> {'pack': '.packing.pack'}.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname is not None:
                    out[alias.asname] = alias.name
                else:
                    # `import a.b` binds the name `a` to module `a`; mapping
                    # it to 'a.b' would make use-site resolution re-append
                    # the submodule ('a.b.b.urlopen') and silently miss
                    # every rule keyed on the dotted origin
                    head = alias.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom):
            prefix = ("." * node.level) + (node.module or "")
            for alias in node.names:
                out[alias.asname or alias.name] = f"{prefix}.{alias.name}"
    return out


def resolves_to(name: str, aliases: Dict[str, str], *origins: str) -> bool:
    """Does a dotted use-site name (e.g. 'jnp.cumsum' or 'jax.jit') start
    with any of the given canonical origins ('jax.numpy', 'jax')?"""
    if not name:
        return False
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    full = origin + ("." + rest if rest else "")
    for o in origins:
        if full == o or full.startswith(o + "."):
            return True
    return False


class FunctionIndex:
    """All function/method defs in a module, keyed by qualified name."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[str, Dict[str, ast.FunctionDef]] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                table: Dict[str, ast.FunctionDef] = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        table[item.name] = item
                self.methods[node.name] = table


def call_name(node: ast.Call, aliases: Dict[str, str]) -> str:
    """Canonical dotted name of the callee ('' when not a name chain)."""
    name = dotted_name(node.func)
    if name is None:
        return ""
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    return origin + ("." + rest if rest else "")
