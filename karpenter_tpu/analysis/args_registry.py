"""Pass 12: kernel-argument registry consistency (ARG12xx).

The solve kernel's 56-argument tuple is named once —
``solver/encode.py:SOLVE_ARG_NAMES`` — and then re-spelled on five more
surfaces that have no runtime link back to it:

- ``EncodedSnapshot.solve_args`` assembles the tuple (encode side);
- ``parallel/mesh.py:ARG_SPECS`` declares each position's mesh
  partition spec (the SHP6xx shard checks and the scenario axis read it);
- ``parallel/mesh.py:pad_args_for_mesh`` pads exactly the sharded
  positions so every sharded axis divides its mesh dim (the SHP604
  pow2/divisibility guarantee);
- ``native/__init__.py:solve_core_native`` unpacks the same prefix
  positionally for the C++ twin;
- ``solver/residency.py`` partitions the names into device-buffer
  delta classes (NODE_ROW/CROSS/GROUP/GCOUNT, NO_ROW_DELTA), and
  ``ops/solve.py`` picks the scenario-batched subset.

Adding an argument means editing all of them; nothing but convention
keeps them aligned, and a miss is a silent positional skew (the exact
drift class PAR5xx guards between the JAX and C++ kernel *bodies* —
this pass guards the *signatures*). A cross-module content parse
rebuilds every surface from the AST and diffs them against the
authority:

- ARG1201 — an argument missing from (or extra on) a surface:
  ARG_SPECS keys, the solve_args tuple, the native wrapper's
  parameters, or a scenario-batched name that isn't an argument at all.
- ARG1202 — a surface spells the arguments in a different order than
  SOLVE_ARG_NAMES (positional tuples make order part of the contract).
- ARG1203 — residency delta classes inconsistent: a class member that
  is not an argument, two classes claiming the same name, or a
  NO_ROW_DELTA entry outside GROUP_ARGS (row-delta suppression only
  means anything for group-class buffers).
- ARG1204 — a sharded ARG_SPECS entry without the matching
  ``pad_args_for_mesh`` pad (same axis index, same mesh dim), or a pad
  for a replicated entry: the shard-divisibility guarantee SHP604
  relies on would silently not hold for that argument.

Surfaces are detected by content in whatever file set the pass is given
(the fixture twins are tiny multi-file replicas); each check runs only
when both of its surfaces were found, so partial scans stay quiet
rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core.summaries import ModuleInfo, load_modules
from .findings import Finding, Severity, SourceFile

RULES = {
    "ARG1200": "unparsable file (kernel-arg registry pass)",
    "ARG1201": "kernel argument missing from a registry surface",
    "ARG1202": "registry surface orders arguments differently than SOLVE_ARG_NAMES",
    "ARG1203": "residency delta classes inconsistent with the argument registry",
    "ARG1204": "sharded ARG_SPECS entry without a matching mesh pad",
}

_RESIDENCY_SETS = ("NODE_ROW_ARGS", "CROSS_ARGS", "GROUP_ARGS",
                   "GCOUNT_ARGS", "NO_ROW_DELTA")
_SCENARIO_SETS = ("SCENARIO_BATCHED_ARGS", "SCENARIO_TOPO_BATCHED_ARGS")


class _Site:
    """One detected surface: where it lives plus its parsed content."""

    __slots__ = ("path", "line", "value")

    def __init__(self, path: str, line: int, value):
        self.path = path
        self.line = line
        self.value = value


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """A literal tuple/list of string constants, or None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for elt in node.elts:
        if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
            return None
        out.append(elt.value)
    return tuple(out)


def _str_set(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """frozenset({...}) / set literal of string constants, in source
    order (the order only matters for deterministic reporting)."""
    if isinstance(node, ast.Call):
        callee = node.func
        if (
            isinstance(callee, ast.Name)
            and callee.id in ("frozenset", "set")
            and len(node.args) == 1
        ):
            node = node.args[0]
        else:
            return None
    if isinstance(node, ast.Set):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return _str_tuple(node)


def _spec_entry(node: ast.AST) -> Optional[Tuple[Optional[str], ...]]:
    """One ARG_SPECS value: a tuple of None / axis-name references.
    Axis names are kept symbolically (the Name/Attribute tail)."""
    if not isinstance(node, ast.Tuple):
        return None
    out: List[Optional[str]] = []
    for elt in node.elts:
        if isinstance(elt, ast.Constant) and elt.value is None:
            out.append(None)
        elif isinstance(elt, (ast.Name, ast.Attribute)):
            tail = elt.attr if isinstance(elt, ast.Attribute) else elt.id
            out.append(tail)
        else:
            return None
    return tuple(out)


class _Surfaces:
    """Everything the file set declared, first definition wins (modules
    arrive in sorted-path order, so collisions resolve deterministically)."""

    def __init__(self):
        self.names: Optional[_Site] = None        # SOLVE_ARG_NAMES tuple
        self.specs: Optional[_Site] = None        # ARG_SPECS ordered dict
        self.pads: Optional[_Site] = None         # {name: (axis, dim_expr)}
        self.native: Optional[_Site] = None       # wrapper param order
        self.assemble: Optional[_Site] = None     # solve_args element order
        self.axis_consts: Dict[str, str] = {}     # AXIS_MODEL -> "model"
        self.residency: Dict[str, _Site] = {}     # set name -> members
        self.scenario: Dict[str, _Site] = {}      # tuple name -> names


def _scan_module(mod: ModuleInfo, out: _Surfaces) -> None:
    path = mod.path
    for node in ast.walk(mod.tree):
        target: Optional[str] = None
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            target, value = node.target.id, node.value
        if target is not None:
            line = node.lineno
            if target == "SOLVE_ARG_NAMES" and out.names is None:
                names = _str_tuple(value)
                if names is not None:
                    out.names = _Site(path, line, names)
            elif target == "ARG_SPECS" and out.specs is None:
                specs = _parse_specs(value)
                if specs is not None:
                    out.specs = _Site(path, line, specs)
            elif target.startswith("AXIS_") and isinstance(
                value, ast.Constant
            ) and isinstance(value.value, str):
                out.axis_consts.setdefault(target, value.value)
            elif target in _RESIDENCY_SETS and target not in out.residency:
                members = _str_set(value)
                if members is not None:
                    out.residency[target] = _Site(path, line, members)
            elif target in _SCENARIO_SETS and target not in out.scenario:
                names = _scenario_tuple(value, out)
                if names is not None:
                    out.scenario[target] = _Site(path, line, names)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == "pad_args_for_mesh" and out.pads is None:
                out.pads = _Site(path, node.lineno, _parse_pads(node))
            elif node.name == "solve_core_native" and out.native is None:
                args = node.args
                params = tuple(
                    a.arg for a in args.posonlyargs + args.args
                )
                out.native = _Site(path, node.lineno, params)
            elif node.name == "solve_args" and out.assemble is None:
                elems = _parse_assembly(node)
                if elems is not None:
                    out.assemble = _Site(path, node.lineno, elems)


def _parse_specs(node: ast.AST) -> Optional[Dict[str, Tuple]]:
    if not isinstance(node, ast.Dict):
        return None
    specs: Dict[str, Tuple] = {}
    for key, value in zip(node.keys, node.values):
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
            return None
        entry = _spec_entry(value)
        if entry is None:
            return None
        specs[key.value] = entry
    return specs


def _scenario_tuple(node: ast.AST, out: _Surfaces) -> Optional[Tuple[str, ...]]:
    """A scenario-batched tuple, including the ``BASE + ("more",)``
    concatenation spelling (resolved against tuples already seen)."""
    direct = _str_tuple(node)
    if direct is not None:
        return direct
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = right = None
        if isinstance(node.left, ast.Name):
            site = out.scenario.get(node.left.id)
            left = site.value if site is not None else None
        else:
            left = _str_tuple(node.left)
        right = _str_tuple(node.right)
        if left is not None and right is not None:
            return left + right
    return None


def _parse_pads(fn: ast.AST) -> Dict[str, Tuple[int, str]]:
    """{arg name: (padded axis index, mesh-dim expression text)} from
    ``byname[...] = pad_axis(..., axis, dim)`` assignments — both the
    direct-subscript spelling and the for-loop-over-a-name-tuple one."""
    pads: Dict[str, Tuple[int, str]] = {}

    def pad_call(node: ast.AST) -> Optional[Tuple[int, str]]:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "pad_axis"
                and len(node.args) >= 3):
            return None
        axis = node.args[1]
        if not (isinstance(axis, ast.Constant) and isinstance(axis.value, int)):
            return None
        dim = node.args[2]
        dim_text = dim.id if isinstance(dim, ast.Name) else ""
        return axis.value, dim_text

    def record(name: str, call) -> None:
        if call is not None and name not in pads:
            pads[name] = call

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Subscript):
            sub = node.targets[0].slice
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                record(sub.value, pad_call(node.value))
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            names = _str_tuple(node.iter)
            if names is None:
                continue
            loop_var = node.target.id
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Subscript):
                    sub = stmt.targets[0].slice
                    if isinstance(sub, ast.Name) and sub.id == loop_var:
                        call = pad_call(stmt.value)
                        for name in names:
                            record(name, call)
    return pads


def _parse_assembly(fn: ast.AST) -> Optional[Tuple[str, ...]]:
    """Element names of the solve_args return tuple: ``self.x`` -> x,
    a bare parameter name -> itself. Any other element shape means the
    surface is not the assembly we know how to diff — skip it."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Tuple):
            out = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Attribute) and \
                        isinstance(elt.value, ast.Name) and \
                        elt.value.id == "self":
                    out.append(elt.attr)
                elif isinstance(elt, ast.Name):
                    out.append(elt.id)
                else:
                    return None
            return tuple(out)
    return None


def _order_diff(canon: Tuple[str, ...], other: Tuple[str, ...]) -> Optional[str]:
    """First order disagreement between ``other`` and ``canon`` restricted
    to their common names, rendered for the message; None when aligned."""
    common = set(canon) & set(other)
    want = [n for n in canon if n in common]
    got = [n for n in other if n in common]
    for w, g in zip(want, got):
        if w != g:
            return f"expected {w!r} here, found {g!r}"
    return None


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the kernel-arg registry pass; returns (findings, sources)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("ARG1200", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    surfaces = _Surfaces()
    for mod in modules.values():
        _scan_module(mod, surfaces)

    names_site = surfaces.names
    if names_site is None:
        return findings, sources  # no authority in scope: nothing to diff
    canon = names_site.value
    canon_set = set(canon)

    def flag(rule: str, site: _Site, message: str) -> None:
        findings.append(
            Finding(rule, Severity.ERROR, site.path, site.line, message)
        )

    # -- ARG_SPECS: full key parity + order ------------------------------
    if surfaces.specs is not None:
        site = surfaces.specs
        keys = tuple(site.value.keys())
        for name in canon:
            if name not in site.value:
                flag("ARG1201", site,
                     f"ARG_SPECS has no partition spec for {name!r}; every "
                     "SOLVE_ARG_NAMES position needs one (replicated = ())")
        for name in keys:
            if name not in canon_set:
                flag("ARG1201", site,
                     f"ARG_SPECS entry {name!r} is not a SOLVE_ARG_NAMES "
                     "argument (stale key after a rename?)")
        diff = _order_diff(canon, keys)
        if diff is not None:
            flag("ARG1202", site,
                 f"ARG_SPECS key order diverges from SOLVE_ARG_NAMES "
                 f"({diff}); keep the table in tuple order so positional "
                 "reviews stay 1:1")

    # -- solve_args assembly: exact sequence -----------------------------
    if surfaces.assemble is not None:
        site = surfaces.assemble
        elems = site.value
        for name in canon:
            if name not in elems:
                flag("ARG1201", site,
                     f"solve_args never assembles {name!r}; the kernel "
                     "will read a shifted position for every later arg")
        for name in elems:
            if name not in canon_set:
                flag("ARG1201", site,
                     f"solve_args assembles {name!r}, which "
                     "SOLVE_ARG_NAMES does not name")
        diff = _order_diff(canon, elems)
        if diff is not None:
            flag("ARG1202", site,
                 f"solve_args tuple order diverges from SOLVE_ARG_NAMES "
                 f"({diff}); positional consumers (kernel, padding, "
                 "scenario axes) all read this order")

    # -- native wrapper: prefix parity + order ---------------------------
    if surfaces.native is not None:
        site = surfaces.native
        params = site.value
        param_set = set(params)
        for name in canon:
            if name not in param_set:
                flag("ARG1201", site,
                     f"solve_core_native has no parameter {name!r}; the "
                     "C++ twin's positional unpack skews from there on")
        diff = _order_diff(canon, params)
        if diff is not None:
            flag("ARG1202", site,
                 f"solve_core_native parameter order diverges from "
                 f"SOLVE_ARG_NAMES ({diff})")

    # -- residency delta classes -----------------------------------------
    classes = [
        (n, surfaces.residency[n])
        for n in ("NODE_ROW_ARGS", "CROSS_ARGS", "GROUP_ARGS", "GCOUNT_ARGS")
        if n in surfaces.residency
    ]
    for cname, site in classes:
        for member in site.value:
            if member not in canon_set:
                flag("ARG1203", site,
                     f"{cname} member {member!r} is not a SOLVE_ARG_NAMES "
                     "argument; its device buffer would never be staged")
    for i, (a_name, a_site) in enumerate(classes):
        for b_name, b_site in classes[i + 1:]:
            both = sorted(set(a_site.value) & set(b_site.value))
            for member in both:
                flag("ARG1203", a_site,
                     f"{member!r} is claimed by both {a_name} and "
                     f"{b_name}; delta classes must partition the args")
    if "NO_ROW_DELTA" in surfaces.residency and \
            "GROUP_ARGS" in surfaces.residency:
        nrd = surfaces.residency["NO_ROW_DELTA"]
        group = set(surfaces.residency["GROUP_ARGS"].value)
        for member in nrd.value:
            if member not in group:
                flag("ARG1203", nrd,
                     f"NO_ROW_DELTA entry {member!r} is not in GROUP_ARGS; "
                     "row-delta suppression only applies to group-class "
                     "buffers")

    # -- scenario-batched subsets ----------------------------------------
    for sname, site in sorted(surfaces.scenario.items()):
        for member in site.value:
            if member not in canon_set:
                flag("ARG1201", site,
                     f"{sname} batches {member!r}, which is not a "
                     "SOLVE_ARG_NAMES argument; its vmap axis would bind "
                     "to nothing")

    # -- sharded specs vs the mesh pads (the SHP604 guarantee) -----------
    if surfaces.specs is not None and surfaces.pads is not None:
        specs_site = surfaces.specs
        pads_site = surfaces.pads
        pads = pads_site.value
        for name, spec in specs_site.value.items():
            sharded = [
                (i, axis) for i, axis in enumerate(spec) if axis is not None
            ]
            if sharded:
                if name not in pads:
                    flag("ARG1204", pads_site,
                         f"{name!r} is sharded in ARG_SPECS but "
                         "pad_args_for_mesh never pads it; its axis is "
                         "not guaranteed to divide the mesh dim (SHP604)")
                    continue
                pad_axis_idx, dim_text = pads[name]
                want = [i for i, _ in sharded]
                if pad_axis_idx not in want:
                    flag("ARG1204", pads_site,
                         f"{name!r} is padded on axis {pad_axis_idx} but "
                         f"ARG_SPECS shards axis {want[0]}; the pad "
                         "protects the wrong dimension")
                else:
                    axis_name = dict(sharded)[pad_axis_idx]
                    axis_value = surfaces.axis_consts.get(axis_name)
                    if axis_value and dim_text and dim_text != axis_value:
                        flag("ARG1204", pads_site,
                             f"{name!r} pads to a multiple of "
                             f"{dim_text!r} but is sharded on the "
                             f"{axis_value!r} mesh axis")
            elif name in pads:
                flag("ARG1204", pads_site,
                     f"{name!r} is padded in pad_args_for_mesh but "
                     "replicated in ARG_SPECS; one of the two is stale")
    return findings, sources
