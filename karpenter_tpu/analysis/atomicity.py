"""Pass: atomicity and tree-wide lock order (ATM14xx).

Two hazards the per-class guarded-by inference (guarded.py) cannot see:

- **ATM1401 check-then-act across a lock release.** A guarded read binds
  a local, the lock is released, the local feeds a branch decision, and
  the branch re-acquires the same lock to write the same attribute. The
  window between release and re-acquire is the classic lost-update
  shape (the adaptive ``nmax_hint`` bug class): another thread's write
  lands in the gap and the late writer clobbers it. The fix is either
  one critical section or a commutative merge (``max``/CAS) computed
  UNDER the second lock.
- **ATM1402 interprocedural lock-order cycles across modules.** The
  locks pass (LCK201) claims cycles whose locks live in one module; this
  pass runs the SAME held-set symbolic walk (locks.build_analyzer) over
  the whole threaded tree and claims the complementary population —
  acquisition cycles threading through ≥2 modules (EncodeCache →
  residency → queue edges), which a store-local scan can never connect.

Both ride the PR-16 call-graph core: one ``load_modules`` parse feeds
the locks-pass walk, and the ATM1401 scan reuses its lock-identity
resolution (``expr_lock``) so ``self._cv``/inherited locks resolve the
same way everywhere.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core.summaries import load_modules
from .findings import Finding, Severity, SourceFile
from .locks import _Analyzer, _ClassInfo, _File, _short, build_analyzer

RULES = {
    "ATM1400": "unparsable file (atomicity pass)",
    "ATM1401": "check-then-act split across a lock release "
               "(lost-update window)",
    "ATM1402": "interprocedural lock-order cycle across modules",
}

_MUTATORS = frozenset({
    "append", "add", "clear", "pop", "popitem", "update", "setdefault",
    "remove", "extend", "discard", "insert", "popleft", "appendleft",
})


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _attr_reads(node: ast.AST) -> Set[str]:
    """`self.attr` loads anywhere under ``node`` (bare or subscripted)."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        attr = _self_attr(sub)
        if attr is not None:
            out.add(attr)
    return out


def _attr_writes(stmts: Sequence[ast.stmt]) -> Dict[str, int]:
    """attr -> first write line for writes inside ``stmts``: assignments
    to ``self.attr``/``self.attr[k]`` and mutator method calls."""
    out: Dict[str, int] = {}

    def note(attr: Optional[str], line: int) -> None:
        if attr is not None and attr not in out:
            out[attr] = line

    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    note(_self_attr(target), node.lineno)
                    if isinstance(target, (ast.Subscript, ast.Attribute)):
                        note(_self_attr(target.value), node.lineno)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                note(_self_attr(node.target), node.lineno)
                if isinstance(node.target, (ast.Subscript, ast.Attribute)):
                    note(_self_attr(node.target.value), node.lineno)
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in _MUTATORS:
                note(_self_attr(node.func.value), node.lineno)
    return out


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _CheckThenAct:
    """Per-method linear scan for the ATM1401 shape."""

    def __init__(self, analyzer: _Analyzer, findings: List[Finding]):
        self.analyzer = analyzer
        self.findings = findings

    def scan_method(self, file: _File, cls: _ClassInfo,
                    fn: ast.FunctionDef) -> None:
        self._scan_seq(file, cls, fn.body, tainted={})

    def _with_lock(self, stmt: ast.With, file: _File,
                   cls: _ClassInfo) -> Optional[str]:
        for item in stmt.items:
            info = self.analyzer.expr_lock(item.context_expr, file, cls)
            if info is not None:
                return info.ident
        return None

    def _scan_seq(self, file: _File, cls: _ClassInfo,
                  stmts: Sequence[ast.stmt],
                  tainted: Dict[str, Tuple[str, str, int]]) -> None:
        """``tainted`` maps a local name to (lock ident, attr, read line)
        for locals bound from a guarded read whose lock has since been
        released."""
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                lock = self._with_lock(stmt, file, cls)
                if lock is not None:
                    # harvest locals bound from guarded reads; the lock
                    # releases when this block ends
                    for inner in stmt.body:
                        if isinstance(inner, ast.Assign) and \
                                len(inner.targets) == 1 and \
                                isinstance(inner.targets[0], ast.Name):
                            reads = _attr_reads(inner.value)
                            if reads:
                                attr = sorted(reads)[0]
                                tainted[inner.targets[0].id] = (
                                    lock, attr, inner.lineno
                                )
                    self._scan_seq(file, cls, stmt.body, dict(tainted))
                    continue
                self._scan_seq(file, cls, stmt.body, tainted)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                test_names = _names_in(stmt.test)
                hits = {
                    name: info for name, info in tainted.items()
                    if name in test_names
                }
                if hits:
                    self._flag_reacquire(file, cls, stmt, hits)
                for children in (stmt.body, stmt.orelse):
                    self._scan_seq(file, cls, children, dict(tainted))
                continue
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                # rebinding a tainted local severs the taint
                tainted.pop(stmt.targets[0].id, None)
            for attr_name in ("body", "orelse", "finalbody"):
                children = getattr(stmt, attr_name, None)
                if children:
                    self._scan_seq(file, cls, children, dict(tainted))
            for handler in getattr(stmt, "handlers", []) or []:
                self._scan_seq(file, cls, handler.body, dict(tainted))

    def _flag_reacquire(
        self, file: _File, cls: _ClassInfo, branch: ast.stmt,
        hits: Dict[str, Tuple[str, str, int]],
    ) -> None:
        """A branch decided by a stale guarded read: flag any with-block
        inside it that re-acquires the same lock and writes the read
        attribute."""
        for node in ast.walk(branch):
            if not isinstance(node, ast.With):
                continue
            lock = self._with_lock(node, file, cls)
            if lock is None:
                continue
            writes = _attr_writes(node.body)
            for local, (t_lock, attr, read_line) in sorted(hits.items()):
                if t_lock == lock and attr in writes:
                    self.findings.append(
                        Finding(
                            "ATM1401", Severity.ERROR, file.path,
                            node.lineno,
                            f"check-then-act on self.{attr}: read under "
                            f"{_short(lock)} at line {read_line} into "
                            f"'{local}', decision taken after release, "
                            "write re-acquires the lock — another "
                            "thread's update in the gap is lost; merge "
                            "into one critical section or recompute "
                            "under the second lock",
                        )
                    )


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the atomicity pass; returns (findings, sources)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("ATM1400", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    # tree-wide acquisition graph: the locks-pass walk, cross-module
    # cycles claimed here (module-local ones are LCK201's)
    analyzer = build_analyzer(modules)
    analyzer.findings = []  # drop the walk's LCK202/LCK203 (locks' beat)
    analyzer.detect_cycles(rule="ATM1402", cross_module_only=True)
    findings.extend(analyzer.findings)

    cta = _CheckThenAct(analyzer, findings)
    for f in analyzer.files:
        for cls in f.classes.values():
            if not any(c.locks for c in analyzer.mro(cls)):
                continue
            for mname, method in cls.methods.items():
                if mname != "__init__":
                    cta.scan_method(f, cls, method)

    unique: Dict[Tuple[str, str, int], Finding] = {}
    for finding in findings:
        unique.setdefault((finding.rule, finding.path, finding.line), finding)
    return list(unique.values()), sources
