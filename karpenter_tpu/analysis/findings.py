"""Finding type, severities, and the inline-marker/baseline channels.

A finding is dropped from the gating set through one of three channels:

- an inline **suppression**::

      risky_call()  # analysis: ignore[LCK202] informer handlers are our own

  on the flagged line or the line directly above it — "the rule cannot
  see why this is safe here";

- an inline **sanction**::

      out = jax.device_get(raw)  # analysis: sanctioned[DTX906] decode boundary

  same placement, different meaning: the flagged operation is a
  *documented, audited boundary crossing* (a blessed host-sync point, a
  real-wall-time diagnostic). Sanctions are not suppressions — the CLI
  counts them separately, the device/clock passes treat the crossing as
  legitimate downstream, and PARITY.md's device-residency contract is
  the list of them. Widening the sanctioned set is a reviewed API
  change, not a lint chore;

- a **baseline** entry (hack/analysis_baseline.txt): tab-separated
  ``RULE<TAB>path<TAB>message``, matched line-number-insensitively so
  unrelated edits don't churn the file.

The stale-suppression audit (stale.py, CLI ``--prune-baseline``) flags
entries and markers in any channel that no longer match a produced
finding.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"


@dataclass(frozen=True)
class Finding:
    rule: str  # e.g. TRC101
    severity: str  # Severity.*
    path: str  # repo-relative when produced by the CLI
    line: int  # 1-based; 0 when the finding has no single line
    message: str

    def render(self) -> str:
        return f"{self.severity}[{self.rule}] {self.path}:{self.line}: {self.message}"

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)


# both comment dialects: `# analysis: ...` (Python) and `// analysis: ...`
# (the C++ kernel twin scanned by parity.py); `ignore` suppresses,
# `sanctioned` marks a documented boundary crossing
_MARKER_RE = re.compile(
    r"(?:#|//)\s*analysis:\s*(ignore|sanctioned)\[([A-Z0-9,\s]+)\]"
)
# real rule ids always carry a number (TRC101, DTX906, STALE001); bare
# uppercase words are documentation placeholders (`ignore[RULE]`), not
# markers — without this the stale audit flags its own docstrings
_RULE_ID_RE = re.compile(r"^[A-Z]+[0-9]+$")


@dataclass(frozen=True)
class Marker:
    """One inline marker as written: its line, dialect, and rule set."""

    line: int
    dialect: str  # "ignore" | "sanctioned"
    rules: frozenset

    def covers(self, line: int) -> bool:
        """A marker reaches its own line and the line below (so block
        statements like ``with`` can carry it above the flagged call)."""
        return line in (self.line, self.line + 1)


def inline_markers(source_lines: Sequence[str]) -> List[Marker]:
    out: List[Marker] = []
    for i, text in enumerate(source_lines, start=1):
        m = _MARKER_RE.search(text)
        if not m:
            continue
        rules = frozenset(
            r.strip()
            for r in m.group(2).split(",")
            if _RULE_ID_RE.match(r.strip())
        )
        if rules:
            out.append(Marker(line=i, dialect=m.group(1), rules=rules))
    return out


def inline_suppressions(source_lines: Sequence[str]) -> dict:
    """{line_number: {rules}} for every inline ignore marker (legacy
    view; sanctions not included)."""
    out: dict = {}
    for marker in inline_markers(source_lines):
        if marker.dialect != "ignore":
            continue
        out.setdefault(marker.line, set()).update(marker.rules)
        out.setdefault(marker.line + 1, set()).update(marker.rules)
    return out


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    entries: Set[Tuple[str, str, str]] = set()
    try:
        with open(path, encoding="utf-8") as fh:
            for raw in fh:
                line = raw.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                parts = line.split("\t", 2)
                if len(parts) == 3:
                    entries.add((parts[0], parts[1], parts[2]))
    except OSError:
        pass
    return entries


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    keys = sorted({f.baseline_key() for f in findings})
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(
            "# Static-analysis baseline: known findings tolerated by\n"
            "# `python -m karpenter_tpu.analysis`. One per line,\n"
            "# RULE<TAB>path<TAB>message. Regenerate with --write-baseline;\n"
            "# prefer inline `# analysis: ignore[RULE] reason` for findings\n"
            "# that are intentionally safe.\n"
        )
        for rule, fpath, message in keys:
            fh.write(f"{rule}\t{fpath}\t{message}\n")


@dataclass
class SourceFile:
    """Parsed-source handle shared by the passes (one read per file)."""

    path: str
    text: str
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.text.splitlines()
        self.markers: List[Marker] = inline_markers(self.lines)

    def _covered(self, line: int, rule: str, dialect: str) -> bool:
        return any(
            m.dialect == dialect and rule in m.rules and m.covers(line)
            for m in self.markers
        )

    def suppressed(self, line: int, rule: str) -> bool:
        return self._covered(line, rule, "ignore")

    def sanctioned(self, line: int, rule: str) -> bool:
        return self._covered(line, rule, "sanctioned")


def partition_findings(
    findings: Iterable[Finding],
    sources: Optional[dict] = None,
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
) -> Tuple[List[Finding], List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed, sanctioned).

    ``sources`` maps finding.path -> SourceFile (for inline markers).
    Suppressed covers baseline entries and inline ignores; sanctioned
    covers inline sanction markers (the documented boundary crossings).
    """
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    sanctioned: List[Finding] = []
    for f in findings:
        if baseline and f.baseline_key() in baseline:
            suppressed.append(f)
            continue
        src = (sources or {}).get(f.path)
        if src is not None and src.suppressed(f.line, f.rule):
            suppressed.append(f)
            continue
        if src is not None and src.sanctioned(f.line, f.rule):
            sanctioned.append(f)
            continue
        kept.append(f)
    return kept, suppressed, sanctioned


def filter_suppressed(
    findings: Iterable[Finding],
    sources: Optional[dict] = None,
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
) -> List[Finding]:
    """Findings not covered by an inline marker (either dialect) or the
    baseline."""
    return partition_findings(findings, sources, baseline)[0]
