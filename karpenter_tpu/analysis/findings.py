"""Finding type, severities, and the two suppression channels.

A finding is suppressed either by an inline marker::

    risky_call()  # analysis: ignore[LCK202] informer handlers are our own

on the flagged line or the line directly above it, or by a baseline entry
(hack/analysis_baseline.txt): tab-separated ``RULE<TAB>path<TAB>message``,
matched line-number-insensitively so unrelated edits don't churn the file.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Set, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"


@dataclass(frozen=True)
class Finding:
    rule: str  # e.g. TRC101
    severity: str  # Severity.*
    path: str  # repo-relative when produced by the CLI
    line: int  # 1-based; 0 when the finding has no single line
    message: str

    def render(self) -> str:
        return f"{self.severity}[{self.rule}] {self.path}:{self.line}: {self.message}"

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)


# both comment dialects: `# analysis: ignore[...]` (Python) and
# `// analysis: ignore[...]` (the C++ kernel twin scanned by parity.py)
_IGNORE_RE = re.compile(r"(?:#|//)\s*analysis:\s*ignore\[([A-Z0-9,\s]+)\]")


def inline_suppressions(source_lines: Sequence[str]) -> dict:
    """{line_number: {rules}} for every inline ignore marker. A marker
    suppresses its own line and the line below (so block statements like
    ``with`` can carry the marker above the flagged call)."""
    out: dict = {}
    for i, text in enumerate(source_lines, start=1):
        m = _IGNORE_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        out.setdefault(i + 1, set()).update(rules)
    return out


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    entries: Set[Tuple[str, str, str]] = set()
    try:
        with open(path, encoding="utf-8") as fh:
            for raw in fh:
                line = raw.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                parts = line.split("\t", 2)
                if len(parts) == 3:
                    entries.add((parts[0], parts[1], parts[2]))
    except OSError:
        pass
    return entries


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    keys = sorted({f.baseline_key() for f in findings})
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(
            "# Static-analysis baseline: known findings tolerated by\n"
            "# `python -m karpenter_tpu.analysis`. One per line,\n"
            "# RULE<TAB>path<TAB>message. Regenerate with --write-baseline;\n"
            "# prefer inline `# analysis: ignore[RULE] reason` for findings\n"
            "# that are intentionally safe.\n"
        )
        for rule, fpath, message in keys:
            fh.write(f"{rule}\t{fpath}\t{message}\n")


@dataclass
class SourceFile:
    """Parsed-source handle shared by the passes (one read per file)."""

    path: str
    text: str
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.text.splitlines()
        self._suppressions = inline_suppressions(self.lines)

    def suppressed(self, line: int, rule: str) -> bool:
        return rule in self._suppressions.get(line, ())


def filter_suppressed(
    findings: Iterable[Finding],
    sources: Optional[dict] = None,
    baseline: Optional[Set[Tuple[str, str, str]]] = None,
) -> List[Finding]:
    """Drop findings covered by inline markers or the baseline.

    ``sources`` maps finding.path -> SourceFile (for inline markers).
    """
    out: List[Finding] = []
    for f in findings:
        if baseline and f.baseline_key() in baseline:
            continue
        src = (sources or {}).get(f.path)
        if src is not None and src.suppressed(f.line, f.rule):
            continue
        out.append(f)
    return out
