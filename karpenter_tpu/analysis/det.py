"""Pass 11: order discipline (DET11xx) for the determinism surface.

PR 14's PYTHONHASHSEED cost drift was this bug class exactly:
``Vocab.observe`` iterated ``Requirement.values`` (a ``set``) in hash
order, so two processes interned the same zone names at different value
ids and every argmin tie-break over those ids diverged — caught only by
a full parity round, fixed by ``sorted(r.values)`` and pinned by a
six-seed two-process dynamic test. The dynamic pin can only sample; this
pass closes the class statically over the determinism surface
(``solver/``, ``ops/``, ``sim/``, ``obs/``).

A dataflow pass on the shared core: values born from **unordered
sources** are tracked through assignments, set algebra, and helper
returns (bottom-up over the module-set call graph, core.summaries), and
flagged when one reaches an **order-sensitive sink** without passing
through ``sorted()``/explicit canonicalization first. Everything the
analysis loses track of joins to UNKNOWN and never flags (the same
poison-to-unknown discipline as DTX9xx).

Unordered sources:

- ``set`` literals and set comprehensions, ``set()``/``frozenset()``
  calls, set-algebra results (``|``/``&``/``-``/``^``, ``.union()``...);
- attribute loads declared set-typed by an annotation the pass can see —
  class-body or ``self.x: Set[...]`` declarations across the scanned set
  PLUS the ``karpenter_tpu/api`` value-object modules (so
  ``r.values`` resolves through ``Requirement.values: Set[str]`` even
  when ``api/`` is outside the scan scope), with receivers typed from
  parameter annotations, constructor calls, and ``__iter__ ->
  Iterator[T]`` element chaining;
- ``os.environ`` (per-process environment order);
- ``dict(unordered)`` — the dict itself is insertion-stable (a language
  guarantee since 3.7, which is why plain dict views are NOT sources)
  but its insertion order inherits the set's hash order, so views and
  iteration over it stay tainted.

Order-sensitive sinks (flag only on *definite* UNORDERED):

- DET1101: ``for``-iteration — the iteration order escapes into
  whatever the body appends/interns/emits (the Vocab.observe shape);
- DET1102: order-fixing materialization — ``list()``/``tuple()``/
  ``enumerate()`` or a list comprehension over an unordered iterable;
- DET1103: ``.join()`` over an unordered iterable — a canonical-record
  string whose bytes depend on hash order;
- DET1104: an unseeded global-RNG draw (``random.*`` module functions,
  ``np.random.*`` legacy functions) — the decision surface must thread
  seeded ``np.random.default_rng(seed)``/``random.Random(seed)``
  instances so twin replays are byte-identical.

Order-insensitive consumption stays silent by construction:
membership tests (``x in s``), ``len``/``sum``/``min``/``max``/
``any``/``all`` reductions, and ``sorted()`` — the canonicalizer —
yields an ORDERED value. Deliberate unordered uses that the lattice
cannot prove commutative carry ``# analysis: sanctioned[DET...]``
boundary annotations (counted separately, stale-audited), mirroring the
CLK1001/DTX906 dialects.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .astutil import call_name, dotted_name
from .core.cfg import Atom, build_cfg
from .core.dataflow import Env, run_forward, sweep
from .core.lattice import Lattice
from .core.summaries import (
    ModuleInfo,
    SummaryTable,
    build_call_graph,
    load_modules,
    resolve_local,
)
from .findings import Finding, Severity, SourceFile

RULES = {
    "DET1100": "unparsable file (order-discipline pass)",
    "DET1101": "iteration over an unordered value (hash-order escape)",
    "DET1102": "order-fixing materialization of an unordered value",
    "DET1103": "join over an unordered value (hash-ordered record)",
    "DET1104": "unseeded global RNG on the determinism surface",
}

ORDERED = 0
UNORDERED = 1
UNKNOWN = 2  # poison: lost track -> never flag

LATTICE = Lattice(top=UNKNOWN, default=ORDERED)

# annotation heads that declare a set-typed attribute
_SET_ANNOTATIONS = {"set", "frozenset", "Set", "FrozenSet", "AbstractSet",
                    "MutableSet"}
# set methods that return another unordered set
_SET_PRODUCERS = {"union", "intersection", "difference",
                  "symmetric_difference", "copy"}
# dict views: ordered on an insertion-stable dict, tainted on a dict
# built from an unordered source (the receiver kind decides)
_DICT_VIEWS = {"keys", "values", "items"}
# commutative reductions: consuming a set through these is the sanctioned
# "counter" use and yields an order-free scalar
_REDUCERS = {"len", "sum", "min", "max", "any", "all", "bool", "sorted",
             "str", "repr", "int", "float", "abs"}
# order-fixing materializers (the DET1102 sinks)
_MATERIALIZERS = {"list", "tuple", "enumerate"}

# unseeded global-RNG draws. random.Random / np.random.default_rng /
# Generator / SeedSequence construct seeded instances and stay silent —
# instance method calls never canonicalize to these module paths.
_GLOBAL_RNG = {
    "random.random", "random.randint", "random.randrange", "random.choice",
    "random.choices", "random.shuffle", "random.sample", "random.uniform",
    "random.gauss", "random.seed", "random.getrandbits", "random.betavariate",
}
_NP_RNG_OK = {"default_rng", "Generator", "SeedSequence", "RandomState",
              "BitGenerator", "PCG64", "Philox"}


def _annotation_is_set(ann: ast.AST) -> Optional[bool]:
    """True/False when the annotation decides set-ness, None when it is
    unreadable (string forward refs to non-set types, unions...)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        head = ann.value.split("[", 1)[0].rpartition(".")[2]
        return head in _SET_ANNOTATIONS or None
    if isinstance(ann, ast.Subscript):
        head = dotted_name(ann.value)
        if head is not None:
            tail = head.rpartition(".")[2]
            if tail == "Optional":
                return _annotation_is_set(ann.slice)
            return tail in _SET_ANNOTATIONS
        return None
    head = dotted_name(ann)
    if head is None:
        return None
    return head.rpartition(".")[2] in _SET_ANNOTATIONS


def _class_name_of(ann: ast.AST) -> Optional[str]:
    """Bare class name an annotation refers to ('Requirements' from
    ``Requirements`` / ``"Requirements"`` / ``mod.Requirements``)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split("[", 1)[0].rpartition(".")[2] or None
    name = dotted_name(ann)
    if name is None:
        return None
    return name.rpartition(".")[2]


class ClassTable:
    """Set-typed attribute declarations and iteration element types,
    collected from class defs across the scanned set plus the api/
    support modules. Name-keyed by bare class name; a redefinition
    merges conservatively (conflicting set-ness reads as unknown)."""

    def __init__(self):
        # class -> attr -> True (set) / False (not a set) / None (conflict)
        self.attrs: Dict[str, Dict[str, Optional[bool]]] = {}
        # class -> element class name from `__iter__ -> Iterator[T]`
        self.elem: Dict[str, str] = {}

    def add_module(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._add_class(node)

    def _add_class(self, cls: ast.ClassDef) -> None:
        table = self.attrs.setdefault(cls.name, {})

        def record(attr: str, is_set: Optional[bool]) -> None:
            if is_set is None:
                return
            if attr in table:
                if table[attr] is not None and table[attr] != is_set:
                    table[attr] = None  # conflicting declarations: unknown
            else:
                table[attr] = is_set

        for item in cls.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                item.target, ast.Name
            ):
                record(item.target.id, _annotation_is_set(item.annotation))
        for item in ast.walk(cls):
            if isinstance(item, ast.AnnAssign) and isinstance(
                item.target, ast.Attribute
            ):
                if (
                    isinstance(item.target.value, ast.Name)
                    and item.target.value.id == "self"
                ):
                    record(item.target.attr,
                           _annotation_is_set(item.annotation))
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if item.name == "__iter__" and item.returns is not None:
                    ret = item.returns
                    if isinstance(ret, ast.Subscript):
                        head = dotted_name(ret.value) or ""
                        if head.rpartition(".")[2] in ("Iterator", "Iterable"):
                            elem = _class_name_of(ret.slice)
                            if elem:
                                self.elem[cls.name] = elem

    def attr_is_set(self, cls: Optional[str], attr: str) -> Optional[bool]:
        if cls is None:
            return None
        return self.attrs.get(cls, {}).get(attr)


def _support_paths() -> List[str]:
    """The api/ value-object modules: always fed to the ClassTable (never
    scanned for findings) so Requirement-style attribute kinds resolve
    even when the scan scope is a single copied file (the static
    mutation test copies solver/vocab.py into a tmpdir)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    api = os.path.join(pkg, "api")
    if not os.path.isdir(api):
        return []
    return [
        os.path.join(api, name)
        for name in sorted(os.listdir(api))
        if name.endswith(".py")
    ]


def _var_types(
    fn_body: List[ast.stmt],
    params: Optional[ast.arguments],
    table: ClassTable,
    self_class: Optional[str],
) -> Dict[str, str]:
    """Flow-insensitive receiver typing: parameter annotations,
    constructor calls, AnnAssigns, and `for x in typed` element chaining
    (two rounds reach chains like reqs -> r)."""
    types: Dict[str, str] = {}
    if self_class:
        types["self"] = self_class
    if params is not None:
        for arg in params.posonlyargs + params.args + params.kwonlyargs:
            if arg.annotation is not None:
                cname = _class_name_of(arg.annotation)
                if cname and cname in table.attrs:
                    types[arg.arg] = cname
    for _ in range(2):
        for stmt in fn_body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target = node.targets[0]
                    if isinstance(target, ast.Name) and isinstance(
                        node.value, ast.Call
                    ):
                        callee = dotted_name(node.value.func)
                        if callee:
                            tail = callee.rpartition(".")[2]
                            if tail in table.attrs:
                                types[target.id] = tail
                elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name
                ):
                    cname = _class_name_of(node.annotation)
                    if cname and cname in table.attrs:
                        types[node.target.id] = cname
                elif isinstance(node, (ast.For, ast.comprehension)):
                    target = node.target
                    it = node.iter
                    if isinstance(target, ast.Name) and isinstance(
                        it, ast.Name
                    ):
                        src = types.get(it.id)
                        if src and src in table.elem:
                            types[target.id] = table.elem[src]
    return types


class _OrderAnalysis:
    """One function (or module body) under the order lattice."""

    def __init__(
        self,
        mod: ModuleInfo,
        modules: Dict[str, ModuleInfo],
        findings: List[Finding],
        summaries: Optional[SummaryTable],
        table: ClassTable,
        types: Dict[str, str],
    ):
        self.mod = mod
        self.modules = modules
        self.findings = findings
        self.summaries = summaries
        self.table = table
        self.types = types
        self._flagged: Set[Tuple[int, str]] = set()

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if (line, rule) in self._flagged:
            return
        self._flagged.add((line, rule))
        self.findings.append(
            Finding(rule, Severity.ERROR, self.mod.path, line, message)
        )

    # -- classification ---------------------------------------------------

    def kind(self, node: ast.AST, env: Env) -> int:
        if isinstance(node, ast.Constant):
            return ORDERED
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            name = dotted_name(node)
            if name is not None:
                head, _, rest = name.partition(".")
                origin = self.mod.aliases.get(head, head)
                if (origin + ("." + rest if rest else "")) == "os.environ":
                    return UNORDERED
            if isinstance(node.value, ast.Name):
                is_set = self.table.attr_is_set(
                    self.types.get(node.value.id), node.attr
                )
                if is_set is True:
                    return UNORDERED
                if is_set is False:
                    return ORDERED
            return UNKNOWN
        if isinstance(node, ast.Set):
            return UNORDERED
        if isinstance(node, ast.SetComp):
            return UNORDERED
        if isinstance(node, (ast.List, ast.Tuple, ast.Dict)):
            return ORDERED
        if isinstance(node, ast.ListComp):
            return ORDERED  # flagged as DET1102 at the check when tainted
        if isinstance(node, (ast.GeneratorExp, ast.DictComp)):
            # defers / inherits the generators' order
            return max(
                (self.kind(g.iter, env) for g in node.generators),
                default=ORDERED,
            )
        if isinstance(node, ast.Call):
            return self._call_kind(node, env)
        if isinstance(node, ast.NamedExpr):
            return self.kind(node.value, env)
        if isinstance(node, ast.BinOp):
            # set algebra (| & - ^) keeps the taint; scalar arithmetic is
            # ORDERED v ORDERED and joins clean
            return max(self.kind(node.left, env), self.kind(node.right, env))
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.Not):
                return ORDERED
            return self.kind(node.operand, env)
        if isinstance(node, ast.BoolOp):
            return max((self.kind(v, env) for v in node.values),
                       default=ORDERED)
        if isinstance(node, ast.Compare):
            return ORDERED  # membership tests are the sanctioned use
        if isinstance(node, ast.IfExp):
            return max(self.kind(node.body, env), self.kind(node.orelse, env))
        if isinstance(node, ast.Starred):
            return self.kind(node.value, env)
        if isinstance(node, ast.JoinedStr):
            return ORDERED
        if isinstance(node, ast.Lambda):
            return ORDERED
        return UNKNOWN

    def _call_kind(self, node: ast.Call, env: Env) -> int:
        cname = call_name(node, self.mod.aliases)
        arg0 = node.args[0] if node.args else None
        if cname in ("set", "frozenset"):
            return UNORDERED
        if cname == "sorted":
            return ORDERED  # THE canonicalizer
        if cname in _REDUCERS:
            return ORDERED
        if cname in _MATERIALIZERS:
            return ORDERED  # the sink check flags; result order is fixed
        if cname == "dict":
            # insertion order inherits an unordered source's hash order
            if arg0 is not None and self.kind(arg0, env) == UNORDERED:
                return UNORDERED
            return ORDERED
        if cname == "reversed" and arg0 is not None:
            return self.kind(arg0, env)
        if isinstance(node.func, ast.Attribute):
            recv = self.kind(node.func.value, env)
            if node.func.attr in _SET_PRODUCERS or node.func.attr in _DICT_VIEWS:
                return recv  # set algebra / dict views carry the receiver
            if node.func.attr == "add":
                return ORDERED
        # call-graph reach: a helper returning a set taints its caller
        raw = dotted_name(node.func)
        if (
            self.summaries is not None
            and raw is not None
            and "." not in raw
            and not env.has(raw)
        ):
            hit = resolve_local(self.mod, raw, self.modules)
            if hit is not None:
                return _return_kind(hit[0], hit[1], self)
        return UNKNOWN

    def _unordered_names(self, node: ast.AST, env: Env) -> str:
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and env.get(sub.id) == UNORDERED:
                if sub.id not in out:
                    out.append(sub.id)
            elif isinstance(sub, ast.Attribute):
                if self.kind(sub, env) == UNORDERED:
                    name = dotted_name(sub)
                    if name and name not in out:
                        out.append(name)
        return ", ".join(out) or "an unordered value"

    # -- transfer ---------------------------------------------------------

    def _bind_target(self, target: ast.AST, kind: int, env: Env) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, kind)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, UNKNOWN, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, kind, env)

    def _bind_walrus(self, node: ast.AST, env: Env) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.NamedExpr) and isinstance(
                sub.target, ast.Name
            ):
                env.set(sub.target.id, self.kind(sub.value, env))

    def transfer(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            self._bind_walrus(node, env)
            if isinstance(node, ast.Assign):
                kind = self.kind(node.value, env)
                for target in node.targets:
                    self._bind_target(target, kind, env)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind_target(
                    node.target, self.kind(node.value, env), env
                )
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    env.set(
                        node.target.id,
                        max(env.get(node.target.id),
                            self.kind(node.value, env)),
                    )
        elif atom.kind == "test":
            self._bind_walrus(node, env)
        elif atom.kind == "for":
            self._bind_walrus(node.iter, env)
            # elements of any iterable are scalar values; their own
            # order-ness is a fresh question
            self._bind_target(node.target, ORDERED, env)
        elif atom.kind == "with":
            self._bind_walrus(node.context_expr, env)
            if node.optional_vars is not None:
                self._bind_target(node.optional_vars, UNKNOWN, env)
        elif atom.kind == "except":
            if node.name:
                env.set(node.name, ORDERED)

    # -- checks -----------------------------------------------------------

    def check(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._check_expr(child, env)
        elif atom.kind == "test":
            self._check_expr(node, env)
        elif atom.kind == "for":
            if self.kind(node.iter, env) == UNORDERED:
                self._flag(
                    "DET1101", node,
                    f"iteration over unordered value(s) "
                    f"({self._unordered_names(node.iter, env)}) runs in "
                    "PYTHONHASHSEED order; wrap in sorted() so interned "
                    "ids / emitted records are content-ordered, or mark "
                    "the loop `# analysis: sanctioned[DET1101] reason` "
                    "if the body is provably commutative",
                )
            self._check_expr(node.iter, env)
        elif atom.kind == "with":
            self._check_expr(node.context_expr, env)
        elif atom.kind == "def":
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(
                    self.mod, node, self.findings, self.modules,
                    self.summaries, self.table, shared_flags=self._flagged,
                )
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        _check_function(
                            self.mod, item, self.findings, self.modules,
                            self.summaries, self.table,
                            self_class=node.name,
                            shared_flags=self._flagged,
                        )

    def _check_expr(self, node: ast.AST, env: Env) -> None:
        if isinstance(node, ast.Call):
            self._check_call(node, env)
        elif isinstance(node, ast.ListComp):
            for gen in node.generators:
                if self.kind(gen.iter, env) == UNORDERED:
                    self._flag(
                        "DET1102", node,
                        "list comprehension over unordered value(s) "
                        f"({self._unordered_names(gen.iter, env)}) "
                        "freezes an arbitrary hash order; iterate "
                        "sorted(...) instead",
                    )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword,
                                  ast.FormattedValue)):
                self._check_expr(child, env)

    def _check_call(self, node: ast.Call, env: Env) -> None:
        cname = call_name(node, self.mod.aliases)
        arg0 = node.args[0] if node.args else None
        if cname in _MATERIALIZERS and arg0 is not None:
            if self.kind(arg0, env) == UNORDERED:
                self._flag(
                    "DET1102", node,
                    f"{cname}() over unordered value(s) "
                    f"({self._unordered_names(arg0, env)}) freezes an "
                    "arbitrary hash order into an indexable sequence; "
                    "use sorted() to pin a content order",
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and arg0 is not None
            and self.kind(arg0, env) == UNORDERED
        ):
            self._flag(
                "DET1103", node,
                "join over unordered value(s) "
                f"({self._unordered_names(arg0, env)}) produces a "
                "hash-ordered record; canonical strings must join "
                "sorted(...)",
            )
        elif cname in _GLOBAL_RNG or (
            cname.startswith("numpy.random.")
            and cname.rpartition(".")[2] not in _NP_RNG_OK
        ):
            self._flag(
                "DET1104", node,
                f"{cname} draws from the unseeded global RNG; the "
                "determinism surface threads seeded "
                "np.random.default_rng(seed)/random.Random(seed) "
                "instances (twin replays must be byte-identical)",
            )


def _param_env(fn: ast.AST, base: Env) -> Env:
    """Parameters are UNKNOWN: the pass only flags values whose unordered
    origin it can actually see (poison-to-unknown)."""
    env = base
    args = fn.args
    for arg in args.posonlyargs + args.args + args.kwonlyargs:
        env.set(arg.arg, UNKNOWN)
    if args.vararg is not None:
        env.set(args.vararg.arg, UNKNOWN)
    if args.kwarg is not None:
        env.set(args.kwarg.arg, UNKNOWN)
    return env


def _return_kind(mod: ModuleInfo, fn: ast.FunctionDef,
                 caller: "_OrderAnalysis") -> int:
    """Call-graph return summary: does the helper hand back an unordered
    value? Bottom-up through the shared SummaryTable; recursive clusters
    read UNKNOWN by SCC collapse."""
    summaries = caller.summaries

    def compute() -> int:
        types = _var_types(fn.body, fn.args, caller.table, None)
        analysis = _OrderAnalysis(
            mod, caller.modules, [], summaries, caller.table, types
        )
        init = _param_env(fn, Env(LATTICE))
        cfg = build_cfg(fn.body)
        envs = run_forward(cfg, init, analysis.transfer)
        out = [ORDERED]

        def collect(atom: Atom, env: Env) -> None:
            if (
                atom.kind == "stmt"
                and isinstance(atom.node, ast.Return)
                and atom.node.value is not None
            ):
                out.append(analysis.kind(atom.node.value, env))

        sweep(cfg, envs, init, analysis.transfer, collect)
        return max(out)

    return summaries.get((mod.path, fn.name), compute)


def _check_function(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    findings: List[Finding],
    modules: Dict[str, ModuleInfo],
    summaries: Optional[SummaryTable],
    table: ClassTable,
    self_class: Optional[str] = None,
    shared_flags: Optional[Set[Tuple[int, str]]] = None,
) -> None:
    types = _var_types(fn.body, fn.args, table, self_class)
    analysis = _OrderAnalysis(mod, modules, findings, summaries, table, types)
    if shared_flags is not None:
        analysis._flagged = shared_flags
    init = _param_env(fn, Env(LATTICE))
    cfg = build_cfg(fn.body)
    envs = run_forward(cfg, init, analysis.transfer)
    sweep(cfg, envs, init, analysis.transfer, analysis.check)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the order-discipline pass; returns (findings, sources)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("DET1100", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    table = ClassTable()
    scanned = set(modules)
    for path in _support_paths():
        if path in scanned:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                table.add_module(ast.parse(fh.read(), filename=path))
        except (OSError, SyntaxError):
            continue  # support modules are best-effort, never findings
    for mod in modules.values():
        table.add_module(mod.tree)
    summaries = SummaryTable(default=UNKNOWN, graph=build_call_graph(modules))
    for mod in modules.values():
        types = _var_types(
            [s for s in mod.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))],
            None, table, None,
        )
        analysis = _OrderAnalysis(mod, modules, findings, summaries, table,
                                  types)
        init = Env(LATTICE)
        cfg = build_cfg(
            [s for s in mod.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))]
        )
        envs = run_forward(cfg, init, analysis.transfer)
        sweep(cfg, envs, init, analysis.transfer, analysis.check)
        for fn in mod.index.functions.values():
            _check_function(mod, fn, findings, modules, summaries, table)
        for cls_name, cls_table in mod.index.methods.items():
            for fn in cls_table.values():
                _check_function(mod, fn, findings, modules, summaries, table,
                                self_class=cls_name)
    return findings, sources
