"""Pass 3: blocking calls in reconcile paths.

Controllers (and the operator loop) are level-triggered and clock-injected:
tests drive a TestClock the way the reference's suites drive
clock.FakeClock, so a direct ``time.sleep``/``time.time`` both blocks the
reconcile thread for real wall-clock time AND desynchronizes from the
simulated clock. Blocking process/network I/O in a reconcile path has the
same shape: it stalls every controller behind the single-threaded step loop.

Rules:
- BLK301: ``time.sleep`` — go through the injectable kube/clock.py
- BLK302: ``time.time``/``time.monotonic`` — use the injected clock's now()
- BLK303: blocking process/network call (subprocess.run/... , socket,
  urllib, requests) in a reconcile path
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .astutil import call_name, import_aliases, iter_py_files, parse_file
from .findings import Finding, Severity, SourceFile

RULES = {
    "BLK300": "unparsable file (blocking pass)",
    "BLK301": "time.sleep in a reconcile path",
    "BLK302": "direct wall-clock read in a reconcile path",
    "BLK303": "blocking process/network call in a reconcile path",
}

_SLEEPS = {"time.sleep"}
_CLOCK_READS = {"time.time", "time.monotonic", "time.perf_counter"}
_BLOCKING_CALLS = {
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "subprocess.check_call", "socket.create_connection",
    "urllib.request.urlopen", "requests.get", "requests.post",
    "requests.put", "requests.delete", "requests.request",
}


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    findings: List[Finding] = []
    sources: Dict[str, SourceFile] = {}
    for path in iter_py_files(paths):
        try:
            src, tree = parse_file(path)
        except (OSError, SyntaxError) as exc:
            findings.append(
                Finding("BLK300", Severity.ERROR, path, 0, f"unparsable: {exc}")
            )
            continue
        sources[path] = src
        aliases = import_aliases(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node, aliases)
            if cname in _SLEEPS:
                findings.append(
                    Finding(
                        "BLK301", Severity.ERROR, path, node.lineno,
                        "time.sleep blocks the reconcile loop on wall-clock "
                        "time; route it through the injectable "
                        "kube/clock.py Clock.sleep",
                    )
                )
            elif cname in _CLOCK_READS:
                findings.append(
                    Finding(
                        "BLK302", Severity.ERROR, path, node.lineno,
                        f"{cname} reads the wall clock directly; use the "
                        "injected Clock.now() so tests can drive time",
                    )
                )
            elif cname in _BLOCKING_CALLS:
                findings.append(
                    Finding(
                        "BLK303", Severity.ERROR, path, node.lineno,
                        f"blocking call {cname} in a reconcile path stalls "
                        "every controller behind the step loop; move it "
                        "off-thread or behind an injectable seam",
                    )
                )
    return findings, sources
