"""Pass 2: lock-order and callback-under-lock analysis.

The store/state layer is a two-lock system with a documented ordering:
informer handlers run under the Cluster lock and call back into Client
reads (cluster -> store), so the store must NEVER invoke watcher callbacks
while its own lock is held (store -> cluster would close the ABBA cycle —
see kube/filestore.py::_atomic's docstring, and tests/test_races.py for
the dynamic pin). This pass extracts the static acquisition graph and
checks that ordering for every method in the configured file set.

Mechanics (AST only, no imports):
- lock identities are ``file::Class.attr`` for instance locks created in
  ``__init__`` (resolved through single-inheritance bases, so
  ``FileClient._lock`` IS ``Client._lock``) and ``file::name`` for module
  globals;
- attribute types come from ``__init__`` parameter annotations and direct
  constructions (``self._client = client  # client: Client``), so calls
  like ``self._client.list(...)`` resolve cross-class;
- a symbolic walk of each method tracks the held-lock set through ``with``
  blocks, ``.acquire()``/``.release()`` pairs, and ``@contextmanager``
  helpers (locks held at ``yield`` count as held in the caller's body),
  recursing through same-set method calls with dynamic dispatch from the
  entry class.

Since PR 19 the pass loads its file set through the call-graph core
(``core.summaries.load_modules`` — one parse per file, shared with the
GRD/ATM passes) and runs tree-wide, not just over the store layer.
``build_analyzer`` exposes the walked acquisition graph to the atomicity
pass: LCK201 claims cycles whose locks live in ONE module; cycles
spanning modules are ATM1402's (atomicity.py), so the two rules
partition the cycle space.

Rules:
- LCK201: cycle in the acquisition-order graph (ABBA deadlock)
- LCK202: watcher/callback invoked while a lock is held
- LCK203: non-reentrant Lock re-acquired while already held
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .astutil import dotted_name, import_aliases
from .core.summaries import load_modules
from .findings import Finding, Severity, SourceFile

RULES = {
    "LCK200": "unparsable file (locks pass)",
    "LCK201": "cycle in the lock acquisition-order graph (ABBA deadlock)",
    "LCK202": "watcher/callback invoked while a lock is held",
    "LCK203": "non-reentrant Lock re-acquired while already held",
}

_CALLBACK_COLLECTION_HINTS = ("watcher", "handler", "callback", "listener")
_CALLBACK_PARAM_NAMES = {"fn", "func", "callback", "handler", "cb"}
_MAX_DEPTH = 8


class _LockInfo:
    def __init__(self, ident: str, reentrant: bool):
        self.ident = ident
        self.reentrant = reentrant


class _ClassInfo:
    def __init__(self, file: "_File", node: ast.ClassDef):
        self.file = file
        self.node = node
        self.name = node.name
        self.bases = [dotted_name(b) or "" for b in node.bases]
        self.methods: Dict[str, ast.FunctionDef] = {
            item.name: item
            for item in node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # attr -> bare type name (classes) — from __init__
        self.attr_types: Dict[str, str] = {}
        # attr -> _LockInfo — locks constructed in __init__/class body
        self.locks: Dict[str, _LockInfo] = {}
        self._harvest()

    def _harvest(self) -> None:
        init = self.methods.get("__init__")
        body = list(init.body) if init else []
        body += [n for n in self.node.body if isinstance(n, ast.Assign)]
        for stmt in ast.walk(ast.Module(body=body, type_ignores=[])):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            target = stmt.targets[0]
            attr = None
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                attr = target.attr
            if attr is None:
                continue
            lock_kind = _lock_constructor(stmt.value)
            if lock_kind is not None:
                ident = f"{self.file.path}::{self.name}.{attr}"
                self.locks[attr] = _LockInfo(ident, reentrant=lock_kind == "RLock")
                continue
            type_name = _constructed_type(stmt.value)
            if type_name is None and init is not None:
                type_name = _param_annotation(init, stmt.value)
            if type_name:
                self.attr_types[attr] = type_name


def _lock_constructor(value: ast.AST) -> Optional[str]:
    """'Lock'/'RLock' when the expression constructs a threading lock."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            tail = name.rpartition(".")[2]
            if tail in ("Lock", "RLock"):
                return tail
    return None


def _constructed_type(value: ast.AST) -> Optional[str]:
    """Bare class name when the RHS (or an `or` arm) constructs a class."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name[0].isupper():
                return name.rpartition(".")[2]
    return None


def _param_annotation(init: ast.FunctionDef, value: ast.AST) -> Optional[str]:
    """Type of ``self.x = param`` from the __init__ signature annotation."""
    names = {n.id for n in ast.walk(value) if isinstance(n, ast.Name)}
    for arg in init.args.args + init.args.kwonlyargs:
        if arg.arg in names and arg.annotation is not None:
            ann = arg.annotation
            # Optional[X] / "X" strings
            if isinstance(ann, ast.Subscript):
                base = dotted_name(ann.value) or ""
                if base.rpartition(".")[2] == "Optional":
                    ann = ann.slice
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                return ann.value.rpartition(".")[2]
            name = dotted_name(ann)
            if name and name[0].isupper():
                return name.rpartition(".")[2]
    return None


class _File:
    def __init__(self, path: str, src: SourceFile, tree: ast.Module):
        self.path = path
        self.src = src
        self.tree = tree
        self.aliases = import_aliases(tree)
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.module_locks: Dict[str, _LockInfo] = {}
        self.global_types: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = _ClassInfo(self, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    kind = _lock_constructor(node.value)
                    if kind is not None:
                        self.module_locks[target.id] = _LockInfo(
                            f"{self.path}::{target.id}", reentrant=kind == "RLock"
                        )
                    else:
                        tname = _constructed_type(node.value)
                        if tname:
                            self.global_types[target.id] = tname


class _Analyzer:
    def __init__(self, files: List[_File]):
        self.files = files
        self.findings: List[Finding] = []
        # bare class name -> _ClassInfo (unique across the small file set)
        self.class_table: Dict[str, _ClassInfo] = {}
        ambiguous: Set[str] = set()
        for f in files:
            for name, info in f.classes.items():
                if name in self.class_table:
                    ambiguous.add(name)
                self.class_table[name] = info
        for name in ambiguous:
            self.class_table.pop(name, None)
        # acquisition edges: (from_ident, to_ident) -> (path, line)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._memo: Set[Tuple[int, str, FrozenSet[str]]] = set()
        self._cm_memo: Dict[int, Set[str]] = {}

    # -- type / lock resolution ------------------------------------------

    def resolve_base(self, cls: _ClassInfo) -> Optional[_ClassInfo]:
        for base in cls.bases:
            info = self.class_table.get(base.rpartition(".")[2])
            if info is not None:
                return info
        return None

    def mro(self, cls: _ClassInfo) -> List[_ClassInfo]:
        out, seen = [], set()
        cur: Optional[_ClassInfo] = cls
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            out.append(cur)
            cur = self.resolve_base(cur)
        return out

    def lock_of(self, cls: Optional[_ClassInfo], attr: str) -> Optional[_LockInfo]:
        for c in self.mro(cls) if cls else []:
            if attr in c.locks:
                return c.locks[attr]
        return None

    def attr_type(self, cls: Optional[_ClassInfo], attr: str) -> Optional[_ClassInfo]:
        for c in self.mro(cls) if cls else []:
            if attr in c.attr_types:
                return self.class_table.get(c.attr_types[attr])
        return None

    def find_method(
        self, cls: Optional[_ClassInfo], name: str
    ) -> Optional[Tuple[_ClassInfo, ast.FunctionDef]]:
        for c in self.mro(cls) if cls else []:
            if name in c.methods:
                return c, c.methods[name]
        return None

    def expr_lock(
        self, node: ast.AST, file: _File, cls: Optional[_ClassInfo]
    ) -> Optional[_LockInfo]:
        """Lock identity of a `with`/.acquire() context expression."""
        name = dotted_name(node)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            return file.module_locks.get(parts[0])
        if parts[0] == "self" and cls is not None:
            owner: Optional[_ClassInfo] = cls
            for attr in parts[1:-1]:
                owner = self.attr_type(owner, attr)
                if owner is None:
                    return None
            info = self.lock_of(owner, parts[-1])
            if info is not None:
                return info
            # unresolved but lock-named attribute on a known class: give it
            # an identity so fixtures without __init__ bodies still work
            if parts[-1] in ("lock", "_lock") and owner is not None:
                return owner.locks.setdefault(
                    parts[-1],
                    _LockInfo(
                        f"{owner.file.path}::{owner.name}.{parts[-1]}",
                        reentrant=False,
                    ),
                )
        return None

    def cm_held_locks(self, file: _File, cls: _ClassInfo, fn: ast.FunctionDef) -> Set[str]:
        """Lock identities held at any yield of a @contextmanager method."""
        if id(fn) in self._cm_memo:
            return self._cm_memo[id(fn)]
        self._cm_memo[id(fn)] = set()  # cycle guard
        held_at_yield: Set[str] = set()

        def walk(stmts: Sequence[ast.stmt], held: Tuple[_LockInfo, ...]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.With):
                    locks = []
                    for item in stmt.items:
                        info = self.expr_lock(item.context_expr, file, cls)
                        if info is not None:
                            locks.append(info)
                    walk(stmt.body, held + tuple(locks))
                    continue
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.Yield, ast.YieldFrom)):
                        held_at_yield.update(l.ident for l in held)
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    children = getattr(stmt, attr, None)
                    if children and not isinstance(stmt, ast.With):
                        inner = []
                        for c in children:
                            if isinstance(c, ast.ExceptHandler):
                                inner.extend(c.body)
                            elif isinstance(c, ast.stmt):
                                inner.append(c)
                        if inner:
                            walk(inner, held)

        # top-level statement walk only (nested defs don't yield for us)
        for stmt in fn.body:
            if isinstance(stmt, ast.With):
                locks = [
                    info
                    for item in stmt.items
                    if (info := self.expr_lock(item.context_expr, file, cls))
                ]
                walk(stmt.body, tuple(locks))
            else:
                walk([stmt], ())
        self._cm_memo[id(fn)] = held_at_yield
        return held_at_yield

    def _lock_by_ident(self, ident: str) -> _LockInfo:
        return _LockInfo(ident, reentrant=True)

    # -- the symbolic walk -------------------------------------------------

    def analyze_method(
        self,
        file: _File,
        dyn_cls: Optional[_ClassInfo],
        fn: ast.FunctionDef,
        held: Tuple[_LockInfo, ...],
        depth: int = 0,
        entry: str = "",
    ) -> None:
        key = (id(fn), entry, frozenset(l.ident for l in held))
        if key in self._memo or depth > _MAX_DEPTH:
            return
        self._memo.add(key)
        callable_locals = self._callable_locals(fn)
        self._walk(file, dyn_cls, fn, list(fn.body), held, depth, entry,
                   callable_locals)

    def _callable_locals(self, fn: ast.FunctionDef) -> Set[str]:
        """Local names that hold externally-supplied callables: bound by
        iterating a watcher/handler/callback collection, loaded from a
        container of them, or passed as a Callable-annotated/named param."""
        out: Set[str] = set()
        for arg in fn.args.args + fn.args.kwonlyargs:
            ann = ""
            if arg.annotation is not None:
                ann = ast.dump(arg.annotation)
            if "Callable" in ann or arg.arg in _CALLBACK_PARAM_NAMES:
                out.add(arg.arg)
        for node in ast.walk(fn):
            source = None
            if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                source = node.iter
                target = node.target.id
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                source = node.value
                target = node.targets[0].id
            else:
                continue
            for sub in ast.walk(source):
                name = dotted_name(sub) if isinstance(sub, (ast.Attribute, ast.Name)) else None
                if name and any(h in name.lower() for h in _CALLBACK_COLLECTION_HINTS):
                    out.add(target)
                    break
        return out

    def _acquire(
        self,
        lock: _LockInfo,
        held: Tuple[_LockInfo, ...],
        file: _File,
        line: int,
        entry: str,
    ) -> Tuple[_LockInfo, ...]:
        for h in held:
            if h.ident == lock.ident:
                if not lock.reentrant:
                    self.findings.append(
                        Finding(
                            "LCK203", Severity.ERROR, file.path, line,
                            f"non-reentrant lock {_short(lock.ident)} "
                            f"re-acquired while already held"
                            + (f" (via {entry})" if entry else ""),
                        )
                    )
                return held  # reentrant: no new edge
        for h in held:
            self.edges.setdefault((h.ident, lock.ident), (file.path, line))
        return held + (lock,)

    def _walk(
        self,
        file: _File,
        dyn_cls: Optional[_ClassInfo],
        fn: ast.FunctionDef,
        stmts: Sequence[ast.stmt],
        held: Tuple[_LockInfo, ...],
        depth: int,
        entry: str,
        callable_locals: Set[str],
    ) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                new_held = held
                for item in stmt.items:
                    ctx = item.context_expr
                    info = self.expr_lock(ctx, file, dyn_cls)
                    if info is not None:
                        new_held = self._acquire(
                            info, new_held, file, ctx.lineno, entry
                        )
                        continue
                    # `with self._atomic():` — contextmanager helper
                    if isinstance(ctx, ast.Call):
                        target = self._resolve_self_call(ctx, file, dyn_cls)
                        if target is not None:
                            t_cls, t_fn, receiver = target
                            for ident in sorted(
                                self.cm_held_locks(
                                    t_cls.file, receiver or t_cls, t_fn
                                )
                            ):
                                info = _LockInfo(ident, reentrant=True)
                                new_held = self._acquire(
                                    info, new_held, file, ctx.lineno, entry
                                )
                self._walk(file, dyn_cls, fn, stmt.body, new_held, depth,
                           entry, callable_locals)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs analyzed only if invoked (skip)
            if hasattr(stmt, "body"):
                # compound statement: scan its header expressions, then
                # recurse into each body exactly once with the same held set
                for expr in (
                    getattr(stmt, "test", None), getattr(stmt, "iter", None)
                ):
                    if expr is not None:
                        self._scan_calls(expr, file, dyn_cls, held, depth,
                                         entry, callable_locals)
                for attr in ("body", "orelse", "finalbody"):
                    children = getattr(stmt, attr, None)
                    if children:
                        self._walk(file, dyn_cls, fn, children, held, depth,
                                   entry, callable_locals)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._walk(file, dyn_cls, fn, handler.body, held, depth,
                               entry, callable_locals)
                continue
            self._scan_calls(stmt, file, dyn_cls, held, depth, entry,
                             callable_locals)

    def _scan_calls(
        self,
        node: ast.AST,
        file: _File,
        dyn_cls: Optional[_ClassInfo],
        held: Tuple[_LockInfo, ...],
        depth: int,
        entry: str,
        callable_locals: Set[str],
    ) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._handle_call(
                    sub, file, dyn_cls, held, depth, entry, callable_locals
                )

    def _resolve_self_call(
        self, call: ast.Call, file: _File, dyn_cls: Optional[_ClassInfo]
    ) -> Optional[Tuple[_ClassInfo, ast.FunctionDef, Optional[_ClassInfo]]]:
        """(defining_class, method, dynamic_receiver_class) for a resolvable
        call. The receiver class stays ``dyn_cls`` only for ``self.m()`` and
        ``super().m()``; ``self.attr.m()`` dispatches on the attr's type."""
        name = dotted_name(call.func)
        if name is None:
            # super().m(...)
            func = call.func
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Call)
                and dotted_name(func.value.func) == "super"
                and dyn_cls is not None
            ):
                base = self.resolve_base(
                    self.class_table.get(dyn_cls.name) or dyn_cls
                )
                hit = self.find_method(base, func.attr)
                if hit is not None:
                    return hit[0], hit[1], dyn_cls
            return None
        parts = name.split(".")
        if parts[0] == "self" and dyn_cls is not None:
            owner: Optional[_ClassInfo] = dyn_cls
            for attr in parts[1:-1]:
                owner = self.attr_type(owner, attr)
                if owner is None:
                    return None
            hit = self.find_method(owner, parts[-1])
            if hit is not None:
                receiver = dyn_cls if len(parts) == 2 else owner
                return hit[0], hit[1], receiver
            return None
        if len(parts) == 2:
            # module-global instance (e.g. a metrics Gauge)
            owner = self.class_table.get(file.global_types.get(parts[0], ""))
            if owner is not None:
                hit = self.find_method(owner, parts[1])
                if hit is not None:
                    return hit[0], hit[1], owner
        return None

    def _handle_call(
        self,
        node: ast.Call,
        file: _File,
        dyn_cls: Optional[_ClassInfo],
        held: Tuple[_LockInfo, ...],
        depth: int,
        entry: str,
        callable_locals: Set[str],
    ) -> None:
        name = dotted_name(node.func)
        # .acquire() outside a with — record as an edge source point
        if isinstance(node.func, ast.Attribute) and node.func.attr == "acquire":
            info = self.expr_lock(node.func.value, file, dyn_cls)
            if info is not None:
                self._acquire(info, held, file, node.lineno, entry)
            return
        if held and name and len(name.split(".")) == 1:
            # a bare call of a callback-shaped name: tracked callable
            # locals, or names that announce themselves (handler/fn/cb/...)
            if name in callable_locals or name in _CALLBACK_PARAM_NAMES:
                locks = ", ".join(sorted(_short(l.ident) for l in held))
                self.findings.append(
                    Finding(
                        "LCK202", Severity.ERROR, file.path, node.lineno,
                        f"callback '{name}(...)' invoked while holding "
                        f"{locks}"
                        + (f" (entered via {entry})" if entry else "")
                        + "; release the lock before notifying",
                    )
                )
                return
        target = self._resolve_self_call(node, file, dyn_cls)
        if target is not None:
            t_cls, t_fn, receiver = target
            next_entry = entry or f"{(dyn_cls or t_cls).name}"
            self.analyze_method(
                t_cls.file, receiver, t_fn, held, depth + 1,
                entry=f"{next_entry} -> {t_cls.name}.{t_fn.name}"
                if held else "",
            )

    # -- cycle detection ---------------------------------------------------

    def detect_cycles(
        self, rule: str = "LCK201", cross_module_only: bool = False
    ) -> None:
        """Report acquisition-order cycles.

        The default (LCK201) reports cycles whose locks all live in one
        module — the store-layer ABBA class this pass was built for. With
        ``cross_module_only`` the SAME graph yields the complementary set
        (cycles spanning ≥2 modules) under the caller's rule id: the
        atomicity pass (ATM1402) runs the tree-wide walk and claims those,
        so the two rules partition the cycle space instead of
        double-reporting one deadlock."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        seen: Set[FrozenSet[str]] = set()

        def dfs(start: str, node: str, path: List[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key in seen:
                        continue
                    seen.add(key)
                    cycle = path + [start]
                    modules = {p.partition("::")[0] for p in path}
                    if cross_module_only != (len(modules) > 1):
                        continue
                    site = self.edges.get((path[-1], start)) or \
                        self.edges.get((path[0], path[1]), ("", 0))
                    if cross_module_only:
                        msg = (
                            "interprocedural lock-order cycle across "
                            "modules: "
                            + " -> ".join(_short(p) for p in cycle)
                            + " (ABBA deadlock potential; keep one global "
                            "acquisition order across layers)"
                        )
                    else:
                        msg = (
                            "lock-order cycle: "
                            + " -> ".join(_short(p) for p in cycle)
                            + " (ABBA deadlock; keep a single global "
                            "acquisition order)"
                        )
                    self.findings.append(
                        Finding(rule, Severity.ERROR, site[0], site[1], msg)
                    )
                elif nxt not in path:
                    dfs(start, nxt, path + [nxt])

        for node in sorted(graph):
            dfs(node, node, [node])


def _short(ident: str) -> str:
    path, _, name = ident.partition("::")
    import os

    return f"{os.path.basename(path)}::{name}"


def build_analyzer(modules) -> "_Analyzer":
    """A fully-walked acquisition analyzer over core-loaded modules.

    Shared entry for this pass and the atomicity pass (ATM1402): both
    need the same held-set symbolic walk and the same acquisition-edge
    graph; they differ only in which cycle population they claim. The
    walk also emits LCK202/LCK203 findings into ``analyzer.findings`` —
    callers keep or drop those by rule."""
    files = [_File(m.path, m.src, m.tree) for m in modules.values()]
    analyzer = _Analyzer(files)
    for f in files:
        for cls in f.classes.values():
            for mname, method in cls.methods.items():
                analyzer.analyze_method(f, cls, method, held=())
        for fn in f.functions.values():
            analyzer.analyze_method(f, None, fn, held=())
    return analyzer


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the lock-order pass over the given files/directories."""
    modules, sources, errors = load_modules(paths)
    parse_findings = [
        Finding("LCK200", Severity.ERROR, path, 0, f"unparsable: {exc}")
        for path, exc in errors
    ]
    analyzer = build_analyzer(modules)
    analyzer.findings = parse_findings + analyzer.findings
    analyzer.detect_cycles()
    # one finding per (rule, site): entry paths multiply otherwise
    unique: Dict[Tuple[str, str, int], Finding] = {}
    for finding in analyzer.findings:
        unique.setdefault((finding.rule, finding.path, finding.line), finding)
    return list(unique.values()), sources
