"""CLI for the static-analysis tier: ``python -m karpenter_tpu.analysis``.

Default targets mirror the hazards each pass exists for:

- tracer:   karpenter_tpu/ops, karpenter_tpu/solver
- locks:    kube/store.py, kube/filestore.py, controllers/state.py,
            solver/driver.py, metrics/registry.py
- blocking: karpenter_tpu/controllers, karpenter_tpu/__main__.py
- schema:   api/schema.py vs api/crds/

Positional paths (with ``--pass``) override a pass's default targets so
fixture suites can point a single pass at seeded-bad files. Exit status is
the number of unsuppressed findings capped at 1 — suitable for presubmit.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

from . import blocking, locks, schema_drift, tracer
from .findings import (
    Finding,
    Severity,
    SourceFile,
    filter_suppressed,
    load_baseline,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join("hack", "analysis_baseline.txt")

PASS_TARGETS = {
    "tracer": ["karpenter_tpu/ops", "karpenter_tpu/solver"],
    "locks": [
        "karpenter_tpu/kube/store.py",
        "karpenter_tpu/kube/filestore.py",
        "karpenter_tpu/controllers/state.py",
        "karpenter_tpu/solver/driver.py",
        "karpenter_tpu/metrics/registry.py",
    ],
    "blocking": ["karpenter_tpu/controllers", "karpenter_tpu/__main__.py"],
    "schema": ["karpenter_tpu/api/schema.py", "karpenter_tpu/api/crds"],
}


def _run_pass(name: str, targets: List[str]):
    if name == "tracer":
        return tracer.check_paths(targets)
    if name == "locks":
        return locks.check_paths(targets)
    if name == "blocking":
        return blocking.check_paths(targets)
    if name == "schema":
        schema_py = targets[0]
        crd_dir = targets[1] if len(targets) > 1 else os.path.join(
            os.path.dirname(targets[0]), "crds"
        )
        return schema_drift.check_schema(schema_py, crd_dir)
    raise ValueError(f"unknown pass {name!r}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.analysis",
        description="AST static analysis: tracer-safety, lock ordering, "
        "blocking calls, schema drift",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="override the selected pass's default targets "
        "(requires exactly one --pass)",
    )
    parser.add_argument(
        "--pass", dest="passes", action="append",
        choices=sorted(PASS_TARGETS),
        help="run only the named pass(es); default: all",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root the default targets are relative to",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help=f"baseline file of tolerated findings (default: "
        f"{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file (report everything)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    args = parser.parse_args(argv)

    selected = args.passes or sorted(PASS_TARGETS)
    if args.paths and len(selected) != 1:
        parser.error("explicit paths require exactly one --pass")

    root = os.path.abspath(args.root)
    all_findings: List[Finding] = []
    all_sources: Dict[str, SourceFile] = {}
    for name in selected:
        if args.paths:
            targets = args.paths
        else:
            targets = [os.path.join(root, t) for t in PASS_TARGETS[name]]
            targets = [t for t in targets if os.path.exists(t)]
            if not targets:
                continue
        findings, sources = _run_pass(name, targets)
        all_findings.extend(findings)
        all_sources.update(sources)

    # repo-relative paths in output and baseline keys
    def relativize(f: Finding) -> Finding:
        rel = os.path.relpath(f.path, root)
        if rel.startswith(".."):
            rel = f.path
        return Finding(f.rule, f.severity, rel, f.line, f.message)

    rel_sources = {}
    for path, src in all_sources.items():
        rel = os.path.relpath(path, root)
        rel_sources[rel if not rel.startswith("..") else path] = src
    all_findings = [relativize(f) for f in all_findings]

    baseline_path = (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(root, args.baseline)
    )
    baseline = None if args.no_baseline else load_baseline(baseline_path)
    remaining = filter_suppressed(all_findings, rel_sources, baseline)

    if args.write_baseline:
        # regenerate from the inline-filtered set only: filtering through
        # the existing baseline would drop still-needed grandfathered
        # entries from the rewritten file
        grandfather = filter_suppressed(all_findings, rel_sources, None)
        write_baseline(baseline_path, grandfather)
        print(
            f"analysis: wrote {len(grandfather)} finding(s) to "
            f"{os.path.relpath(baseline_path, root)}"
        )
        return 0

    for f in sorted(remaining, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    suppressed = len(all_findings) - len(remaining)
    errors = [f for f in remaining if f.severity == Severity.ERROR]
    summary = f"analysis: {len(remaining)} finding(s)"
    if len(remaining) != len(errors):
        summary += f" ({len(remaining) - len(errors)} warning-only)"
    if suppressed:
        summary += f" ({suppressed} suppressed)"
    print(summary, file=sys.stderr)
    # warnings (e.g. "pass skipped: PyYAML unavailable") inform but don't
    # fail presubmit; only error-severity findings gate
    return 1 if errors else 0
