"""CLI for the static-analysis tier: ``python -m karpenter_tpu.analysis``.

Default targets mirror the hazards each pass exists for:

- tracer:   karpenter_tpu/ops, karpenter_tpu/solver
- locks:    the threaded tree (solver/, ops/, controllers/, kube/, obs/,
            metrics/, sim/, operator.py) — generalized from the store
            layer in PR 19
- blocking: karpenter_tpu/controllers, karpenter_tpu/__main__.py,
            solver/service.py, kube/leader.py
- schema:   api/schema.py vs api/crds/
- parity:   ops/packing.py vs native/solve_core.cc (kernel-twin skeletons)
- shapes:   karpenter_tpu/ops, karpenter_tpu/solver, karpenter_tpu/parallel
            (axis/dtype walker + sharding shard-divisibility)
- retry:    karpenter_tpu/controllers, karpenter_tpu/solver, operator.py
            (swallowed exceptions, unbounded retry loops)
- device:   karpenter_tpu/ops, solver/driver.py, faults/guard.py
            (DTX9xx device-residency dataflow)
- clock:    karpenter_tpu/controllers, faults/, obs/, solver/
            (CLK10xx clock-discipline dataflow)
- det:      karpenter_tpu/solver, ops/, sim/, obs/
            (DET11xx order-discipline dataflow: unordered sources to
            order-sensitive sinks)
- args:     solver/encode.py, parallel/mesh.py, solver/residency.py,
            native/__init__.py, ops/solve.py (ARG12xx kernel-arg
            registry surfaces vs SOLVE_ARG_NAMES)
- guarded:  the threaded tree (GRD13xx guarded-by inference: mixed
            guarded/lock-free access, reference escapes, locking
            __init__-published callbacks)
- atomicity: the threaded tree (ATM14xx: check-then-act across a lock
            release, cross-module lock-order cycles)

Positional paths (with ``--pass``) override a pass's default targets so
fixture suites can point a single pass at seeded-bad files. Exit status is
the number of unsuppressed findings capped at 1 — suitable for presubmit.

``--changed-only`` scopes file discovery to ``git diff --name-only
<--base>`` plus untracked files — the presubmit fast lane; the full run
(default, or explicit ``--all``) is the slow-lane gate and the only mode
that runs the stale-suppression audit (STALE001) — staleness can only be
judged when every producing pass ran. ``--prune-baseline`` rewrites
hack/analysis_baseline.txt with stale entries dropped.

``--format sarif`` emits SARIF 2.1.0 with the analyzer's own runtime in
the run properties (per-pass seconds — the BENCH-adjacent artifact that
makes analyzer-speed regressions visible); ``--write-baseline``
regenerates hack/analysis_baseline.txt so bulk grandfathering is a
designed workflow instead of a hand-edit.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Set

from . import (
    all_rules,
    args_registry,
    atomicity,
    blocking,
    clock,
    det,
    device,
    guarded,
    locks,
    obs,
    parity,
    retry,
    schema_drift,
    shapes,
    stale,
    tracer,
)
from .astutil import iter_py_files
from .findings import (
    Finding,
    Severity,
    SourceFile,
    load_baseline,
    partition_findings,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join("hack", "analysis_baseline.txt")

# the whole threaded surface: every layer that constructs a lock or a
# thread — the GRD/ATM dogfood set, and (since PR 19) the locks pass's
# generalized scope (it was store-local before)
_THREADED_TREE = [
    "karpenter_tpu/solver",
    "karpenter_tpu/ops",
    "karpenter_tpu/controllers",
    "karpenter_tpu/kube",
    "karpenter_tpu/obs",
    "karpenter_tpu/metrics",
    "karpenter_tpu/sim",
    "karpenter_tpu/operator.py",
]

PASS_TARGETS = {
    "tracer": ["karpenter_tpu/ops", "karpenter_tpu/solver"],
    "locks": list(_THREADED_TREE),
    "blocking": [
        "karpenter_tpu/controllers",
        "karpenter_tpu/__main__.py",
        # the sidecar's solve path and the leader-election loop are
        # reconcile-shaped too: both run behind level-triggered steps and
        # must stay on the injectable clock
        "karpenter_tpu/solver/service.py",
        "karpenter_tpu/kube/leader.py",
    ],
    "schema": ["karpenter_tpu/api/schema.py", "karpenter_tpu/api/crds"],
    "parity": [
        "karpenter_tpu/ops/packing.py",
        "karpenter_tpu/native/solve_core.cc",
    ],
    "shapes": [
        "karpenter_tpu/ops", "karpenter_tpu/solver", "karpenter_tpu/parallel",
    ],
    # retry/except hygiene where the degradation ladder lives: the
    # reconcile roster, the solver path, and the operator's requeue loop
    "retry": [
        "karpenter_tpu/controllers",
        "karpenter_tpu/solver",
        "karpenter_tpu/operator.py",
    ],
    # observability hygiene: span leaks and per-call metric construction
    # anywhere in the package (the obs seams thread through everything)
    "obs": ["karpenter_tpu"],
    # device-residency dataflow over the solve path: where device values
    # are born (ops/), routed (driver), held BETWEEN solves
    # (solver/residency.py — the dev_*/_dev* resident-attribute
    # convention), and guarded (faults/guard.py)
    "device": [
        "karpenter_tpu/ops",
        "karpenter_tpu/solver/driver.py",
        "karpenter_tpu/solver/residency.py",
        "karpenter_tpu/faults/guard.py",
    ],
    # clock discipline over the determinism surface: every timestamp in
    # these trees must flow from an injected clock or a RealClock seam
    "clock": [
        "karpenter_tpu/controllers",
        "karpenter_tpu/faults",
        "karpenter_tpu/obs",
        "karpenter_tpu/solver",
    ],
    # order discipline over the determinism surface: unordered-source
    # values (sets, os.environ, unseeded RNG) must not reach
    # order-sensitive sinks un-sorted (DET11xx — the PYTHONHASHSEED
    # interning class, statically)
    "det": [
        "karpenter_tpu/solver",
        "karpenter_tpu/ops",
        "karpenter_tpu/sim",
        "karpenter_tpu/obs",
    ],
    # the kernel-arg registry's six hand-aligned surfaces, diffed
    # against SOLVE_ARG_NAMES (ARG12xx)
    "args": [
        "karpenter_tpu/solver/encode.py",
        "karpenter_tpu/parallel/mesh.py",
        "karpenter_tpu/solver/residency.py",
        "karpenter_tpu/native/__init__.py",
        "karpenter_tpu/ops/solve.py",
    ],
    # guarded-by inference (GRD13xx) and atomicity/lock-order (ATM14xx)
    # over the same threaded tree the generalized locks pass scans
    "guarded": list(_THREADED_TREE),
    "atomicity": list(_THREADED_TREE),
}

# passes whose targets are a comparison pair (or cross-file registry),
# not an independently scannable file set: --changed-only runs them in
# full when ANY of their targets changed — a partial scan would read as
# "surface missing" instead of "surface unchanged"
_PAIR_PASSES = {"schema", "parity", "args"}


def _run_pass(name: str, targets: List[str]):
    if name == "tracer":
        return tracer.check_paths(targets)
    if name == "locks":
        return locks.check_paths(targets)
    if name == "blocking":
        return blocking.check_paths(targets)
    if name == "schema":
        schema_py = targets[0]
        crd_dir = targets[1] if len(targets) > 1 else os.path.join(
            os.path.dirname(targets[0]), "crds"
        )
        return schema_drift.check_schema(schema_py, crd_dir)
    if name == "parity":
        py_path = targets[0]
        cc_path = targets[1] if len(targets) > 1 else os.path.join(
            os.path.dirname(os.path.dirname(py_path)),
            "native", "solve_core.cc",
        )
        return parity.check_parity(py_path, cc_path)
    if name == "shapes":
        return shapes.check_paths(targets)
    if name == "retry":
        return retry.check_paths(targets)
    if name == "obs":
        return obs.check_paths(targets)
    if name == "device":
        return device.check_paths(targets)
    if name == "clock":
        return clock.check_paths(targets)
    if name == "det":
        return det.check_paths(targets)
    if name == "args":
        return args_registry.check_paths(targets)
    if name == "guarded":
        return guarded.check_paths(targets)
    if name == "atomicity":
        return atomicity.check_paths(targets)
    raise ValueError(f"unknown pass {name!r}")


# pass name -> producing module, for RULES lookup (stale-audit scope) —
# the single place a new pass registers itself besides PASS_TARGETS
PASS_MODULES = {
    "tracer": tracer, "locks": locks, "blocking": blocking,
    "schema": schema_drift, "parity": parity, "shapes": shapes,
    "retry": retry, "obs": obs, "device": device, "clock": clock,
    "det": det, "args": args_registry, "guarded": guarded,
    "atomicity": atomicity,
}


def _pass_worker(job):
    """Run one pass in a worker process: (name, targets) ->
    (name, findings, sources, seconds). Module-level so the process
    pool can pickle it by reference."""
    name, targets = job
    t0 = time.perf_counter()
    findings, sources = _run_pass(name, targets)
    return name, findings, sources, round(time.perf_counter() - t0, 4)


def _changed_files(root: str, base: str) -> Optional[Set[str]]:
    """Absolute paths changed vs ``base`` (diff + untracked), or None when
    git is unavailable (callers fall back to the full run)."""
    changed: Set[str] = set()
    for args in (
        ["git", "diff", "--name-only", base],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                args, capture_output=True, text=True, cwd=root, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line:
                changed.add(os.path.abspath(os.path.join(root, line)))
    return changed


def _scope_targets(
    name: str, targets: List[str], changed: Set[str]
) -> List[str]:
    """The subset of a pass's targets --changed-only should run."""
    if name in _PAIR_PASSES:
        hit = False
        for t in targets:
            if os.path.isdir(t):
                hit = hit or any(c.startswith(t + os.sep) for c in changed)
            else:
                hit = hit or os.path.abspath(t) in changed
        return targets if hit else []
    out: List[str] = []
    for t in targets:
        for path in iter_py_files([t]):
            if os.path.abspath(path) in changed:
                out.append(path)
    return out


def _sarif(findings: List[Finding], properties: Optional[dict] = None) -> dict:
    """Minimal SARIF 2.1.0 document for the given (unsuppressed) findings."""
    rules_meta = all_rules()
    used = sorted({f.rule for f in findings})
    run = {
        "tool": {
            "driver": {
                # informationUri omitted: SARIF 2.1.0 requires an
                # absolute URI and this tool has no canonical URL
                "name": "karpenter-tpu-analysis",
                "rules": [
                    {
                        "id": rule,
                        "shortDescription": {
                            "text": rules_meta.get(rule, rule)
                        },
                    }
                    for rule in used
                ],
            }
        },
        "results": [
            {
                "ruleId": f.rule,
                "level": (
                    "error" if f.severity == Severity.ERROR
                    else "warning"
                ),
                "message": {"text": f.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": max(f.line, 1)
                            },
                        }
                    }
                ],
            }
            for f in sorted(
                findings, key=lambda f: (f.path, f.line, f.rule)
            )
        ],
    }
    if properties:
        # analyzer self-runtime rides in the run properties: the SARIF
        # artifact doubles as the BENCH-adjacent record that makes
        # analyzer-speed regressions visible across PRs
        run["properties"] = properties
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [run],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.analysis",
        description="Static analysis on the shared dataflow core: "
        "tracer-safety, lock ordering, blocking calls, schema drift, "
        "kernel-twin parity, axis/dtype shape discipline, retry hygiene, "
        "observability hygiene, device-residency (DTX9xx), clock "
        "discipline (CLK10xx), order discipline (DET11xx), kernel-arg "
        "registry consistency (ARG12xx), guarded-by inference "
        "(GRD13xx), and atomicity/lock-order (ATM14xx)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="override the selected pass's default targets "
        "(requires exactly one --pass)",
    )
    parser.add_argument(
        "--pass", dest="passes", action="append",
        choices=sorted(PASS_TARGETS),
        help="run only the named pass(es); default: all",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root the default targets are relative to",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help=f"baseline file of tolerated findings (default: "
        f"{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file (report everything)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="scope file discovery to `git diff --name-only <--base>` "
        "plus untracked files (the presubmit fast lane); skips the "
        "stale-suppression audit",
    )
    parser.add_argument(
        "--base", default="HEAD",
        help="git ref --changed-only diffs against (default: HEAD)",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="force the full run (the default; overrides --changed-only)",
    )
    parser.add_argument(
        "--prune-baseline", action="store_true",
        help="run the full analysis, drop baseline entries matching no "
        "finding, rewrite the baseline, and exit 0",
    )
    parser.add_argument(
        "--format", choices=("text", "sarif"), default="text",
        help="finding output format (sarif: SARIF 2.1.0 JSON on stdout)",
    )
    parser.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="run pass modules in an N-process pool (passes are "
        "independent file scans; 1 = in-process, the default)",
    )
    args = parser.parse_args(argv)

    selected = args.passes or sorted(PASS_TARGETS)
    if args.paths and len(selected) != 1:
        parser.error("explicit paths require exactly one --pass")
    if args.prune_baseline:
        # pruning needs the FULL finding set to judge staleness, and a
        # loaded baseline to prune — partial runs would silently prune
        # nothing, and --no-baseline would truncate every entry
        if args.no_baseline:
            parser.error("--prune-baseline conflicts with --no-baseline")
        if args.passes or args.paths or args.write_baseline:
            parser.error(
                "--prune-baseline requires the full run (no --pass, "
                "paths, or --write-baseline)"
            )
        args.changed_only = False  # force the full file set

    root = os.path.abspath(args.root)
    changed: Optional[Set[str]] = None
    if args.changed_only and not args.all and not args.paths:
        changed = _changed_files(root, args.base)
        if changed is None:
            print(
                "analysis: --changed-only needs git; running the full set",
                file=sys.stderr,
            )

    t_start = time.perf_counter()
    jobs: List = []
    for name in selected:
        if args.paths:
            targets = args.paths
        else:
            targets = [os.path.join(root, t) for t in PASS_TARGETS[name]]
            targets = [t for t in targets if os.path.exists(t)]
            if changed is not None:
                targets = _scope_targets(name, targets, changed)
            if not targets:
                continue
        jobs.append((name, targets))

    if args.jobs > 1 and len(jobs) > 1:
        # passes are independent file scans with picklable results; a
        # process pool turns sum-of-pass wall time into max-of-pass.
        # Results are reassembled in selection order, so output and exit
        # status are byte-identical to the sequential run.
        from concurrent.futures import ProcessPoolExecutor

        with ProcessPoolExecutor(
            max_workers=min(args.jobs, len(jobs))
        ) as pool:
            results = list(pool.map(_pass_worker, jobs))
    else:
        results = [_pass_worker(job) for job in jobs]

    pass_seconds: Dict[str, float] = {}
    all_findings: List[Finding] = []
    all_sources: Dict[str, SourceFile] = {}
    # rule id -> abs paths its pass scanned (stale-audit accuracy gate)
    scanned_by_rule: Dict[str, Set[str]] = {}
    for name, findings, sources, seconds in results:
        pass_seconds[name] = seconds
        all_findings.extend(findings)
        all_sources.update(sources)
        for rule in getattr(PASS_MODULES[name], "RULES", {}):
            scanned_by_rule.setdefault(rule, set()).update(sources)

    # repo-relative paths in output and baseline keys
    def relativize(f: Finding) -> Finding:
        rel = os.path.relpath(f.path, root)
        if rel.startswith(".."):
            rel = f.path
        return Finding(f.rule, f.severity, rel, f.line, f.message)

    rel_sources = {}
    rel_scanned: Dict[str, Set[str]] = {}
    for path, src in all_sources.items():
        rel = os.path.relpath(path, root)
        rel_sources[rel if not rel.startswith("..") else path] = src
    for rule, paths in scanned_by_rule.items():
        rel_scanned[rule] = {
            os.path.relpath(p, root)
            if not os.path.relpath(p, root).startswith("..")
            else p
            for p in paths
        }
    all_findings = [relativize(f) for f in all_findings]

    baseline_path = (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(root, args.baseline)
    )
    baseline = None if args.no_baseline else load_baseline(baseline_path)

    # stale-suppression audit: full runs only — staleness can only be
    # judged when every pass that could match a marker actually ran
    full_run = (
        not args.paths
        and changed is None
        and not args.passes
        and not args.write_baseline
    )
    stale_findings: List[Finding] = []
    stale_entries: Set = set()
    if full_run and not args.no_baseline:
        stale_findings, stale_entries = stale.audit(
            all_findings, rel_sources, baseline,
            os.path.relpath(baseline_path, root),
            scanned_by_rule=rel_scanned,
        )

    if args.prune_baseline:
        live = sorted((baseline or set()) - stale_entries)
        with open(baseline_path, "w", encoding="utf-8") as fh:
            fh.write(
                "# Static-analysis baseline: known findings tolerated by\n"
                "# `python -m karpenter_tpu.analysis`. One per line,\n"
                "# RULE<TAB>path<TAB>message. Regenerate with "
                "--write-baseline;\n"
                "# prefer inline `# analysis: ignore[RULE] reason` for "
                "findings\n"
                "# that are intentionally safe.\n"
            )
            if not live:
                fh.write(
                    "#\n# Currently empty: every tolerated finding carries "
                    "an inline\n# suppression next to the code it "
                    "describes.\n"
                )
            for rule, fpath, message in live:
                fh.write(f"{rule}\t{fpath}\t{message}\n")
        print(
            f"analysis: pruned {len(stale_entries)} stale baseline "
            f"entr{'y' if len(stale_entries) == 1 else 'ies'}; "
            f"{len(live)} kept"
        )
        for f in stale_findings:
            if f.path != os.path.relpath(baseline_path, root):
                print(f.render())
        return 0

    remaining, suppressed_fs, sanctioned_fs = partition_findings(
        all_findings, rel_sources, baseline
    )
    remaining = remaining + stale_findings

    if args.write_baseline:
        # regenerate from the inline-filtered set only: filtering through
        # the existing baseline would drop still-needed grandfathered
        # entries from the rewritten file
        grandfather, _, _ = partition_findings(all_findings, rel_sources, None)
        write_baseline(baseline_path, grandfather)
        print(
            f"analysis: wrote {len(grandfather)} finding(s) to "
            f"{os.path.relpath(baseline_path, root)}"
        )
        return 0

    total_seconds = round(time.perf_counter() - t_start, 4)
    if args.format == "sarif":
        properties = {
            "analysisSeconds": total_seconds,
            "passSeconds": pass_seconds,
            # sum of per-pass seconds = the sequential-equivalent wall;
            # with --jobs > 1 the gap to analysisSeconds is the measured
            # pool speedup, recorded so it regresses visibly
            "sequentialSeconds": round(sum(pass_seconds.values()), 4),
            "jobs": args.jobs,
            "sanctionedSites": len(sanctioned_fs),
            "suppressedFindings": len(suppressed_fs),
            "changedOnly": changed is not None,
        }
        json.dump(_sarif(remaining, properties), sys.stdout, indent=2)
        print()
    else:
        for f in sorted(remaining, key=lambda f: (f.path, f.line, f.rule)):
            print(f.render())
    errors = [f for f in remaining if f.severity == Severity.ERROR]
    summary = f"analysis: {len(remaining)} finding(s)"
    if len(remaining) != len(errors):
        summary += f" ({len(remaining) - len(errors)} warning-only)"
    if suppressed_fs:
        summary += f" ({len(suppressed_fs)} suppressed)"
    if sanctioned_fs:
        summary += f" ({len(sanctioned_fs)} sanctioned boundary site(s))"
    summary += f" [{total_seconds:.2f}s"
    if changed is not None:
        summary += f", changed-only over {len(changed)} file(s)"
    summary += "]"
    print(summary, file=sys.stderr)
    # warnings (e.g. "pass skipped: PyYAML unavailable") inform but don't
    # fail presubmit; only error-severity findings gate
    return 1 if errors else 0
