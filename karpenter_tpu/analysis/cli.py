"""CLI for the static-analysis tier: ``python -m karpenter_tpu.analysis``.

Default targets mirror the hazards each pass exists for:

- tracer:   karpenter_tpu/ops, karpenter_tpu/solver
- locks:    kube/store.py, kube/filestore.py, controllers/state.py,
            solver/driver.py, metrics/registry.py
- blocking: karpenter_tpu/controllers, karpenter_tpu/__main__.py,
            solver/service.py, kube/leader.py
- schema:   api/schema.py vs api/crds/
- parity:   ops/packing.py vs native/solve_core.cc (kernel-twin skeletons)
- shapes:   karpenter_tpu/ops, karpenter_tpu/solver (axis/dtype walker)
- retry:    karpenter_tpu/controllers, karpenter_tpu/solver, operator.py
            (swallowed exceptions, unbounded retry loops)

Positional paths (with ``--pass``) override a pass's default targets so
fixture suites can point a single pass at seeded-bad files. Exit status is
the number of unsuppressed findings capped at 1 — suitable for presubmit.
``--format sarif`` emits SARIF 2.1.0 for code-review UIs;
``--write-baseline`` regenerates hack/analysis_baseline.txt so bulk
grandfathering is a designed workflow instead of a hand-edit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from . import (
    all_rules,
    blocking,
    locks,
    obs,
    parity,
    retry,
    schema_drift,
    shapes,
    tracer,
)
from .findings import (
    Finding,
    Severity,
    SourceFile,
    filter_suppressed,
    load_baseline,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join("hack", "analysis_baseline.txt")

PASS_TARGETS = {
    "tracer": ["karpenter_tpu/ops", "karpenter_tpu/solver"],
    "locks": [
        "karpenter_tpu/kube/store.py",
        "karpenter_tpu/kube/filestore.py",
        "karpenter_tpu/controllers/state.py",
        "karpenter_tpu/solver/driver.py",
        "karpenter_tpu/metrics/registry.py",
    ],
    "blocking": [
        "karpenter_tpu/controllers",
        "karpenter_tpu/__main__.py",
        # the sidecar's solve path and the leader-election loop are
        # reconcile-shaped too: both run behind level-triggered steps and
        # must stay on the injectable clock
        "karpenter_tpu/solver/service.py",
        "karpenter_tpu/kube/leader.py",
    ],
    "schema": ["karpenter_tpu/api/schema.py", "karpenter_tpu/api/crds"],
    "parity": [
        "karpenter_tpu/ops/packing.py",
        "karpenter_tpu/native/solve_core.cc",
    ],
    "shapes": ["karpenter_tpu/ops", "karpenter_tpu/solver"],
    # retry/except hygiene where the degradation ladder lives: the
    # reconcile roster, the solver path, and the operator's requeue loop
    "retry": [
        "karpenter_tpu/controllers",
        "karpenter_tpu/solver",
        "karpenter_tpu/operator.py",
    ],
    # observability hygiene: span leaks and per-call metric construction
    # anywhere in the package (the obs seams thread through everything)
    "obs": ["karpenter_tpu"],
}


def _run_pass(name: str, targets: List[str]):
    if name == "tracer":
        return tracer.check_paths(targets)
    if name == "locks":
        return locks.check_paths(targets)
    if name == "blocking":
        return blocking.check_paths(targets)
    if name == "schema":
        schema_py = targets[0]
        crd_dir = targets[1] if len(targets) > 1 else os.path.join(
            os.path.dirname(targets[0]), "crds"
        )
        return schema_drift.check_schema(schema_py, crd_dir)
    if name == "parity":
        py_path = targets[0]
        cc_path = targets[1] if len(targets) > 1 else os.path.join(
            os.path.dirname(os.path.dirname(py_path)),
            "native", "solve_core.cc",
        )
        return parity.check_parity(py_path, cc_path)
    if name == "shapes":
        return shapes.check_paths(targets)
    if name == "retry":
        return retry.check_paths(targets)
    if name == "obs":
        return obs.check_paths(targets)
    raise ValueError(f"unknown pass {name!r}")


def _sarif(findings: List[Finding]) -> dict:
    """Minimal SARIF 2.1.0 document for the given (unsuppressed) findings."""
    rules_meta = all_rules()
    used = sorted({f.rule for f in findings})
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        # informationUri omitted: SARIF 2.1.0 requires an
                        # absolute URI and this tool has no canonical URL
                        "name": "karpenter-tpu-analysis",
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {
                                    "text": rules_meta.get(rule, rule)
                                },
                            }
                            for rule in used
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": (
                            "error" if f.severity == Severity.ERROR
                            else "warning"
                        ),
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": max(f.line, 1)
                                    },
                                }
                            }
                        ],
                    }
                    for f in sorted(
                        findings, key=lambda f: (f.path, f.line, f.rule)
                    )
                ],
            }
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.analysis",
        description="AST static analysis: tracer-safety, lock ordering, "
        "blocking calls, schema drift, kernel-twin parity, axis/dtype "
        "shape discipline",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="override the selected pass's default targets "
        "(requires exactly one --pass)",
    )
    parser.add_argument(
        "--pass", dest="passes", action="append",
        choices=sorted(PASS_TARGETS),
        help="run only the named pass(es); default: all",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root the default targets are relative to",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help=f"baseline file of tolerated findings (default: "
        f"{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file (report everything)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--format", choices=("text", "sarif"), default="text",
        help="finding output format (sarif: SARIF 2.1.0 JSON on stdout)",
    )
    args = parser.parse_args(argv)

    selected = args.passes or sorted(PASS_TARGETS)
    if args.paths and len(selected) != 1:
        parser.error("explicit paths require exactly one --pass")

    root = os.path.abspath(args.root)
    all_findings: List[Finding] = []
    all_sources: Dict[str, SourceFile] = {}
    for name in selected:
        if args.paths:
            targets = args.paths
        else:
            targets = [os.path.join(root, t) for t in PASS_TARGETS[name]]
            targets = [t for t in targets if os.path.exists(t)]
            if not targets:
                continue
        findings, sources = _run_pass(name, targets)
        all_findings.extend(findings)
        all_sources.update(sources)

    # repo-relative paths in output and baseline keys
    def relativize(f: Finding) -> Finding:
        rel = os.path.relpath(f.path, root)
        if rel.startswith(".."):
            rel = f.path
        return Finding(f.rule, f.severity, rel, f.line, f.message)

    rel_sources = {}
    for path, src in all_sources.items():
        rel = os.path.relpath(path, root)
        rel_sources[rel if not rel.startswith("..") else path] = src
    all_findings = [relativize(f) for f in all_findings]

    baseline_path = (
        args.baseline
        if os.path.isabs(args.baseline)
        else os.path.join(root, args.baseline)
    )
    baseline = None if args.no_baseline else load_baseline(baseline_path)
    remaining = filter_suppressed(all_findings, rel_sources, baseline)

    if args.write_baseline:
        # regenerate from the inline-filtered set only: filtering through
        # the existing baseline would drop still-needed grandfathered
        # entries from the rewritten file
        grandfather = filter_suppressed(all_findings, rel_sources, None)
        write_baseline(baseline_path, grandfather)
        print(
            f"analysis: wrote {len(grandfather)} finding(s) to "
            f"{os.path.relpath(baseline_path, root)}"
        )
        return 0

    if args.format == "sarif":
        json.dump(_sarif(remaining), sys.stdout, indent=2)
        print()
    else:
        for f in sorted(remaining, key=lambda f: (f.path, f.line, f.rule)):
            print(f.render())
    suppressed = len(all_findings) - len(remaining)
    errors = [f for f in remaining if f.severity == Severity.ERROR]
    summary = f"analysis: {len(remaining)} finding(s)"
    if len(remaining) != len(errors):
        summary += f" ({len(remaining) - len(errors)} warning-only)"
    if suppressed:
        summary += f" ({suppressed} suppressed)"
    print(summary, file=sys.stderr)
    # warnings (e.g. "pass skipped: PyYAML unavailable") inform but don't
    # fail presubmit; only error-severity findings gate
    return 1 if errors else 0
