"""Pass 1: tracer-safety for the JAX kernels (ops/, solver/).

Inside a jit region every array is a tracer: Python ``if``/``while`` on a
traced value raises (or silently specializes), host materialization
(``float()``, ``.item()``, ``.tolist()``) forces a device sync per call,
and ``numpy``/``random``/``time`` execute at trace time with stale values.
The kernels avoid all of this by construction — branching only on static
Python scalars (shape components, ``static_argnames``) — and this pass
pins that convention.

Hosted on the shared dataflow core (analysis/core/): each traced function
is analyzed over its CFG with a forward fixpoint, so value kinds merge
correctly at branch joins and survive loop back-edges, and bare-name
calls to same-module helpers resolve through call-graph return-kind
summaries (``core.summaries``) instead of defaulting to static — a
helper that hands back a ``jnp`` result is traced at the call site even
when the jnp call sits several helper hops down (bottom-up propagation
over the module-set call graph; recursive clusters collapse to static).

Traced-function discovery (unchanged from the AST-walker generation):
- decorated with ``jax.jit`` (directly or via ``partial(jax.jit, ...)``);
- named ``solve_core*`` (the kernel entry naming convention);
- wrapped at module level (``solve_all = jax.jit(solve_core, ...)``);
- referenced from the body of any traced function (covers helpers passed
  as arguments, e.g. the ``packer`` callables), transitively across the
  scanned file set.

Value classification inside a traced function: unannotated positional
parameters are traced arrays; parameters with scalar annotations
(``int``/``bool``/``float``/``str``) or keyword-only parameters are trace-time
statics, as are ``.shape``/``.ndim``/``.size``/``.dtype``/``len()`` projections.
Locals inherit from their right-hand sides; at a branch join, traced wins.

Rules:
- TRC101: ``if``/``while``/ternary on a traced value
- TRC102: host materialization of a traced value
- TRC103: ``numpy``/``random``/``time`` use inside a jit region
- TRC104: Python loop over a traced value (data-dependent trip count)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import call_name, dotted_name
from .core.cfg import Atom, build_cfg
from .core.dataflow import Env, run_forward, sweep
from .core.lattice import Lattice
from .core.summaries import (
    ModuleInfo,
    SummaryTable,
    build_call_graph,
    load_modules,
    resolve_local,
)
from .findings import Finding, Severity, SourceFile

RULES = {
    "TRC100": "unparsable file (tracer pass)",
    "TRC101": "python if/while/ternary on a traced value",
    "TRC102": "host materialization of a traced value",
    "TRC103": "numpy/random/time use inside a jit region",
    "TRC104": "python loop over a traced value",
}

TRACED = 2
STATIC = 0

# taint-style lattice: traced is top, unbound names read as static
LATTICE = Lattice(top=TRACED, default=STATIC)

_STATIC_ANNOTATIONS = {"int", "bool", "float", "str"}
_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}
_STATIC_BUILTINS = {"len", "isinstance", "issubclass", "getattr", "hasattr",
                    "type", "repr", "str", "print"}
_PROPAGATING_BUILTINS = {"range", "min", "max", "sum", "abs", "enumerate",
                         "zip", "sorted", "reversed", "tuple", "list", "map",
                         "filter"}
_MATERIALIZERS = {"float", "int", "bool", "complex"}
_MATERIALIZER_METHODS = {"item", "tolist"}
_TRACED_ORIGINS = ("jax.numpy", "jax.lax", "jax.nn", "jax.scipy")
_HOST_ORIGINS = ("numpy", "random", "time")


def _collect_static_argnames(tree: ast.Module) -> Set[str]:
    """Names listed in any static_argnames=(...) in the module: they are
    trace-time statics wherever they appear as parameters."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.keyword) and node.arg == "static_argnames":
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out.add(sub.value)
    return out


def _is_jit_decorator(dec: ast.AST, aliases: Dict[str, str]) -> bool:
    name = dotted_name(dec)
    if name is None and isinstance(dec, ast.Call):
        cname = call_name(dec, aliases)
        if cname in ("functools.partial", "partial") and dec.args:
            inner = dotted_name(dec.args[0])
            if inner is not None:
                return _canonical(inner, aliases) in ("jax.jit", "jit")
        return cname in ("jax.jit", "jit")
    if name is None:
        return False
    return _canonical(name, aliases) in ("jax.jit", "jit")


def _canonical(name: str, aliases: Dict[str, str]) -> str:
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    return origin + ("." + rest if rest else "")


def _traced_functions(modules: Dict[str, ModuleInfo]) -> Set[Tuple[str, str]]:
    """Fixpoint of (module_path, function_name) trace roots + references."""
    traced: Set[Tuple[str, str]] = set()
    for mod in modules.values():
        for fname, fn in mod.index.functions.items():
            if fname.startswith("solve_core"):
                traced.add((mod.path, fname))
            if any(_is_jit_decorator(d, mod.aliases) for d in fn.decorator_list):
                traced.add((mod.path, fname))
        # module-level jax.jit(fn, ...) wrappers
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                if call_name(node, mod.aliases) in ("jax.jit", "jit") and node.args:
                    inner = dotted_name(node.args[0])
                    if inner and inner in mod.index.functions:
                        traced.add((mod.path, inner))
    # propagate through references from traced bodies
    changed = True
    while changed:
        changed = False
        for mod in modules.values():
            for fname, fn in mod.index.functions.items():
                if (mod.path, fname) not in traced:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                        hit = resolve_local(mod, node.id, modules)
                        if hit is not None:
                            key = (hit[0].path, hit[1].name)
                            if key not in traced:
                                traced.add(key)
                                changed = True
    return traced


class _FunctionAnalysis:
    """One traced function on the dataflow core: CFG fixpoint for the
    name->kind environment, then a deterministic check sweep."""

    def __init__(
        self,
        mod: ModuleInfo,
        modules: Dict[str, ModuleInfo],
        findings: List[Finding],
        summaries: Optional[SummaryTable],
    ):
        self.mod = mod
        self.modules = modules
        self.findings = findings
        self.summaries = summaries
        self._flagged_lines: Set[Tuple[int, str]] = set()

    # -- reporting --------------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if (line, rule) in self._flagged_lines:
            return
        self._flagged_lines.add((line, rule))
        self.findings.append(
            Finding(rule, Severity.ERROR, self.mod.path, line, message)
        )

    # -- classification ---------------------------------------------------

    def kind(self, node: ast.AST, env: Env) -> int:
        if isinstance(node, ast.Constant):
            return STATIC
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return STATIC
            return self.kind(node.value, env)
        if isinstance(node, ast.Subscript):
            return max(self.kind(node.value, env), self.kind(node.slice, env))
        if isinstance(node, ast.Call):
            return self._call_kind(node, env)
        if isinstance(node, ast.NamedExpr):
            return self.kind(node.value, env)
        if isinstance(node, (ast.BinOp,)):
            return max(self.kind(node.left, env), self.kind(node.right, env))
        if isinstance(node, ast.UnaryOp):
            return self.kind(node.operand, env)
        if isinstance(node, ast.BoolOp):
            return max((self.kind(v, env) for v in node.values), default=STATIC)
        if isinstance(node, ast.Compare):
            # `is None` / `is not None` inspect the python value, not data
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return STATIC
            return max(
                self.kind(node.left, env),
                max((self.kind(c, env) for c in node.comparators),
                    default=STATIC),
            )
        if isinstance(node, ast.IfExp):
            return max(self.kind(node.body, env), self.kind(node.orelse, env))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return max((self.kind(e, env) for e in node.elts), default=STATIC)
        if isinstance(node, ast.Starred):
            return self.kind(node.value, env)
        if isinstance(node, ast.Slice):
            parts = [p for p in (node.lower, node.upper, node.step) if p]
            return max((self.kind(p, env) for p in parts), default=STATIC)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return max(
                (self.kind(g.iter, env) for g in node.generators),
                default=STATIC,
            )
        return STATIC

    def _call_kind(self, node: ast.Call, env: Env) -> int:
        cname = call_name(node, self.mod.aliases)
        arg_kind = max(
            (self.kind(a, env) for a in list(node.args) +
             [kw.value for kw in node.keywords]),
            default=STATIC,
        )
        if cname:
            if any(cname == o or cname.startswith(o + ".") for o in _TRACED_ORIGINS):
                return TRACED
            if cname == "jax.jit":
                return STATIC
            if cname.startswith("jax."):
                return TRACED
            if cname in _STATIC_BUILTINS:
                return STATIC
            if cname in _PROPAGATING_BUILTINS or cname in _MATERIALIZERS:
                return arg_kind
        if isinstance(node.func, ast.Attribute):
            # method on a traced value yields a traced value
            if self.kind(node.func.value, env) == TRACED:
                return TRACED
        # interprocedural reach on the call graph: a bare-name call
        # resolving to a same-module (or from-import sibling) helper
        # returns the helper's summarized return kind — `hidden =
        # make_mask(x)` is traced when make_mask returns a jnp result,
        # even when the jnp call sits several helper hops down
        # (core.summaries: bottom-up propagation, SCC-collapsed cycles)
        raw = dotted_name(node.func)
        if (
            self.summaries is not None
            and raw is not None
            and "." not in raw
            and not env.has(raw)
        ):
            hit = resolve_local(self.mod, raw, self.modules)
            if hit is not None:
                ret = _return_kind(hit[0], hit[1], self.modules, self.summaries)
                return max(ret, arg_kind)
        return arg_kind

    def _traced_names(self, node: ast.AST, env: Env) -> List[str]:
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and env.get(sub.id) == TRACED:
                if sub.id not in out:
                    out.append(sub.id)
        return out

    # -- bindings (transfer function) -------------------------------------

    def _bind_target(self, target: ast.AST, kind: int, env: Env) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, kind)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, kind, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, kind, env)

    def _bind_walrus(self, node: ast.AST, env: Env) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.NamedExpr):
                self._bind_target(sub.target, self.kind(sub.value, env), env)

    def transfer(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            self._bind_walrus(node, env)
            if isinstance(node, ast.Assign):
                kind = self.kind(node.value, env)
                for target in node.targets:
                    self._bind_target(target, kind, env)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind_target(node.target, self.kind(node.value, env), env)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    prior = env.get(node.target.id)
                    env.set(
                        node.target.id,
                        max(prior, self.kind(node.value, env)),
                    )
        elif atom.kind == "test":
            self._bind_walrus(node, env)
        elif atom.kind == "for":
            self._bind_walrus(node.iter, env)
            self._bind_target(node.target, self.kind(node.iter, env), env)
        elif atom.kind == "with":
            self._bind_walrus(node.context_expr, env)
            if node.optional_vars is not None:
                self._bind_target(
                    node.optional_vars,
                    self.kind(node.context_expr, env),
                    env,
                )
        elif atom.kind == "except":
            if node.name:
                env.set(node.name, STATIC)

    # -- checks (sweep hook) ----------------------------------------------

    def check(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._check_expr(child, env)
        elif atom.kind == "test":
            if atom.label in ("if", "while"):
                self._check_branch(node, atom.label, env)
            self._check_expr(node, env)
        elif atom.kind == "for":
            if self.kind(node.iter, env) == TRACED:
                names = (
                    ", ".join(self._traced_names(node.iter, env))
                    or "a traced value"
                )
                self._flag(
                    "TRC104", node,
                    f"python loop over traced value(s) ({names}) unrolls "
                    "with a data-dependent trip count; use "
                    "lax.scan/fori_loop",
                )
            self._check_expr(node.iter, env)
        elif atom.kind == "with":
            self._check_expr(node.context_expr, env)
        elif atom.kind == "def":
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested function (scan/while bodies): params are traced
                # carries, analyzed against a snapshot of this env
                check_function(
                    self.mod, node, self.findings,
                    modules=self.modules, summaries=self.summaries,
                    parent_env=env,
                )
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        check_function(
                            self.mod, item, self.findings,
                            modules=self.modules, summaries=self.summaries,
                            parent_env=env,
                        )

    def _check_branch(self, test: ast.AST, what: str, env: Env) -> None:
        label = "conditional expression" if what == "ternary" else what
        if self.kind(test, env) == TRACED:
            names = ", ".join(self._traced_names(test, env)) or "a traced value"
            self._flag(
                "TRC101", test,
                f"python {label} branches on traced value(s) ({names}); "
                "use jnp.where/lax.cond or hoist to a static argument",
            )

    def _check_expr(self, node: ast.AST, env: Env) -> None:
        if isinstance(node, ast.Call):
            cname = call_name(node, self.mod.aliases)
            if cname in _MATERIALIZERS and node.args:
                if self.kind(node.args[0], env) == TRACED:
                    self._flag(
                        "TRC102", node,
                        f"{cname}() materializes a traced value on host "
                        "(forces a device sync per call inside jit)",
                    )
            if isinstance(node.func, ast.Attribute):
                if (
                    node.func.attr in _MATERIALIZER_METHODS
                    and self.kind(node.func.value, env) == TRACED
                ):
                    self._flag(
                        "TRC102", node,
                        f".{node.func.attr}() materializes a traced value "
                        "on host (forces a device sync per call inside "
                        "jit)",
                    )
        elif isinstance(node, ast.Name):
            origin = self.mod.aliases.get(node.id, "")
            if origin in _HOST_ORIGINS and isinstance(node.ctx, ast.Load):
                self._flag(
                    "TRC103", node,
                    f"host module '{origin}' used inside a jit region: it "
                    "runs at trace time, not per execution",
                )
        elif isinstance(node, ast.IfExp):
            self._check_branch(node.test, "ternary", env)
        elif isinstance(node, ast.NamedExpr):
            # keep intra-statement ordering: later subexpressions of this
            # atom see the walrus binding (transfer re-applies it after)
            self._check_expr(node.value, env)
            self._bind_target(node.target, self.kind(node.value, env), env)
            return
        elif isinstance(node, ast.Lambda):
            env_l = Env(LATTICE, dict(env.kinds))
            for arg in node.args.args + node.args.kwonlyargs:
                env_l.set(arg.arg, TRACED)
            sub = _FunctionAnalysis(
                self.mod, self.modules, self.findings, self.summaries
            )
            sub._flagged_lines = self._flagged_lines
            sub._check_expr(node.body, env_l)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword)):
                self._check_expr(child, env)
            elif isinstance(child, ast.FormattedValue):
                self._check_expr(child.value, env)

def _param_env(
    mod: ModuleInfo, fn: ast.FunctionDef, parent_env: Optional[Env]
) -> Env:
    static_names = getattr(mod, "static_names", set())
    base = dict(parent_env.kinds) if parent_env is not None else {}
    env = Env(LATTICE, base)
    for arg in fn.args.posonlyargs + fn.args.args:
        ann = dotted_name(arg.annotation) if arg.annotation is not None else None
        static = (
            (ann in _STATIC_ANNOTATIONS)
            or arg.arg in static_names
            or arg.arg == "self"
        )
        env.set(arg.arg, STATIC if static else TRACED)
    for arg in fn.args.kwonlyargs:
        env.set(arg.arg, STATIC)  # statics ride keyword-only by convention
    if fn.args.vararg is not None:
        env.set(fn.args.vararg.arg, TRACED)
    if fn.args.kwarg is not None:
        env.set(fn.args.kwarg.arg, STATIC)
    return env


def _return_kind(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    modules: Dict[str, ModuleInfo],
    summaries: SummaryTable,
) -> int:
    """Return-kind summary on the call graph: the helper's own fixpoint
    with nested helper calls resolved through the SAME table, joined
    over every return expression — facts propagate bottom-up through any
    number of hops, and the table's SCC collapse keeps recursive
    clusters at the default."""

    def compute() -> int:
        analysis = _FunctionAnalysis(
            mod, modules, findings=[], summaries=summaries
        )
        init = _param_env(mod, fn, None)
        cfg = build_cfg(fn.body)
        envs = run_forward(cfg, init, analysis.transfer)
        out = [STATIC]

        def check(atom: Atom, env: Env) -> None:
            if (
                atom.kind == "stmt"
                and isinstance(atom.node, ast.Return)
                and atom.node.value is not None
            ):
                out.append(analysis.kind(atom.node.value, env))

        sweep(cfg, envs, init, analysis.transfer, check)
        return max(out)

    return summaries.get((mod.path, fn.name), compute)


def check_function(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    findings: List[Finding],
    modules: Optional[Dict[str, ModuleInfo]] = None,
    summaries: Optional[SummaryTable] = None,
    parent_env: Optional[Env] = None,
) -> None:
    modules = modules if modules is not None else {mod.path: mod}
    analysis = _FunctionAnalysis(mod, modules, findings, summaries)
    init = _param_env(mod, fn, parent_env)
    cfg = build_cfg(fn.body)
    envs = run_forward(cfg, init, analysis.transfer)
    sweep(cfg, envs, init, analysis.transfer, analysis.check)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the tracer-safety pass; returns (findings, sources-by-path)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("TRC100", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    for mod in modules.values():
        mod.static_names = _collect_static_argnames(mod.tree)

    summaries = SummaryTable(default=STATIC, graph=build_call_graph(modules))
    traced = _traced_functions(modules)
    for mod in modules.values():
        for fname, fn in mod.index.functions.items():
            if (mod.path, fname) in traced:
                check_function(
                    mod, fn, findings, modules=modules, summaries=summaries
                )
    return findings, sources
