"""Pass 1: tracer-safety for the JAX kernels (ops/, solver/).

Inside a jit region every array is a tracer: Python ``if``/``while`` on a
traced value raises (or silently specializes), host materialization
(``float()``, ``.item()``, ``.tolist()``) forces a device sync per call,
and ``numpy``/``random``/``time`` execute at trace time with stale values.
The kernels avoid all of this by construction — branching only on static
Python scalars (shape components, ``static_argnames``) — and this pass
pins that convention.

Traced-function discovery:
- decorated with ``jax.jit`` (directly or via ``partial(jax.jit, ...)``);
- named ``solve_core*`` (the kernel entry naming convention);
- wrapped at module level (``solve_all = jax.jit(solve_core, ...)``);
- referenced from the body of any traced function (covers helpers passed
  as arguments, e.g. the ``packer`` callables), transitively across the
  scanned file set.

Value classification inside a traced function: unannotated positional
parameters are traced arrays; parameters with scalar annotations
(``int``/``bool``/``float``/``str``) or keyword-only parameters are trace-time
statics, as are ``.shape``/``.ndim``/``.size``/``.dtype``/``len()`` projections.
Locals inherit from their right-hand sides.

Rules:
- TRC101: ``if``/``while``/ternary on a traced value
- TRC102: host materialization of a traced value
- TRC103: ``numpy``/``random``/``time`` use inside a jit region
- TRC104: Python loop over a traced value (data-dependent trip count)
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .astutil import (
    FunctionIndex,
    call_name,
    dotted_name,
    import_aliases,
    iter_py_files,
    parse_file,
)
from .findings import Finding, Severity, SourceFile

RULES = {
    "TRC100": "unparsable file (tracer pass)",
    "TRC101": "python if/while/ternary on a traced value",
    "TRC102": "host materialization of a traced value",
    "TRC103": "numpy/random/time use inside a jit region",
    "TRC104": "python loop over a traced value",
}

TRACED = 2
STATIC = 0

_STATIC_ANNOTATIONS = {"int", "bool", "float", "str"}
_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}
_STATIC_BUILTINS = {"len", "isinstance", "issubclass", "getattr", "hasattr",
                    "type", "repr", "str", "print"}
_PROPAGATING_BUILTINS = {"range", "min", "max", "sum", "abs", "enumerate",
                         "zip", "sorted", "reversed", "tuple", "list", "map",
                         "filter"}
_MATERIALIZERS = {"float", "int", "bool", "complex"}
_MATERIALIZER_METHODS = {"item", "tolist"}
_TRACED_ORIGINS = ("jax.numpy", "jax.lax", "jax.nn", "jax.scipy")
_HOST_ORIGINS = ("numpy", "random", "time")


class _Env:
    def __init__(self, parent: Optional["_Env"] = None):
        self.parent = parent
        self.kinds: Dict[str, int] = {}

    def get(self, name: str) -> Optional[int]:
        env: Optional[_Env] = self
        while env is not None:
            if name in env.kinds:
                return env.kinds[name]
            env = env.parent
        return None

    def set(self, name: str, kind: int) -> None:
        self.kinds[name] = kind


class _Module:
    def __init__(self, path: str, src: SourceFile, tree: ast.Module):
        self.path = path
        self.src = src
        self.tree = tree
        self.aliases = import_aliases(tree)
        self.index = FunctionIndex(tree)
        self.static_names: Set[str] = _collect_static_argnames(tree)


def _collect_static_argnames(tree: ast.Module) -> Set[str]:
    """Names listed in any static_argnames=(...) in the module: they are
    trace-time statics wherever they appear as parameters."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.keyword) and node.arg == "static_argnames":
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    out.add(sub.value)
    return out


def _is_jit_decorator(dec: ast.AST, aliases: Dict[str, str]) -> bool:
    name = dotted_name(dec)
    if name is None and isinstance(dec, ast.Call):
        cname = call_name(dec, aliases)
        if cname in ("functools.partial", "partial") and dec.args:
            inner = dotted_name(dec.args[0])
            if inner is not None:
                return _canonical(inner, aliases) in ("jax.jit", "jit")
        return cname in ("jax.jit", "jit")
    if name is None:
        return False
    return _canonical(name, aliases) in ("jax.jit", "jit")


def _canonical(name: str, aliases: Dict[str, str]) -> str:
    head, _, rest = name.partition(".")
    origin = aliases.get(head, head)
    return origin + ("." + rest if rest else "")


def _resolve_function(
    mod: _Module, name: str, modules: Dict[str, _Module]
) -> Optional[Tuple[_Module, ast.FunctionDef]]:
    """Resolve a bare name used in ``mod`` to a function def in the scanned
    set — locally, or through a ``from .x import name`` alias."""
    if name in mod.index.functions:
        return mod, mod.index.functions[name]
    origin = mod.aliases.get(name)
    if not origin or "." not in origin:
        return None
    mod_part, _, fn_name = origin.rpartition(".")
    base = mod_part.lstrip(".") or ""
    tail = base.rpartition(".")[2] if base else ""
    for other in modules.values():
        stem = os.path.splitext(os.path.basename(other.path))[0]
        if stem == tail and fn_name in other.index.functions:
            return other, other.index.functions[fn_name]
    return None


def _traced_functions(modules: Dict[str, _Module]) -> Set[Tuple[str, str]]:
    """Fixpoint of (module_path, function_name) trace roots + references."""
    traced: Set[Tuple[str, str]] = set()
    for mod in modules.values():
        for fname, fn in mod.index.functions.items():
            if fname.startswith("solve_core"):
                traced.add((mod.path, fname))
            if any(_is_jit_decorator(d, mod.aliases) for d in fn.decorator_list):
                traced.add((mod.path, fname))
        # module-level jax.jit(fn, ...) wrappers
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                if call_name(node, mod.aliases) in ("jax.jit", "jit") and node.args:
                    inner = dotted_name(node.args[0])
                    if inner and inner in mod.index.functions:
                        traced.add((mod.path, inner))
    # propagate through references from traced bodies
    changed = True
    while changed:
        changed = False
        for mod in modules.values():
            for fname, fn in mod.index.functions.items():
                if (mod.path, fname) not in traced:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                        hit = _resolve_function(mod, node.id, modules)
                        if hit is not None:
                            key = (hit[0].path, hit[1].name)
                            if key not in traced:
                                traced.add(key)
                                changed = True
    return traced


class _FunctionChecker(ast.NodeVisitor):
    """Sequentially walks one traced function, tracking value kinds."""

    def __init__(self, mod: _Module, findings: List[Finding], env: _Env):
        self.mod = mod
        self.findings = findings
        self.env = env
        self._flagged_lines: Set[Tuple[int, str]] = set()

    # -- reporting --------------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if (line, rule) in self._flagged_lines:
            return
        self._flagged_lines.add((line, rule))
        self.findings.append(
            Finding(rule, Severity.ERROR, self.mod.path, line, message)
        )

    # -- classification ---------------------------------------------------

    def kind(self, node: ast.AST) -> int:
        if isinstance(node, ast.Constant):
            return STATIC
        if isinstance(node, ast.Name):
            known = self.env.get(node.id)
            return known if known is not None else STATIC
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return STATIC
            return self.kind(node.value)
        if isinstance(node, ast.Subscript):
            return max(self.kind(node.value), self.kind(node.slice))
        if isinstance(node, ast.Call):
            return self._call_kind(node)
        if isinstance(node, (ast.BinOp,)):
            return max(self.kind(node.left), self.kind(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.kind(node.operand)
        if isinstance(node, ast.BoolOp):
            return max((self.kind(v) for v in node.values), default=STATIC)
        if isinstance(node, ast.Compare):
            # `is None` / `is not None` inspect the python value, not data
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return STATIC
            return max(
                self.kind(node.left),
                max((self.kind(c) for c in node.comparators), default=STATIC),
            )
        if isinstance(node, ast.IfExp):
            return max(self.kind(node.body), self.kind(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return max((self.kind(e) for e in node.elts), default=STATIC)
        if isinstance(node, ast.Starred):
            return self.kind(node.value)
        if isinstance(node, ast.Slice):
            parts = [p for p in (node.lower, node.upper, node.step) if p]
            return max((self.kind(p) for p in parts), default=STATIC)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return max(
                (self.kind(g.iter) for g in node.generators), default=STATIC
            )
        return STATIC

    def _call_kind(self, node: ast.Call) -> int:
        cname = call_name(node, self.mod.aliases)
        arg_kind = max(
            (self.kind(a) for a in list(node.args) +
             [kw.value for kw in node.keywords]),
            default=STATIC,
        )
        if cname:
            if any(cname == o or cname.startswith(o + ".") for o in _TRACED_ORIGINS):
                return TRACED
            if cname == "jax.jit":
                return STATIC
            if cname.startswith("jax."):
                return TRACED
            if cname in _STATIC_BUILTINS:
                return STATIC
            if cname in _PROPAGATING_BUILTINS or cname in _MATERIALIZERS:
                return arg_kind
        if isinstance(node.func, ast.Attribute):
            # method on a traced value yields a traced value
            if self.kind(node.func.value) == TRACED:
                return TRACED
        return arg_kind

    def _traced_names(self, node: ast.AST) -> List[str]:
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and self.env.get(sub.id) == TRACED:
                if sub.id not in out:
                    out.append(sub.id)
        return out

    # -- bindings ---------------------------------------------------------

    def _bind_target(self, target: ast.AST, kind: int) -> None:
        if isinstance(target, ast.Name):
            self.env.set(target.id, kind)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, kind)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, kind)

    # -- statement visitors ----------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        kind = self.kind(node.value)
        for target in node.targets:
            self._bind_target(target, kind)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None:
            self._bind_target(node.target, self.kind(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if isinstance(node.target, ast.Name):
            prior = self.env.get(node.target.id) or STATIC
            self.env.set(node.target.id, max(prior, self.kind(node.value)))

    def visit_NamedExpr(self, node: ast.NamedExpr) -> None:
        self.generic_visit(node)
        self._bind_target(node.target, self.kind(node.value))

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node.test, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_branch(node.test, "conditional expression")
        self.generic_visit(node)

    def _check_branch(self, test: ast.AST, what: str) -> None:
        if self.kind(test) == TRACED:
            names = ", ".join(self._traced_names(test)) or "a traced value"
            self._flag(
                "TRC101", test,
                f"python {what} branches on traced value(s) ({names}); "
                "use jnp.where/lax.cond or hoist to a static argument",
            )

    def visit_For(self, node: ast.For) -> None:
        iter_kind = self.kind(node.iter)
        if iter_kind == TRACED:
            names = ", ".join(self._traced_names(node.iter)) or "a traced value"
            self._flag(
                "TRC104", node,
                f"python loop over traced value(s) ({names}) unrolls with a "
                "data-dependent trip count; use lax.scan/fori_loop",
            )
        self._bind_target(node.target, iter_kind)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        cname = call_name(node, self.mod.aliases)
        if cname in _MATERIALIZERS and node.args:
            if self.kind(node.args[0]) == TRACED:
                self._flag(
                    "TRC102", node,
                    f"{cname}() materializes a traced value on host "
                    "(forces a device sync per call inside jit)",
                )
        if isinstance(node.func, ast.Attribute):
            if (
                node.func.attr in _MATERIALIZER_METHODS
                and self.kind(node.func.value) == TRACED
            ):
                self._flag(
                    "TRC102", node,
                    f".{node.func.attr}() materializes a traced value on "
                    "host (forces a device sync per call inside jit)",
                )
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        origin = self.mod.aliases.get(node.id, "")
        if origin in _HOST_ORIGINS and isinstance(node.ctx, ast.Load):
            self._flag(
                "TRC103", node,
                f"host module '{origin}' used inside a jit region: it runs "
                "at trace time, not per execution",
            )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested function (scan/while bodies): params are traced carries
        check_function(self.mod, node, self.findings, parent_env=self.env)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        env = _Env(parent=self.env)
        for arg in node.args.args + node.args.kwonlyargs:
            env.set(arg.arg, TRACED)
        sub = _FunctionChecker(self.mod, self.findings, env)
        sub.visit(node.body)


def _param_env(
    mod: _Module, fn: ast.FunctionDef, parent_env: Optional[_Env]
) -> _Env:
    env = _Env(parent=parent_env)
    for arg in fn.args.posonlyargs + fn.args.args:
        ann = dotted_name(arg.annotation) if arg.annotation is not None else None
        static = (
            (ann in _STATIC_ANNOTATIONS)
            or arg.arg in mod.static_names
            or arg.arg == "self"
        )
        env.set(arg.arg, STATIC if static else TRACED)
    for arg in fn.args.kwonlyargs:
        env.set(arg.arg, STATIC)  # statics ride keyword-only by convention
    if fn.args.vararg is not None:
        env.set(fn.args.vararg.arg, TRACED)
    if fn.args.kwarg is not None:
        env.set(fn.args.kwarg.arg, STATIC)
    return env


def check_function(
    mod: _Module,
    fn: ast.FunctionDef,
    findings: List[Finding],
    parent_env: Optional[_Env] = None,
) -> None:
    env = _param_env(mod, fn, parent_env)
    checker = _FunctionChecker(mod, findings, env)
    for stmt in fn.body:
        checker.visit(stmt)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the tracer-safety pass; returns (findings, sources-by-path)."""
    modules: Dict[str, _Module] = {}
    sources: Dict[str, SourceFile] = {}
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            src, tree = parse_file(path)
        except (OSError, SyntaxError) as exc:
            findings.append(
                Finding("TRC100", Severity.ERROR, path, 0, f"unparsable: {exc}")
            )
            continue
        modules[path] = _Module(path, src, tree)
        sources[path] = src

    traced = _traced_functions(modules)
    for mod in modules.values():
        for fname, fn in mod.index.functions.items():
            if (mod.path, fname) in traced:
                check_function(mod, fn, findings)
    return findings, sources
