"""Pass 9: device-residency discipline (DTX9xx) for the solve path.

The ROADMAP's device-resident-tensors + delta-encode refactor only pays
off if the formulation genuinely stays on device: one stray host sync —
a truthiness test on a device array, ``float()``/``.item()``, an
``np.asarray`` on a device value, iteration, a print — silently reads
the array back, serializing the dispatch pipeline the async
double-buffering is supposed to hide. This pass machine-checks the
boundary.

Hosted on the dataflow core: values originating from ``jnp.*`` /
``jax.device_put`` / kernel-dispatch returns (``dispatch_*`` /
``solve_all*`` by the ops/solve.py naming convention) are tracked as
DEVICE through assignments, attributes, tuple unpacks, and helper calls
— return-kind summaries propagate bottom-up over the module-set call
graph (core.summaries), so a device origin buried several helper hops
down still reaches the call site; everything the analysis loses track
of joins to UNKNOWN and never flags (poison-to-unknown), and recursive
helper clusters collapse to UNKNOWN by SCC. Host-sync sinks flag
only on *definite* device values:

- DTX901: truthiness — ``if``/``while``/``assert``/ternary/``not``/
  ``bool()`` on a device value
- DTX902: host materialization — ``float()``/``int()``/``complex()``,
  ``.item()``/``.tolist()``/``.tobytes()``
- DTX903: host-numpy call (``np.asarray``/``np.array``/any ``numpy.*``)
  on a device value — an implicit ``device_get``
- DTX904: Python iteration over a device value (``for``, unpacking,
  ``list()``/``sorted()``/``min()``/...)
- DTX905: ``print``/f-string/``str()`` interpolation of a device value
- DTX906: explicit host readback — every ``jax.device_get`` call. This
  one is not an error to *have*; it is an error to have UNSANCTIONED:
  the blessed decode/guard boundary carries
  ``# analysis: sanctioned[DTX906] reason`` annotations, PARITY.md's
  device-residency contract lists them, and the delta-encode PR must
  not widen the set. (A sanction is an audited boundary marker, not a
  suppression — see findings.py.)

**No host crossing between solves** (the delta-encode extension the
contract table designed for): attributes named by the resident
convention — ``dev_*`` / ``_dev*`` — hold device values ACROSS solves
(solver/residency.py's buffer store), so loads from them are DEVICE-born
no matter what object carries them. A delta path that launders a
resident buffer through ``np.asarray`` between solves flags DTX903, an
iteration DTX904, a ``device_get`` outside the sanctioned drain DTX906 —
the same sinks, now reachable through persistent state the
poison-to-unknown discipline used to hide. One rule of origin, the
existing rules of sin.

``jax.device_get`` and sanctioned sinks yield HOST downstream, so the
decode path (all host numpy after the readback) stays silent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import call_name, dotted_name
from .core.cfg import Atom, build_cfg
from .core.dataflow import Env, run_forward, sweep
from .core.lattice import Lattice
from .core.summaries import (
    ModuleInfo,
    SummaryTable,
    build_call_graph,
    load_modules,
    resolve_local,
)
from .findings import Finding, Severity, SourceFile

RULES = {
    "DTX900": "unparsable file (device-residency pass)",
    "DTX901": "truthiness/branch on a device value (host sync)",
    "DTX902": "host materialization of a device value",
    "DTX903": "host-numpy call on a device value (implicit device_get)",
    "DTX904": "python iteration over a device value (host sync)",
    "DTX905": "print/f-string interpolation of a device value",
    "DTX906": "device->host readback outside a sanctioned boundary",
}

HOST = 0
DEVICE = 1
UNKNOWN = 2  # poison: lost track -> never flag

LATTICE = Lattice(top=UNKNOWN, default=HOST)

_DEVICE_ORIGINS = ("jax.numpy", "jax.lax", "jax.nn", "jax.scipy")
# jax APIs that return host/python values (or are control surface)
_HOST_JAX = (
    "jax.device_get", "jax.devices", "jax.local_devices",
    "jax.device_count", "jax.local_device_count", "jax.default_backend",
    "jax.named_scope", "jax.config", "jax.profiler", "jax.debug",
    "jax.tree_util", "jax.eval_shape",
)
# kernel-dispatch naming convention (ops/solve.py): these return device
# arrays by contract even through the fault-seam wrappers
_DISPATCH_PREFIXES = ("dispatch_", "solve_all")
# device-resident attribute naming convention (solver/residency.py):
# attributes holding device buffers BETWEEN solves — loads are
# DEVICE-born, so host sinks on them flag even though the carrying
# object itself is untracked ("no host crossing between solves")
_RESIDENT_ATTR_PREFIXES = ("dev_", "_dev")

_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
_MATERIALIZERS = {"float", "int", "complex"}
_MATERIALIZER_METHODS = {"item", "tolist", "tobytes"}
_ITERATORS = {"list", "tuple", "set", "sorted", "sum", "min", "max",
              "any", "all", "iter", "enumerate", "zip", "map", "filter",
              "frozenset"}
_STRINGIFIERS = {"str", "repr", "format", "print"}
_HOST_BUILTINS = {"len", "isinstance", "issubclass", "getattr", "hasattr",
                  "type", "range", "id", "callable"}


class _DeviceAnalysis:
    """One function (or module body) under the device-residency lattice."""

    def __init__(
        self,
        mod: ModuleInfo,
        modules: Dict[str, ModuleInfo],
        findings: List[Finding],
        summaries: Optional[SummaryTable],
    ):
        self.mod = mod
        self.modules = modules
        self.findings = findings
        self.summaries = summaries
        self._flagged: Set[Tuple[int, str]] = set()
        # return-kind summaries of nested defs seen in this scope, joined
        # across conditional re-definitions
        self._local_ret: Dict[str, int] = {}

    # -- reporting --------------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if (line, rule) in self._flagged:
            return
        self._flagged.add((line, rule))
        self.findings.append(
            Finding(rule, Severity.ERROR, self.mod.path, line, message)
        )

    # -- classification ---------------------------------------------------

    def kind(self, node: ast.AST, env: Env) -> int:
        if isinstance(node, ast.Constant):
            return HOST
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return HOST
            if node.attr.startswith(_RESIDENT_ATTR_PREFIXES):
                # the device-resident naming convention: dev_*/_dev*
                # attributes hold device buffers between solves
                # (PARITY.md device-residency contract), so a load is
                # DEVICE-born regardless of the carrying object
                return DEVICE
            return self.kind(node.value, env)
        if isinstance(node, ast.Subscript):
            return self.kind(node.value, env)
        if isinstance(node, ast.Call):
            return self._call_kind(node, env)
        if isinstance(node, ast.NamedExpr):
            return self.kind(node.value, env)
        if isinstance(node, ast.BinOp):
            return max(self.kind(node.left, env), self.kind(node.right, env))
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.Not):
                return HOST  # truthiness flagged as a sink, result is bool
            return self.kind(node.operand, env)
        if isinstance(node, ast.BoolOp):
            return max((self.kind(v, env) for v in node.values), default=HOST)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in node.ops):
                return HOST
            return max(
                self.kind(node.left, env),
                max((self.kind(c, env) for c in node.comparators),
                    default=HOST),
            )
        if isinstance(node, ast.IfExp):
            return max(self.kind(node.body, env), self.kind(node.orelse, env))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return max((self.kind(e, env) for e in node.elts), default=HOST)
        if isinstance(node, ast.Starred):
            return self.kind(node.value, env)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return max(
                (self.kind(g.iter, env) for g in node.generators),
                default=HOST,
            )
        if isinstance(node, ast.JoinedStr):
            return HOST
        if isinstance(node, (ast.Dict, ast.DictComp)):
            return UNKNOWN
        if isinstance(node, ast.Lambda):
            return UNKNOWN
        if isinstance(node, ast.Slice):
            return HOST
        return UNKNOWN

    def _call_kind(self, node: ast.Call, env: Env) -> int:
        cname = call_name(node, self.mod.aliases)
        if cname:
            last = cname.rpartition(".")[2]
            if cname == "jax.device_get":
                return HOST  # the readback itself is checked as DTX906
            if cname == "jax.block_until_ready" and node.args:
                return self.kind(node.args[0], env)
            if any(cname == h or cname.startswith(h + ".") for h in _HOST_JAX):
                return HOST
            if any(cname == o or cname.startswith(o + ".")
                   for o in _DEVICE_ORIGINS):
                return DEVICE
            if cname == "jax.device_put":
                return DEVICE
            if cname in ("jax.jit", "jax.vmap", "jax.pmap", "jax.grad"):
                return UNKNOWN  # a callable, not an array
            if cname.startswith("jax."):
                return DEVICE
            if last.startswith(_DISPATCH_PREFIXES):
                return DEVICE
            head = cname.partition(".")[0]
            origin = self.mod.aliases.get(head, head)
            if origin == "numpy" or cname.startswith("numpy."):
                return HOST  # numpy returns host arrays (sink checked)
            if cname in _MATERIALIZERS or cname in _STRINGIFIERS:
                return HOST
            if cname in _HOST_BUILTINS:
                return HOST
            if cname in ("bool",):
                return HOST
            if cname in _ITERATORS:
                return UNKNOWN
        raw = dotted_name(node.func)
        if raw is not None and "." not in raw:
            if raw in self._local_ret:
                return self._local_ret[raw]
            if self.summaries is not None and not env.has(raw):
                hit = resolve_local(self.mod, raw, self.modules)
                if hit is not None:
                    return _return_kind(
                        hit[0], hit[1], self.modules, self.summaries
                    )
        if isinstance(node.func, ast.Attribute):
            recv = self.kind(node.func.value, env)
            if recv == DEVICE:
                if node.func.attr in _MATERIALIZER_METHODS:
                    return HOST  # flagged as DTX902 at the check
                return DEVICE  # .astype/.sum/.reshape/... stay on device
            if recv == HOST:
                return HOST
        return UNKNOWN

    def _device_names(self, node: ast.AST, env: Env) -> str:
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and env.get(sub.id) == DEVICE:
                if sub.id not in out:
                    out.append(sub.id)
        return ", ".join(out) or "a device value"

    # -- transfer ---------------------------------------------------------

    def _bind_target(self, target: ast.AST, value: Optional[ast.AST],
                     kind: int, env: Env) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, kind)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._bind_target(t, v, self.kind(v, env), env)
                return
            # tuple returns from jax calls (lax.scan, kernel outputs)
            # unpack without host iteration: elements inherit the kind
            for elt in target.elts:
                self._bind_target(elt, None, kind, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, None, kind, env)

    def _bind_walrus(self, node: ast.AST, env: Env) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.NamedExpr) and isinstance(
                sub.target, ast.Name
            ):
                env.set(sub.target.id, self.kind(sub.value, env))

    def transfer(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            self._bind_walrus(node, env)
            if isinstance(node, ast.Assign):
                kind = self.kind(node.value, env)
                for target in node.targets:
                    self._bind_target(target, node.value, kind, env)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind_target(
                    node.target, node.value, self.kind(node.value, env), env
                )
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    env.set(
                        node.target.id,
                        max(env.get(node.target.id),
                            self.kind(node.value, env)),
                    )
        elif atom.kind == "test":
            self._bind_walrus(node, env)
        elif atom.kind == "for":
            self._bind_walrus(node.iter, env)
            iter_kind = self.kind(node.iter, env)
            elem = UNKNOWN if iter_kind != HOST else HOST
            self._bind_target(node.target, None, elem, env)
        elif atom.kind == "with":
            self._bind_walrus(node.context_expr, env)
            if node.optional_vars is not None:
                self._bind_target(
                    node.optional_vars, None, UNKNOWN, env
                )
        elif atom.kind == "except":
            if node.name:
                env.set(node.name, HOST)
        elif atom.kind == "def":
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ret = self._nested_return_kind(node, env)
                prior = self._local_ret.get(node.name)
                self._local_ret[node.name] = (
                    ret if prior is None else max(prior, ret)
                )

    def _nested_return_kind(self, fn: ast.AST, env: Env) -> int:
        """Return-kind summary of a nested def against a snapshot of the
        enclosing scope (closures over device values resolve)."""
        sub = _DeviceAnalysis(self.mod, self.modules, [], self.summaries)
        init = _param_env(fn, Env(LATTICE, dict(env.kinds)))
        cfg = build_cfg(fn.body)
        envs = run_forward(cfg, init, sub.transfer)
        out = [HOST]

        def collect(atom: Atom, e: Env) -> None:
            if (
                atom.kind == "stmt"
                and isinstance(atom.node, ast.Return)
                and atom.node.value is not None
            ):
                out.append(sub.kind(atom.node.value, e))

        sweep(cfg, envs, init, sub.transfer, collect)
        return max(out)

    # -- checks -----------------------------------------------------------

    def check(self, atom: Atom, env: Env) -> None:
        node = atom.node
        if atom.kind == "stmt":
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._check_expr(child, env)
        elif atom.kind == "test":
            if atom.label in ("if", "while", "assert"):
                self._check_truthiness(node, atom.label, env)
            self._check_expr(node, env)
        elif atom.kind == "for":
            if self.kind(node.iter, env) == DEVICE:
                self._flag(
                    "DTX904", node,
                    f"python loop over device value(s) "
                    f"({self._device_names(node.iter, env)}) syncs once "
                    "per element; keep the loop on device or read back "
                    "at the decode boundary",
                )
            self._check_expr(node.iter, env)
        elif atom.kind == "with":
            self._check_expr(node.context_expr, env)
        elif atom.kind == "def":
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(
                    self.mod, node, self.findings, self.modules,
                    self.summaries, parent_env=env, shared_flags=self._flagged,
                )
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        _check_function(
                            self.mod, item, self.findings, self.modules,
                            self.summaries, parent_env=env,
                            shared_flags=self._flagged,
                        )

    def _check_truthiness(self, test: ast.AST, what: str, env: Env) -> None:
        nodes = (
            list(test.values) if isinstance(test, ast.BoolOp) else [test]
        )
        for n in nodes:
            target = n.operand if (
                isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not)
            ) else n
            if self.kind(target, env) == DEVICE:
                self._flag(
                    "DTX901", test,
                    f"python {what} on device value(s) "
                    f"({self._device_names(target, env)}) forces a host "
                    "sync; branch on host metadata or use jnp.where",
                )

    def _check_expr(self, node: ast.AST, env: Env) -> None:
        if isinstance(node, ast.Call):
            self._check_call(node, env)
        elif isinstance(node, ast.IfExp):
            self._check_truthiness(node.test, "ternary", env)
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            if self.kind(node.operand, env) == DEVICE:
                self._flag(
                    "DTX901", node,
                    "`not` on a device value forces a host sync; compare "
                    "on host metadata or keep the predicate on device",
                )
        elif isinstance(node, ast.FormattedValue):
            if self.kind(node.value, env) == DEVICE:
                self._flag(
                    "DTX905", node,
                    "f-string interpolation of a device value syncs it to "
                    "host; log host metadata or defer to the decode "
                    "boundary",
                )
        elif isinstance(node, ast.NamedExpr):
            self._check_expr(node.value, env)
            if isinstance(node.target, ast.Name):
                env.set(node.target.id, self.kind(node.value, env))
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword,
                                  ast.FormattedValue)):
                self._check_expr(child, env)

    def _check_call(self, node: ast.Call, env: Env) -> None:
        cname = call_name(node, self.mod.aliases)
        arg_kinds = [self.kind(a, env) for a in node.args]
        # sanctioned sites still emit: partition_findings routes them into
        # the sanctioned channel, which is how the CLI counts the blessed
        # boundary and how the stale audit sees a marker is live
        if cname == "jax.device_get":
            self._flag(
                "DTX906", node,
                "jax.device_get is a device->host readback; the "
                "blessed decode/guard boundary must carry an "
                "`# analysis: sanctioned[DTX906]` annotation "
                "(PARITY.md device-residency contract)",
            )
        elif cname in _MATERIALIZERS and DEVICE in arg_kinds:
            self._flag(
                "DTX902", node,
                f"{cname}() materializes a device value on host "
                "(one blocking sync per call)",
            )
        elif cname == "bool" and DEVICE in arg_kinds:
            self._flag(
                "DTX901", node,
                "bool() on a device value forces a host sync",
            )
        elif cname in _ITERATORS and DEVICE in arg_kinds:
            self._flag(
                "DTX904", node,
                f"{cname}() iterates a device value on host (one "
                "sync per element)",
            )
        elif cname in _STRINGIFIERS and DEVICE in arg_kinds:
            self._flag(
                "DTX905", node,
                f"{cname}() renders a device value on host (blocking "
                "sync); print host metadata instead",
            )
        else:
            head = cname.partition(".")[0] if cname else ""
            origin = self.mod.aliases.get(head, head)
            if (origin == "numpy" or cname.startswith("numpy.")) and (
                DEVICE in arg_kinds
                or any(
                    self.kind(kw.value, env) == DEVICE
                    for kw in node.keywords
                )
            ):
                self._flag(
                    "DTX903", node,
                    f"{cname} on a device value is an implicit "
                    "device_get; read back once at the sanctioned "
                    "decode boundary instead",
                )
        if isinstance(node.func, ast.Attribute):
            if (
                node.func.attr in _MATERIALIZER_METHODS
                and self.kind(node.func.value, env) == DEVICE
            ):
                self._flag(
                    "DTX902", node,
                    f".{node.func.attr}() materializes a device value on "
                    "host (one blocking sync per call)",
                )


def _param_env(fn: ast.AST, base: Env) -> Env:
    """Parameters are UNKNOWN: the pass only tracks values whose device
    origin it can see (poison-to-unknown keeps helper params silent)."""
    env = base
    args = fn.args
    for arg in args.posonlyargs + args.args + args.kwonlyargs:
        env.set(arg.arg, UNKNOWN)
    if args.vararg is not None:
        env.set(args.vararg.arg, UNKNOWN)
    if args.kwarg is not None:
        env.set(args.kwarg.arg, UNKNOWN)
    return env


def _return_kind(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    modules: Dict[str, ModuleInfo],
    summaries: SummaryTable,
) -> int:
    """Call-graph helper summary: nested helper calls resolve through
    the same table (bottom-up, SCC-collapsed to UNKNOWN)."""

    def compute() -> int:
        analysis = _DeviceAnalysis(mod, modules, [], summaries=summaries)
        init = _param_env(fn, Env(LATTICE))
        cfg = build_cfg(fn.body)
        envs = run_forward(cfg, init, analysis.transfer)
        out = [HOST]

        def collect(atom: Atom, env: Env) -> None:
            if (
                atom.kind == "stmt"
                and isinstance(atom.node, ast.Return)
                and atom.node.value is not None
            ):
                out.append(analysis.kind(atom.node.value, env))

        sweep(cfg, envs, init, analysis.transfer, collect)
        return max(out)

    return summaries.get((mod.path, fn.name), compute)


def _check_function(
    mod: ModuleInfo,
    fn: ast.FunctionDef,
    findings: List[Finding],
    modules: Dict[str, ModuleInfo],
    summaries: Optional[SummaryTable],
    parent_env: Optional[Env] = None,
    shared_flags: Optional[Set[Tuple[int, str]]] = None,
) -> None:
    analysis = _DeviceAnalysis(mod, modules, findings, summaries)
    if shared_flags is not None:
        analysis._flagged = shared_flags
    base = Env(LATTICE, dict(parent_env.kinds)) if parent_env else Env(LATTICE)
    init = _param_env(fn, base)
    cfg = build_cfg(fn.body)
    envs = run_forward(cfg, init, analysis.transfer)
    sweep(cfg, envs, init, analysis.transfer, analysis.check)


def check_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, SourceFile]]:
    """Run the device-residency pass; returns (findings, sources)."""
    findings: List[Finding] = []
    modules, sources, errors = load_modules(paths)
    for path, exc in errors:
        findings.append(
            Finding("DTX900", Severity.ERROR, path, 0, f"unparsable: {exc}")
        )
    summaries = SummaryTable(default=UNKNOWN, graph=build_call_graph(modules))
    for mod in modules.values():
        # module body first (a top-level `_TABLE = jnp.arange(8)` fed
        # into list()/print()/np.asarray is a host sync like any other);
        # def statements are excluded here — every function and method
        # is analyzed separately below, and the device contract gives
        # module globals no flow into them (fresh UNKNOWN-param envs)
        analysis = _DeviceAnalysis(mod, modules, findings, summaries)
        init = Env(LATTICE)
        cfg = build_cfg(
            [s for s in mod.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))]
        )
        envs = run_forward(cfg, init, analysis.transfer)
        sweep(cfg, envs, init, analysis.transfer, analysis.check)
        for fn in mod.index.functions.values():
            _check_function(mod, fn, findings, modules, summaries)
        for cls, table in mod.index.methods.items():
            for fn in table.values():
                _check_function(mod, fn, findings, modules, summaries)
    return findings, sources
