"""karpenter_tpu — a TPU-native provisioning/scheduling framework.

A brand-new framework with the capabilities of kubernetes-sigs/karpenter
(reference at /root/reference): watch unschedulable pods, evaluate their
scheduling constraints, provision right-sized nodes, and consolidate or
remove nodes no longer needed.

Unlike the reference's pod-by-pod first-fit-decreasing Go simulation
(reference: pkg/controllers/provisioning/scheduling/scheduler.go:270-339),
the decision kernel here is a dense (pods x instance-types x resources)
feasibility/cost tensor solved in batch on TPU with JAX/XLA, behind a
pluggable Solver seam. The host-side Python FFD packer mirrors the Go
semantics exactly and serves as the parity/cost oracle.

Layout:
  api/            data model: resources, labels, taints, requirements, objects
  scheduling/     host-side scheduling library (queue, preferences, topology)
  ops/            JAX kernels: feasibility, packing scan, topology tensors
  solver/         snapshot encoding (vocab interning) + solver drivers + oracle
  parallel/       device mesh / sharding for multi-chip solves
  controllers/    provisioning, disruption, state, lifecycle, termination, ...
  cloudprovider/  SPI + kwok-style and fake providers
  kube/           in-process object store standing in for the kube-apiserver
  utils/          shared helpers
"""

__version__ = "0.1.0"
