"""Minimal labeled metrics registry.

Plays the role of the reference's prometheus metrics (pkg/metrics,
namespace "karpenter" — constants.go:27). Dependency-free: a dict-backed
registry with counters/gauges/histograms, a text exposition dump, and full
introspection for tests (the reference asserts metrics in its suites too).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter_tpu"

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60
)


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


class Metric:
    def __init__(self, name: str, help_text: str = "", registry: "Registry" = None):
        self.name = f"{NAMESPACE}_{name}" if not name.startswith(NAMESPACE) else name
        self.help = help_text
        self._lock = threading.Lock()
        (registry or REGISTRY).register(self)


class Counter(Metric):
    def __init__(self, name, help_text="", registry=None):
        super().__init__(name, help_text, registry)
        self._values: Dict[tuple, float] = {}

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self):
        # snapshot under the lock: a concurrent inc() inserting a new
        # label key mid-iteration is a RuntimeError (GRD1301 dogfood)
        with self._lock:
            items = list(self._values.items())
        return [("counter", self.name, dict(k), v) for k, v in items]


class Gauge(Metric):
    def __init__(self, name, help_text="", registry=None):
        super().__init__(name, help_text, registry)
        self._values: Dict[tuple, float] = {}

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def delete(self, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values.pop(_label_key(labels), None)

    def delete_partial(self, labels: Dict[str, str]) -> None:
        """Drop every series whose labels are a superset (prometheus
        DeletePartialMatch)."""
        items = set(labels.items())
        with self._lock:
            for key in [k for k in self._values if items.issubset(set(k))]:
                del self._values[key]

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self):
        with self._lock:
            items = list(self._values.items())
        return [("gauge", self.name, dict(k), v) for k, v in items]


class Histogram(Metric):
    def __init__(self, name, help_text="", buckets: Iterable[float] = _DEFAULT_BUCKETS, registry=None):
        super().__init__(name, help_text, registry)
        self.buckets = sorted(buckets)
        self._counts: Dict[tuple, List[int]] = {}
        self._sums: Dict[tuple, float] = {}
        self._totals: Dict[tuple, int] = {}

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            idx = bisect.bisect_left(self.buckets, value)
            for i in range(idx, len(self.buckets)):
                counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, labels: Optional[Dict[str, str]] = None) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def sum(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._sums.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()

    def collect(self):
        with self._lock:
            pairs = [(k, self._totals[k], self._sums[k]) for k in self._totals]
        return [
            ("histogram", self.name, dict(k), {"count": total, "sum": s})
            for k, total, s in pairs
        ]


def _escape_label(v) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


# a single metric should never explode into unbounded label series (pod
# uids, node names as labels, ...): the guard test in tests/test_obs.py
# fails any metric whose series count crosses this after a full sim run
MAX_LABEL_SERIES = 64


class Registry:
    def __init__(self):
        self._metrics: List[Metric] = []
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> None:
        with self._lock:
            self._metrics.append(metric)

    def collect(self):
        # snapshot the metric list under the registry lock (a concurrent
        # register() grows it); each metric then snapshots its own series
        # under its own lock — registry -> metric is the one acquisition
        # order (render() below follows it too)
        with self._lock:
            metrics = list(self._metrics)
        out = []
        for m in metrics:
            out.extend(m.collect())
        return out

    def exposition(self) -> str:
        """Prometheus text format (for a /metrics endpoint)."""
        lines = []
        for kind, name, labels, value in self.collect():
            label_str = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            label_str = f"{{{label_str}}}" if label_str else ""
            if kind == "histogram":
                lines.append(f"{name}_count{label_str} {value['count']}")
                lines.append(f"{name}_sum{label_str} {value['sum']}")
            else:
                lines.append(f"{name}{label_str} {value}")
        return "\n".join(lines) + "\n"

    def render(self) -> str:
        """Full Prometheus text exposition (format 0.0.4): ``# HELP`` /
        ``# TYPE`` headers per metric family, histogram series expanded
        into cumulative ``_bucket{le=...}`` rows (``+Inf`` included) plus
        ``_sum``/``_count`` — the form real scrapers and promtool expect,
        unlike the test-oriented ``exposition()`` summary above."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            samples = m.collect()
            if not samples:
                continue
            kind = (
                "counter" if isinstance(m, Counter)
                else "gauge" if isinstance(m, Gauge)
                else "histogram"
            )
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {kind}")
            if isinstance(m, Histogram):
                with m._lock:
                    keys = list(m._totals)
                    counts = {k: list(m._counts[k]) for k in keys}
                    sums = dict(m._sums)
                    totals = dict(m._totals)
                for key in keys:
                    labels = dict(key)
                    for le, cum in zip(m.buckets, counts[key]):
                        le_pair = 'le="%s"' % le
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_label_str(labels, le_pair)} {cum}"
                        )
                    inf_pair = 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_label_str(labels, inf_pair)} {totals[key]}"
                    )
                    lines.append(
                        f"{m.name}_sum{_label_str(labels)} {sums[key]}"
                    )
                    lines.append(
                        f"{m.name}_count{_label_str(labels)} {totals[key]}"
                    )
            else:
                for _kind, name, labels, value in samples:
                    lines.append(f"{name}{_label_str(labels)} {value}")
        return "\n".join(lines) + "\n"

    def dump(self, path: str) -> None:
        """Write the full text exposition to ``path`` (operator shutdown
        and the sim harness flush final metric state through this)."""
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(self.render())

    def series_counts(self) -> Dict[str, int]:
        """{metric name: live label-series count} — the input to the
        cardinality guard."""
        out: Dict[str, int] = {}
        for _kind, name, _labels, _value in self.collect():
            out[name] = out.get(name, 0) + 1
        return out

    def check_cardinality(
        self,
        bound: int = MAX_LABEL_SERIES,
        exempt: Tuple[str, ...] = (),
    ) -> Dict[str, int]:
        """Metrics whose series count exceeds ``bound`` (empty = healthy).
        A nonempty result means some label carries unbounded identity
        (pod uid, node name) and would blow up a real scrape. ``exempt``
        lists name prefixes excluded from the check — the per-node/per-pod
        gauges mirror the reference's identity-labeled metrics and scale
        with cluster size BY DESIGN; everything else must stay bounded."""
        return {
            name: n
            for name, n in self.series_counts().items()
            if n > bound and not any(name.startswith(p) for p in exempt)
        }


REGISTRY = Registry()
