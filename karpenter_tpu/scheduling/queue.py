"""First-fit-decreasing pod queue with staleness detection.

Mirror of the reference's scheduling queue (queue.go:37-112): pods sorted by
CPU then memory descending; ``pop`` stops once a full cycle over the queue
makes no progress; relaxation resets the progress tracking.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import resources as res


def ffd_sort_key(pod, requests: res.ResourceList) -> tuple:
    """Descending cpu, then memory; stable tie-break by creation time then
    uid (queue.go:76-112)."""
    return (
        -requests.get(res.CPU, 0),
        -requests.get(res.MEMORY, 0),
        pod.metadata.creation_timestamp,
        pod.uid,
    )


class Queue:
    def __init__(self, pods: List, requests_by_uid: Dict[str, res.ResourceList]):
        self._pods = sorted(pods, key=lambda p: ffd_sort_key(p, requests_by_uid[p.uid]))
        self._last_len: Dict[str, int] = {}

    def pop(self) -> Optional[object]:
        """Next pod, or None once a full no-progress cycle completes."""
        if not self._pods:
            return None
        pod = self._pods[0]
        if self._last_len.get(pod.uid) == len(self._pods):
            return None
        self._pods.pop(0)
        return pod

    def push(self, pod, relaxed: bool = False) -> None:
        self._pods.append(pod)
        if relaxed:
            self._last_len = {}
        else:
            self._last_len[pod.uid] = len(self._pods)

    def list(self) -> List:
        return list(self._pods)

    def __len__(self) -> int:
        return len(self._pods)
