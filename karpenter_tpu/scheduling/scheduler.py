"""The scheduling simulation driver.

Host-side mirror of the reference's Scheduler.Solve
(scheduler.go:80-134, 270-425): FFD queue -> place each pod on existing
nodes, then open in-flight claims (fewest pods first), then a new claim from
the highest-weight feasible NodePool; on failure relax preferences and
requeue. This implementation is the exact-semantics oracle and fallback; the
TPU solver (karpenter_tpu.solver) accelerates the same decision problem and
is parity-tested against this.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import labels as labels_mod
from ..api import resources as res
from ..api import taints as taints_mod
from ..api.objects import Node, NodePool, Pod
from ..api.requirements import (
    Operator,
    Requirement,
    Requirements,
    has_preferred_node_affinity,
    pod_requirements,
    strict_pod_requirements,
)
from ..cloudprovider import types as cp
from .inflight import (
    ExistingNode,
    InFlightNodeClaim,
    PodData,
    RESERVED_OFFERING_MODE_FALLBACK,
    ReservedOfferingError,
    filter_instance_types,
)
from .preferences import Preferences
from .queue import Queue
from .reservation import ReservationManager
from .template import MAX_INSTANCE_TYPES, NodeClaimTemplate
from .topology import Topology


class AddError:
    """Lazily-formatted placement failure for one pod; ``reserved`` marks a
    reservation-policy failure which must not trigger relaxation
    (scheduler.go:313-321)."""

    __slots__ = ("parts", "reserved")

    def __init__(self, parts, reserved=False):
        self.parts = parts
        self.reserved = reserved

    def __str__(self) -> str:
        if not self.parts:
            return "no nodepool matched pod"
        return "; ".join(
            f"incompatible with nodepool {p[0]!r}, {p[1]}" if isinstance(p, tuple) else str(p)
            for p in self.parts
        )

    def __repr__(self) -> str:
        return str(self)


@dataclass
class Results:
    """Outcome of one Solve (reference: scheduler.go:161-165)."""

    new_node_claims: List[InFlightNodeClaim] = field(default_factory=list)
    existing_nodes: List[ExistingNode] = field(default_factory=list)
    pod_errors: Dict[str, str] = field(default_factory=dict)  # pod uid -> error

    def all_pods_scheduled(self) -> bool:
        return not self.pod_errors

    def truncate_instance_types(self, max_types: int = MAX_INSTANCE_TYPES) -> "Results":
        """Price-ordered truncation per new claim (scheduler.go:249-267).

        Runs at the end of every solve (oracle and TPU paths), so all
        consumers — provisioning, disruption replacements, the solver
        sidecar — see validated, launchable option sets. Claims already
        within the cap skip the price sort; minValues (when present) is
        still validated over the full set.

        The price sort is memoized on the inputs it actually depends on:
        the options list, the requirement entries over keys any offering
        defines (zone/capacity-type/reservation), and the presence set of
        positively-constrained custom keys (the Compatible asymmetry makes
        every offering incompatible when one is undefined offering-side,
        types.go:289-293 + requirements.go:178-188). Claims opened from
        the same group bulk — and across bulks, claims pinned to the same
        domain — share these, and on shapes like the diverse mix (~1,000
        one-pod anti-affinity claims) the per-claim Python sort otherwise
        dwarfs the entire kernel solve."""
        valid = []
        memo: dict = {}
        okeys_memo: dict = {}
        for claim in self.new_node_claims:
            options = claim.instance_type_options
            reqs = claim.requirements
            if len(options) <= max_types:
                err = None
                if reqs.has_min_values():
                    _, err = cp.satisfies_min_values(options, reqs)
                truncated = options
            elif reqs.has_min_values():
                # minValues depends on every requirement entry; don't
                # risk key coarsening on the rare pools that use it
                truncated, err = cp.truncate(options, reqs, max_types)
            else:
                # object identity, not names: distinct per-pool catalogs
                # may reuse type names with different offerings, and the
                # InstanceType objects are stable for this call's lifetime
                names = tuple(map(id, options))
                okeys = okeys_memo.get(names)
                if okeys is None:
                    seen: set = set()
                    for it in options:
                        for o in it.offerings:
                            seen.update(o.requirements.keys())
                    okeys = okeys_memo[names] = tuple(sorted(seen))
                custom_pos = tuple(sorted(
                    r.key
                    for r in reqs
                    if r.key not in labels_mod.WELL_KNOWN_LABELS
                    and r.key not in okeys
                    and r.operator() in ("In", "Exists", "Gt", "Lt")
                ))
                # full requirement state, NOT repr: __repr__ is lossy
                # ('k Exists' for both defined-Exists and undefined; Gt/Lt
                # bounds drop intersected values), and defined-vs-undefined
                # changes the Compatible asymmetry's verdict
                def _req_state(k):
                    if not reqs.has(k):
                        return None
                    r = reqs.get(k)
                    return (
                        r.complement, tuple(sorted(r.values)),
                        r.greater_than, r.less_than,
                    )

                key = (
                    names,
                    tuple(_req_state(k) for k in okeys),
                    custom_pos,
                )
                hit = memo.get(key)
                if hit is None:
                    hit = memo[key] = cp.truncate(options, reqs, max_types)
                cached, err = hit
                truncated = list(cached)
            if err is not None:
                for pod in claim.pods:
                    self.pod_errors[pod.uid] = (
                        f"nodepool {claim.template.node_pool_name!r} couldn't meet"
                        f" minValues requirements after truncation"
                    )
            else:
                claim.instance_type_options = truncated
                valid.append(claim)
        self.new_node_claims = valid
        return self

    def node_count(self) -> int:
        return len(self.new_node_claims)

    def total_price(self) -> float:
        """Packing cost: sum of each new claim's cheapest launchable price.

        The packing-cost comparator used for oracle-vs-TPU parity
        (BASELINE.json metric)."""
        total = 0.0
        for claim in self.new_node_claims:
            prices = [
                cp.min_compatible_price(it, claim.requirements)
                for it in claim.instance_type_options
            ]
            total += min(prices) if prices else 0.0
        return total


_LOG = logging.getLogger("karpenter_tpu.scheduler")


class Scheduler:
    def __init__(
        self,
        node_pools: Sequence[NodePool],
        instance_types: Dict[str, List[cp.InstanceType]],
        topology: Topology,
        state_nodes: Sequence = (),
        daemonset_pods: Sequence[Pod] = (),
        reserved_offering_mode: str = RESERVED_OFFERING_MODE_FALLBACK,
        reserved_capacity_enabled: bool = False,
        clock=None,
        volume_resolver=None,
        node_model_cache: Optional[dict] = None,
    ):
        self.clock = clock
        self.volume_resolver = volume_resolver
        # cross-solve cache for the pure per-node model inputs (taints,
        # daemon remainder, label requirements) — consolidation's binary
        # search rebuilds a Scheduler per probe over the SAME snapshot
        # nodes, and this construction dominated per-probe host time
        self._node_model_cache = node_model_cache
        # tolerate PreferNoSchedule during relaxation if any pool taints with it
        tolerate_pns = any(
            t.effect == taints_mod.PREFER_NO_SCHEDULE
            for np in node_pools
            for t in np.spec.template.spec.taints
        )
        self.preferences = Preferences(tolerate_prefer_no_schedule=tolerate_pns)
        self.topology = topology
        self.reservation_manager = ReservationManager(instance_types)
        self.reserved_offering_mode = reserved_offering_mode
        self.reserved_capacity_enabled = reserved_capacity_enabled

        # templates in weight order, pre-filtered to feasible instance types
        # (scheduler.go:104-114); order: weight desc, then name
        self.templates: List[NodeClaimTemplate] = []
        for np in sorted(node_pools, key=lambda p: (-p.spec.weight, p.name)):
            nct = NodeClaimTemplate(np)
            options, _ = filter_instance_types(
                instance_types.get(np.name, []), nct.requirements, {}, {}, {}
            )
            if not options:
                continue  # pool requirements filtered out all instance types
            nct.instance_type_options = options
            self.templates.append(nct)

        self.daemon_overhead = {
            nct: _daemon_overhead(nct, daemonset_pods) for nct in self.templates
        }
        self.remaining_resources: Dict[str, res.ResourceList] = {
            np.name: dict(np.spec.limits) for np in node_pools if np.spec.limits
        }
        self.cached_pod_data: Dict[str, PodData] = {}
        self.new_node_claims: List[InFlightNodeClaim] = []
        self.existing_nodes: List[ExistingNode] = []
        self._calculate_existing_nodes(state_nodes, daemonset_pods)

    # -- existing nodes (scheduler.go:427-463) ----------------------------

    @staticmethod
    def _node_identity(sn) -> tuple:
        """Cache identity of a StateNode's pure model inputs: labels and
        taints can only change with the backing objects' resource
        versions."""
        node_rv = sn.node.metadata.resource_version if sn.node is not None else -1
        claim_rv = (
            sn.node_claim.metadata.resource_version
            if sn.node_claim is not None
            else -1
        )
        return (sn.name, node_rv, claim_rv)

    def _calculate_existing_nodes(self, state_nodes, daemonset_pods) -> None:
        cache = self._node_model_cache
        daemon_fp = (
            tuple(
                (p.uid, p.metadata.resource_version) for p in daemonset_pods
            )
            if cache is not None
            else ()
        )
        # content-shared label requirements: fleets are homogeneous, so the
        # non-hostname label shape repeats across thousands of nodes. The
        # shared base is built once per distinct shape; each node's
        # requirements are a fresh container over the SHARED Requirement
        # entries plus its own hostname pin (safe: Requirements.add never
        # mutates stored entries, it replaces them with intersections).
        shared_base: dict = {}
        for sn in state_nodes:
            hit = None
            if cache is not None:
                key = self._node_identity(sn) + (daemon_fp,)
                hit = cache.get(key)
            if hit is not None:
                taints, daemon_requests, base_entries = hit
                # a FRESH container per solve over the shared (immutable)
                # Requirement entries: the container itself is mutated by
                # decode's existing-node fill commit, so handing out a
                # cached container would leak one solve's fills into the
                # next solve's node model
                base_reqs = Requirements(*base_entries)
            else:
                taints = sn.taints()
                daemons = []
                for p in daemonset_pods:
                    if taints_mod.tolerates_pod(taints, p) is not None:
                        continue
                    if (
                        Requirements.from_labels(sn.labels()).compatible(pod_requirements(p))
                        is not None
                    ):
                        continue
                    daemons.append(p)
                daemon_requests = res.merge(*(p.spec.requests for p in daemons)) if daemons else {}
                base_reqs = None
                if cache is not None:
                    labels = sn.labels()
                    ckey = tuple(
                        sorted(
                            (k, v)
                            for k, v in labels.items()
                            if k != labels_mod.HOSTNAME
                        )
                    )
                    shared = shared_base.get(ckey)
                    if shared is None:
                        shared = shared_base[ckey] = Requirements.from_labels(
                            {
                                k: v
                                for k, v in labels.items()
                                if k != labels_mod.HOSTNAME
                            }
                        ).values()
                    # the hostname pin subsumes the hostname label (its
                    # value IS the label's, statenode hostname fallback
                    # included), so base+pin == build_requirements(sn)
                    base_reqs = Requirements(*shared)
                    base_reqs.add(
                        Requirement(
                            labels_mod.HOSTNAME, Operator.IN, [sn.hostname()]
                        )
                    )
                    # cache the ENTRIES, not the container (see the hit
                    # path above)
                    cache[key] = (
                        taints, daemon_requests, tuple(base_reqs.values())
                    )
            self.existing_nodes.append(
                ExistingNode(
                    sn, self.topology, taints, daemon_requests,
                    base_requirements=base_reqs,
                )
            )
            pool = sn.labels().get(labels_mod.NODEPOOL_LABEL_KEY)
            if pool in self.remaining_resources:
                self.remaining_resources[pool] = res.subtract(
                    self.remaining_resources[pool], sn.capacity()
                )
        self.existing_nodes.sort(key=lambda n: (not n.initialized(), n.name))
        # resource-version churn retires entries; bound the long-lived
        # provisioner cache rather than leak one entry per rv bump
        if cache is not None and len(cache) > max(10_000, 8 * len(self.existing_nodes)):
            cache.clear()

    # -- per-pod placement (scheduler.go:357-425) -------------------------

    def _update_cached_pod_data(self, pod: Pod) -> None:
        requirements = pod_requirements(pod)
        strict = requirements
        if has_preferred_node_affinity(pod):
            strict = strict_pod_requirements(pod)
        resolved_volumes, volume_error = (), None
        if pod.spec.volumes and self.volume_resolver is not None:
            resolved_volumes, volume_error = self.volume_resolver.resolve(pod)
        self.cached_pod_data[pod.uid] = PodData(
            requests=dict(pod.spec.requests),
            requirements=requirements,
            strict_requirements=strict,
            resolved_volumes=resolved_volumes,
            volume_error=volume_error,
        )

    def _add(self, pod: Pod) -> Optional[AddError]:
        pod_data = self.cached_pod_data[pod.uid]
        # a pod whose PVC can't be resolved can never run anywhere — fail it
        # instead of launching capacity for it (volumetopology.go:152-199;
        # matters for disruption simulations, which bypass Provisioner
        # validation)
        if pod_data.volume_error is not None:
            return AddError([pod_data.volume_error])
        # 1. existing nodes, initialized first
        for node in self.existing_nodes:
            if node.add(pod, pod_data) is None:
                return None
        # 2. open in-flight claims, fewest pods first
        self.new_node_claims.sort(key=lambda c: len(c.pods))
        for claim in self.new_node_claims:
            try:
                if claim.add(pod, pod_data) is None:
                    return None
            except ReservedOfferingError:
                continue
        # 3. new claim from the highest-weight feasible template
        errs = []
        reserved = False
        for nct in self.templates:
            instance_types = nct.instance_type_options
            if nct.node_pool_name in self.remaining_resources:
                instance_types = _filter_by_remaining_resources(
                    instance_types, self.remaining_resources[nct.node_pool_name]
                )
                if not instance_types:
                    errs.append(
                        f"all instance types exceed limits for nodepool"
                        f" {nct.node_pool_name!r}"
                    )
                    continue
            claim = InFlightNodeClaim(
                nct,
                self.topology,
                self.daemon_overhead[nct],
                instance_types,
                self.reservation_manager,
                self.reserved_offering_mode,
                self.reserved_capacity_enabled,
            )
            try:
                err = claim.add(pod, pod_data)
            except ReservedOfferingError as e:
                claim.destroy()
                errs.append(f"reserved offering policy for {nct.node_pool_name!r}: {e}")
                reserved = True
                # don't fall back to lower-weight pools past a reservation error
                break
            if err is not None:
                claim.destroy()
                errs.append((nct.node_pool_name, err))
                continue
            self.new_node_claims.append(claim)
            if nct.node_pool_name in self.remaining_resources:
                self.remaining_resources[nct.node_pool_name] = _subtract_max(
                    self.remaining_resources[nct.node_pool_name],
                    claim.instance_type_options,
                )
            return None
        return AddError(errs, reserved=reserved)

    # -- the solve loop (scheduler.go:270-339) ----------------------------

    def solve(self, pods: Sequence[Pod]) -> Results:
        for p in pods:
            self._update_cached_pod_data(p)
        queue = Queue(
            list(pods), {uid: d.requests for uid, d in self.cached_pod_data.items()}
        )
        pod_errors: Dict[str, str] = {}
        relaxed_uids: set = set()
        # injected clock when provided (the project's clock convention,
        # kube/clock.py) — tests can then drive the progress threshold
        _now = self.clock.now if self.clock is not None else time.monotonic
        solve_start = _now()
        last_progress = solve_start
        placed = 0
        while True:
            pod = queue.pop()
            if pod is None:
                break
            # the reference logs progress every minute inside long Solves
            # (scheduler.go:297-300)
            now = _now()
            if now - last_progress >= 60.0:
                last_progress = now
                _LOG.info(
                    "computing scheduling decision for provisionable pods: "
                    "%d placed, elapsed %.0fs",
                    placed,
                    now - solve_start,
                )
            err = self._add(pod)
            if err is None:
                pod_errors.pop(pod.uid, None)
                placed += 1
                continue
            pod_errors[pod.uid] = err
            relaxed = False
            if not err.reserved:
                if pod.uid not in relaxed_uids and _has_relaxable_terms(
                    pod, self.preferences.tolerate_prefer_no_schedule
                ):
                    # relaxation mutates the pod spec, but callers hand us
                    # LIVE store objects (and disruption probes share pods
                    # across simulations): mutate a private copy, the way
                    # the reference's cache-backed client hands its
                    # scheduler deep copies (preferences.go:38-146 relaxes
                    # without ever touching the informer's object)
                    import copy

                    pod = copy.deepcopy(pod)
                    relaxed_uids.add(pod.uid)
                relaxed = self.preferences.relax(pod)
                if relaxed:
                    self.topology.update(pod)
                    self._update_cached_pod_data(pod)
            queue.push(pod, relaxed)
        for claim in self.new_node_claims:
            claim.finalize()
        return Results(
            new_node_claims=self.new_node_claims,
            existing_nodes=self.existing_nodes,
            pod_errors=pod_errors,
        ).truncate_instance_types()


def _has_relaxable_terms(pod: Pod, tolerate_pns: bool) -> bool:
    """Anything Preferences.relax could mutate (preferences.py): extra
    required node-affinity OR-terms, preferred terms, ScheduleAnyway
    spreads, or (when pools taint PreferNoSchedule) the toleration append.
    Pods with none of these skip the defensive deep copy."""
    spec = pod.spec
    na = spec.node_affinity
    if na is not None and (na.preferred or len(na.required) > 1):
        return True
    if spec.preferred_pod_affinity or spec.preferred_pod_anti_affinity:
        return True
    if any(
        t.when_unsatisfiable == "ScheduleAnyway"
        for t in spec.topology_spread_constraints
    ):
        return True
    return tolerate_pns


def _daemon_overhead(nct: NodeClaimTemplate, daemonset_pods: Sequence[Pod]) -> res.ResourceList:
    """Total requests of daemon pods compatible with the template
    (scheduler.go:466-492)."""
    compatible = [p for p in daemonset_pods if _daemon_compatible(nct, p)]
    return res.merge(*(p.spec.requests for p in compatible)) if compatible else {}


def _daemon_compatible(nct: NodeClaimTemplate, pod: Pod) -> bool:
    import copy

    pod = copy.deepcopy(pod)
    prefs = Preferences()
    prefs._tolerate_prefer_no_schedule_taints(pod)
    if taints_mod.tolerates_pod(nct.taints, pod) is not None:
        return False
    while True:
        if (
            nct.requirements.compatible(
                strict_pod_requirements(pod), labels_mod.WELL_KNOWN_LABELS
            )
            is None
        ):
            return True
        if prefs._remove_required_node_affinity_term(pod) is None:
            return False


def _subtract_max(
    remaining: res.ResourceList, instance_types: Sequence[cp.InstanceType]
) -> res.ResourceList:
    """Pessimistically subtract the max capacity per resource
    (scheduler.go:498-515)."""
    if not instance_types:
        return remaining
    max_caps = res.max_resources(*(it.capacity for it in instance_types))
    return {k: v - max_caps.get(k, 0) for k, v in remaining.items()}


def _filter_by_remaining_resources(
    instance_types: Sequence[cp.InstanceType], remaining: res.ResourceList
) -> List[cp.InstanceType]:
    """Drop instance types whose capacity exceeds any remaining limit
    (scheduler.go:517-534)."""
    out = []
    for it in instance_types:
        if all(it.capacity.get(name, 0) <= q for name, q in remaining.items()):
            out.append(it)
    return out
