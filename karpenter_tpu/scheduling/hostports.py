"""Host-port conflict tracking (reference: pkg/scheduling/hostportusage.go)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# (host_ip, port, protocol)
PortKey = Tuple[str, int, str]


def _entries(pod) -> List[PortKey]:
    out = []
    for hp in pod.spec.host_ports:
        if hp.port:
            out.append((hp.host_ip or "0.0.0.0", hp.port, hp.protocol or "TCP"))
    return out


def _conflicts(a: PortKey, b: PortKey) -> bool:
    ip_a, port_a, proto_a = a
    ip_b, port_b, proto_b = b
    if port_a != port_b or proto_a != proto_b:
        return False
    return ip_a == ip_b or ip_a == "0.0.0.0" or ip_b == "0.0.0.0"


class HostPortUsage:
    """Per-node ledger of reserved host ports."""

    def __init__(self):
        self._used: Dict[str, List[PortKey]] = {}  # pod uid -> entries

    def conflicts(self, pod) -> Optional[str]:
        for entry in _entries(pod):
            for uid, entries in self._used.items():
                if uid == pod.uid:
                    continue
                for existing in entries:
                    if _conflicts(entry, existing):
                        return f"host port {entry} conflicts with pod {uid}"
        return None

    def add(self, pod) -> None:
        self._used[pod.uid] = _entries(pod)

    def delete_pod(self, uid: str) -> None:
        self._used.pop(uid, None)

    def copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        out._used = {k: list(v) for k, v in self._used.items()}
        return out
