"""Topology tracking: spread, pod affinity, pod anti-affinity.

Host-side mirror of the reference's topology engine
(topology.go, topologygroup.go, topologynodefilter.go,
topologydomaingroup.go). This is the semantic oracle; the tensorized forms
live in solver/encode.py (TopoSpec distillation: hostname per-entity caps,
domain-quota descriptors, shared-constraint carries) and ops/packing.py
(the kernel's quota water-fill and count carries), and
tests/test_solver_parity.py asserts agreement between the two.
"""

from __future__ import annotations

import math
from enum import Enum
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..api import labels as labels_mod
from ..api import taints as taints_mod
from ..api.objects import LabelSelector, Node, Pod, Taint
from ..api.requirements import Operator, Requirement, Requirements

MAX_SKEW_UNBOUNDED = 2**31 - 1

HONOR = "Honor"
IGNORE = "Ignore"


class TopologyType(str, Enum):
    SPREAD = "topology spread"
    POD_AFFINITY = "pod affinity"
    POD_ANTI_AFFINITY = "pod anti-affinity"


class TopologyDomainGroup:
    """Universe of domains for one topology key, annotated with the taint
    sets of the NodePools providing each domain
    (reference: topologydomaingroup.go:25-72)."""

    def __init__(self):
        self._domains: Dict[str, List[Tuple[Taint, ...]]] = {}

    def insert(self, domain: str, taints: Sequence[Taint] = ()) -> None:
        taints = tuple(taints)
        if domain not in self._domains or not taints:
            self._domains[domain] = [taints]
            return
        if not self._domains[domain][0]:
            return  # already tracking the always-eligible empty taint set
        self._domains[domain].append(taints)

    def for_each_domain(self, pod, taint_policy: str, fn: Callable[[str], None]) -> None:
        for domain, taint_groups in self._domains.items():
            if taint_policy == IGNORE:
                fn(domain)
                continue
            for taints in taint_groups:
                if taints_mod.tolerates_pod(taints, pod) is None:
                    fn(domain)
                    break

    def domains(self) -> Set[str]:
        return set(self._domains)


class TopologyNodeFilter:
    """Node-inclusion policy for spread counting
    (reference: topologynodefilter.go:26-97). Zero-value filter matches all
    nodes — affinity/anti-affinity topologies use that.
    """

    def __init__(
        self,
        requirements: Optional[List[Requirements]] = None,
        taint_policy: str = IGNORE,
        affinity_policy: str = HONOR,
        tolerations: Sequence = (),
    ):
        self.requirements = requirements or []
        self.taint_policy = taint_policy
        self.affinity_policy = affinity_policy
        self.tolerations = list(tolerations)

    @classmethod
    def for_pod(cls, pod: Pod, taint_policy: str, affinity_policy: str) -> "TopologyNodeFilter":
        selector_reqs = Requirements.from_labels(pod.spec.node_selector or {})
        affinity = pod.spec.node_affinity
        if affinity is None or not affinity.required:
            return cls(
                [selector_reqs], taint_policy, affinity_policy, pod.spec.tolerations
            )
        # node-affinity OR-terms: any term + the node selector may match
        reqs_list = []
        for term in affinity.required:
            reqs = Requirements(*selector_reqs.values())
            reqs.add(*(t.to_requirement() for t in term))
            reqs_list.append(reqs)
        return cls(reqs_list, taint_policy, affinity_policy, pod.spec.tolerations)

    def matches(self, taints: Sequence[Taint], node_requirements: Requirements) -> bool:
        matches_affinity = True
        if self.affinity_policy == HONOR:
            matches_affinity = self._matches_requirements(node_requirements)
        matches_taints = True
        if self.taint_policy == HONOR:
            matches_taints = taints_mod.tolerates(taints, self.tolerations) is None
        return matches_affinity and matches_taints

    def _matches_requirements(self, node_requirements: Requirements) -> bool:
        if not self.requirements or self.affinity_policy == IGNORE:
            return True
        return any(
            node_requirements.compatible(req) is None for req in self.requirements
        )

    def key(self) -> tuple:
        return (
            tuple(
                tuple(sorted((r.key, repr(r)) for r in reqs)) for reqs in self.requirements
            ),
            self.taint_policy,
            self.affinity_policy,
            tuple(sorted((t.key, t.operator, t.value, t.effect) for t in self.tolerations)),
        )


class TopologyGroup:
    """Per-constraint domain->count tracker
    (reference: topologygroup.go:56-149)."""

    def __init__(
        self,
        topology_type: TopologyType,
        key: str,
        pod: Pod,
        namespaces: Set[str],
        selector: Optional[LabelSelector],
        max_skew: int,
        min_domains: Optional[int],
        taint_policy: Optional[str],
        affinity_policy: Optional[str],
        domain_group: TopologyDomainGroup,
    ):
        self.type = topology_type
        self.key = key
        self.namespaces = set(namespaces)
        self.selector = selector
        self.max_skew = max_skew
        self.min_domains = min_domains
        if topology_type is TopologyType.SPREAD:
            self.node_filter = TopologyNodeFilter.for_pod(
                pod, taint_policy or IGNORE, affinity_policy or HONOR
            )
        else:
            self.node_filter = TopologyNodeFilter()  # matches everything
        self.domains: Dict[str, int] = {}
        self.empty_domains: Set[str] = set()
        self.owners: Set[str] = set()
        domain_group.for_each_domain(pod, self.node_filter.taint_policy, self._init_domain)

    def _init_domain(self, domain: str) -> None:
        if domain not in self.domains:
            self.domains[domain] = 0
            self.empty_domains.add(domain)

    # -- identity (dedup across owner pods; topologygroup.go:181-198) -----

    def hash_key(self) -> tuple:
        return (
            self.key,
            self.type,
            frozenset(self.namespaces),
            self.selector.key() if self.selector is not None else None,
            self.max_skew,
            self.node_filter.key(),
        )

    # -- ownership --------------------------------------------------------

    def add_owner(self, uid: str) -> None:
        self.owners.add(uid)

    def remove_owner(self, uid: str) -> None:
        self.owners.discard(uid)

    def is_owned_by(self, uid: str) -> bool:
        return uid in self.owners

    # -- counting ---------------------------------------------------------

    def record(self, *domains: str) -> None:
        for domain in domains:
            self.domains[domain] = self.domains.get(domain, 0) + 1
            self.empty_domains.discard(domain)

    def register(self, *domains: str) -> None:
        for domain in domains:
            if domain not in self.domains:
                self.domains[domain] = 0
                self.empty_domains.add(domain)

    def unregister(self, *domains: str) -> None:
        for domain in domains:
            self.domains.pop(domain, None)
            self.empty_domains.discard(domain)

    def selects(self, pod: Pod) -> bool:
        if pod.metadata.namespace not in self.namespaces:
            return False
        if self.selector is None:
            return False  # nil selector selects nothing (labels.Nothing())
        return self.selector.matches(pod.metadata.labels)

    def counts(self, pod: Pod, taints: Sequence[Taint], requirements: Requirements) -> bool:
        """Would the pod count against this topology if scheduled onto a node
        with the given requirements (topologygroup.go:147-149)."""
        return self.selects(pod) and self.node_filter.matches(taints, requirements)

    # -- domain selection (topologygroup.go:205-366) ----------------------

    def get(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type is TopologyType.SPREAD:
            return self._next_domain_spread(pod, pod_domains, node_domains)
        if self.type is TopologyType.POD_AFFINITY:
            return self._next_domain_affinity(pod, pod_domains, node_domains)
        return self._next_domain_anti_affinity(pod_domains, node_domains)

    def _candidate_domains(self, node_domains: Requirement) -> Iterable[str]:
        if node_domains.operator() is Operator.IN:
            return [d for d in sorted(node_domains.values) if d in self.domains]
        return [d for d in sorted(self.domains) if node_domains.has(d)]

    def _next_domain_spread(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        global_min = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        min_domain, min_count = None, math.inf
        for domain in self._candidate_domains(node_domains):
            count = self.domains[domain]
            if self_selecting:
                count += 1
            if count - global_min <= self.max_skew and count < min_count:
                min_domain, min_count = domain, count
        if min_domain is None:
            return Requirement(pod_domains.key, Operator.DOES_NOT_EXIST)
        return Requirement(pod_domains.key, Operator.IN, [min_domain])

    def _domain_min_count(self, pod_domains: Requirement) -> int:
        # hostname topologies can always mint a fresh node: min is 0
        # (topologygroup.go:253-274)
        if self.key == labels_mod.HOSTNAME:
            return 0
        counts = [c for d, c in self.domains.items() if pod_domains.has(d)]
        minimum = min(counts) if counts else MAX_SKEW_UNBOUNDED
        if self.min_domains is not None and len(counts) < self.min_domains:
            minimum = 0
        return minimum

    def _next_domain_affinity(
        self, pod: Pod, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        options = [
            d
            for d in self._candidate_domains(node_domains)
            if pod_domains.has(d) and self.domains[d] > 0
        ]
        if options:
            return Requirement(pod_domains.key, Operator.IN, options)
        # bootstrap: a self-selecting pod with no compatible placed pods may
        # pick a viable domain (topologygroup.go:277-324)
        if self.selects(pod) and (
            len(self.domains) == len(self.empty_domains)
            or not self._any_compatible_pod_domain(pod_domains)
        ):
            intersected = pod_domains.intersection(node_domains)
            for domain in sorted(self.domains):
                if intersected.has(domain):
                    return Requirement(pod_domains.key, Operator.IN, [domain])
            for domain in sorted(self.domains):
                if pod_domains.has(domain):
                    return Requirement(pod_domains.key, Operator.IN, [domain])
        return Requirement(pod_domains.key, Operator.DOES_NOT_EXIST)

    def _any_compatible_pod_domain(self, pod_domains: Requirement) -> bool:
        return any(
            pod_domains.has(d) and count > 0 for d, count in self.domains.items()
        )

    def _next_domain_anti_affinity(
        self, pod_domains: Requirement, node_domains: Requirement
    ) -> Requirement:
        options = [
            d
            for d in sorted(self.empty_domains)
            if node_domains.has(d) and pod_domains.has(d)
        ]
        if options:
            return Requirement(pod_domains.key, Operator.IN, options)
        return Requirement(pod_domains.key, Operator.DOES_NOT_EXIST)


def ignored_for_topology(pod: Pod) -> bool:
    """Terminal / terminating pods don't count (reference: topology.go:522+)."""
    return pod.status.phase in ("Succeeded", "Failed") or pod.metadata.deletion_timestamp is not None


class Topology:
    """Cross-group topology tracker for one scheduling run
    (reference: topology.go:45-98)."""

    def __init__(
        self,
        client,
        state_nodes: Sequence,
        node_pools: Sequence,
        instance_types: Dict[str, List],
        pods: Sequence[Pod],
        cluster=None,
    ):
        self._client = client
        self._state_nodes = list(state_nodes)
        self._cluster = cluster
        self.domain_groups = build_domain_groups(node_pools, instance_types)
        self.topology_groups: Dict[tuple, TopologyGroup] = {}
        self.inverse_topology_groups: Dict[tuple, TopologyGroup] = {}
        # pod uid -> owned forward groups; avoids scanning every group per
        # placement attempt (add_requirements is the oracle's hot loop)
        self._owner_index: Dict[str, List[TopologyGroup]] = {}
        self.excluded_pods: Set[str] = {p.uid for p in pods}
        self._update_inverse_affinities()
        for pod in pods:
            self.update(pod)

    # -- group construction ----------------------------------------------

    def update(self, pod: Pod) -> None:
        """(Re)register the pod as owner of its topologies; called again
        after preference relaxation (topology.go:157-189)."""
        spec = pod.spec
        has_constraints = bool(
            spec.topology_spread_constraints
            or spec.pod_affinity
            or spec.pod_anti_affinity
            or spec.preferred_pod_affinity
            or spec.preferred_pod_anti_affinity
        )
        if has_constraints or pod.uid in self._owner_index:
            for tg in self._owner_index.pop(pod.uid, ()):
                tg.remove_owner(pod.uid)
        if not has_constraints:
            # constraint-free pods own no topology groups; this walk runs
            # once per pod per Topology build (50k times on the headline
            # batch), so the common case takes one attribute sweep
            return

        if spec.pod_anti_affinity:
            self._update_inverse_anti_affinity(pod, None)

        groups = self._new_for_topologies(pod) + self._new_for_affinities(pod)
        owned: List[TopologyGroup] = []
        for tg in groups:
            key = tg.hash_key()
            existing = self.topology_groups.get(key)
            if existing is None:
                self._count_domains(tg)
                self.topology_groups[key] = tg
            else:
                tg = existing
            tg.add_owner(pod.uid)
            if tg not in owned:  # duplicate constraints share one group
                owned.append(tg)
        if owned:
            self._owner_index[pod.uid] = owned

    def _new_for_topologies(self, pod: Pod) -> List[TopologyGroup]:
        return [
            TopologyGroup(
                TopologyType.SPREAD,
                tsc.topology_key,
                pod,
                {pod.metadata.namespace},
                tsc.label_selector,
                tsc.max_skew,
                tsc.min_domains,
                tsc.node_taints_policy,
                tsc.node_affinity_policy,
                self.domain_groups.get(tsc.topology_key, TopologyDomainGroup()),
            )
            for tsc in pod.spec.topology_spread_constraints
        ]

    def _new_for_affinities(self, pod: Pod) -> List[TopologyGroup]:
        groups = []
        terms = [(TopologyType.POD_AFFINITY, t) for t in pod.spec.pod_affinity]
        terms += [(TopologyType.POD_AFFINITY, wt.term) for wt in pod.spec.preferred_pod_affinity]
        terms += [(TopologyType.POD_ANTI_AFFINITY, t) for t in pod.spec.pod_anti_affinity]
        terms += [
            (TopologyType.POD_ANTI_AFFINITY, wt.term)
            for wt in pod.spec.preferred_pod_anti_affinity
        ]
        for ttype, term in terms:
            groups.append(
                TopologyGroup(
                    ttype,
                    term.topology_key,
                    pod,
                    self._namespaces(pod, term),
                    term.label_selector,
                    MAX_SKEW_UNBOUNDED,
                    None,
                    None,
                    None,
                    self.domain_groups.get(term.topology_key, TopologyDomainGroup()),
                )
            )
        return groups

    def _namespaces(self, pod: Pod, term) -> Set[str]:
        if term.namespaces:
            return set(term.namespaces)
        return {pod.metadata.namespace}

    # -- inverse anti-affinity (topology.go:273-313) ----------------------

    def _update_inverse_affinities(self) -> None:
        for p in self._client.list(Pod):
            if not p.spec.pod_anti_affinity or not p.bound():
                continue
            if p.uid in self.excluded_pods or ignored_for_topology(p):
                continue
            node = self._client.try_get(Node, p.spec.node_name)
            self._update_inverse_anti_affinity(
                p, node.metadata.labels if node is not None else {}
            )

    def _update_inverse_anti_affinity(self, pod: Pod, domains: Optional[Dict[str, str]]) -> None:
        for term in pod.spec.pod_anti_affinity:
            tg = TopologyGroup(
                TopologyType.POD_ANTI_AFFINITY,
                term.topology_key,
                pod,
                self._namespaces(pod, term),
                term.label_selector,
                MAX_SKEW_UNBOUNDED,
                None,
                None,
                None,
                self.domain_groups.get(term.topology_key, TopologyDomainGroup()),
            )
            key = tg.hash_key()
            existing = self.inverse_topology_groups.get(key)
            if existing is None:
                self.inverse_topology_groups[key] = tg
            else:
                tg = existing
            if domains is not None and tg.key in domains:
                tg.record(domains[tg.key])
            tg.add_owner(pod.uid)

    # -- counting from live cluster (topology.go:318-420) -----------------

    def _count_domains(self, tg: TopologyGroup) -> None:
        # register domains present on real nodes even without selected pods
        for sn in self._state_nodes:
            node = getattr(sn, "node", sn)
            if node is None or not isinstance(node, Node):
                continue
            if not tg.node_filter.matches(
                node.taints, Requirements.from_labels(node.metadata.labels)
            ):
                continue
            domain = node.metadata.labels.get(tg.key)
            if domain is not None:
                tg.register(domain)

        node_cache: Dict[str, Optional[Node]] = {}
        for pod in self._client.list(Pod):
            if pod.metadata.namespace not in tg.namespaces:
                continue
            if tg.selector is None or not tg.selector.matches(pod.metadata.labels):
                continue
            if ignored_for_topology(pod) or pod.uid in self.excluded_pods:
                continue
            if not pod.spec.node_name:
                continue
            if pod.spec.node_name not in node_cache:
                node_cache[pod.spec.node_name] = self._client.try_get(Node, pod.spec.node_name)
            node = node_cache[pod.spec.node_name]
            if node is None:
                continue  # leaked binding to a deleted node
            domain = node.metadata.labels.get(tg.key)
            if domain is None and tg.key == labels_mod.HOSTNAME:
                domain = node.metadata.name
            if domain is None:
                continue
            if not tg.node_filter.matches(
                node.taints, Requirements.from_labels(node.metadata.labels)
            ):
                continue
            tg.record(domain)

    # -- scheduling API (topology.go:192-270) -----------------------------

    def record(self, pod: Pod, taints: Sequence[Taint], requirements: Requirements) -> None:
        for tg in self.topology_groups.values():
            if tg.counts(pod, taints, requirements):
                domains = requirements.get(tg.key)
                if tg.type is TopologyType.POD_ANTI_AFFINITY:
                    tg.record(*domains.values_list())
                elif not domains.complement and len(domains.values) == 1:
                    tg.record(next(iter(domains.values)))
        for tg in self.inverse_topology_groups.values():
            if tg.is_owned_by(pod.uid):
                tg.record(*requirements.get(tg.key).values_list())

    def add_requirements(
        self,
        pod: Pod,
        taints: Sequence[Taint],
        pod_requirements: Requirements,
        node_requirements: Requirements,
    ) -> Tuple[Optional[Requirements], Optional[str]]:
        """Tighten node requirements with topology-selected domains; returns
        (requirements, None) or (None, error) (topology.go:220-242)."""
        requirements = Requirements(*node_requirements.values())
        for tg in self._matching_topologies(pod, taints, node_requirements):
            pod_domains = (
                pod_requirements.get(tg.key)
                if pod_requirements.has(tg.key)
                else Requirement(tg.key, Operator.EXISTS)
            )
            node_domains = (
                node_requirements.get(tg.key)
                if node_requirements.has(tg.key)
                else Requirement(tg.key, Operator.EXISTS)
            )
            domains = tg.get(pod, pod_domains, node_domains)
            if not domains.complement and not domains.values:
                return None, (
                    f"unsatisfiable topology constraint for {tg.type.value},"
                    f" key={tg.key}"
                )
            requirements.add(domains)
        return requirements, None

    def owned_topologies(self, uid: str):
        """Forward TopologyGroups owned by a pod, via the owner index."""
        return self._owner_index.get(uid, ())

    def register(self, topology_key: str, domain: str) -> None:
        for tg in list(self.topology_groups.values()) + list(
            self.inverse_topology_groups.values()
        ):
            if tg.key == topology_key:
                tg.register(domain)

    def unregister(self, topology_key: str, domain: str) -> None:
        for tg in list(self.topology_groups.values()) + list(
            self.inverse_topology_groups.values()
        ):
            if tg.key == topology_key:
                tg.unregister(domain)

    def _matching_topologies(
        self, pod: Pod, taints: Sequence[Taint], requirements: Requirements
    ) -> List[TopologyGroup]:
        """Forward groups apply only to their OWNER pods; inverse
        anti-affinity groups apply to any pod they select that would count on
        this node (reference: topology.go:513-528)."""
        out = list(self._owner_index.get(pod.uid, ()))
        for tg in self.inverse_topology_groups.values():
            if tg.counts(pod, taints, requirements):
                out.append(tg)
        return out


def build_domain_groups(
    node_pools: Sequence, instance_types: Dict[str, List]
) -> Dict[str, TopologyDomainGroup]:
    """Universe of domains per topology key from nodepool x instance-type
    requirements (reference: topology.go:100-138)."""
    groups: Dict[str, TopologyDomainGroup] = {}
    pool_index = {np.name: np for np in node_pools}
    for np_name, its in instance_types.items():
        np = pool_index[np_name]
        template = np.spec.template
        taints = template.spec.taints
        for it in its:
            requirements = Requirements(
                *(r.to_requirement() for r in template.spec.requirements)
            )
            requirements.add(*Requirements.from_labels(template.labels).values())
            requirements.add(*it.requirements.values())
            for req in requirements:
                groups.setdefault(req.key, TopologyDomainGroup())
                for domain in req.values_list():
                    groups[req.key].insert(domain, taints)
        requirements = Requirements(
            *(r.to_requirement() for r in template.spec.requirements)
        )
        requirements.add(*Requirements.from_labels(template.labels).values())
        for req in requirements:
            if req.operator() is Operator.IN:
                groups.setdefault(req.key, TopologyDomainGroup())
                for domain in req.values_list():
                    groups[req.key].insert(domain, taints)
    return groups
