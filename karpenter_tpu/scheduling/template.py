"""NodeClaimTemplate: NodePool -> launchable claim template.

Mirror of the reference's nodeclaimtemplate.go:35-97: precomputed
Requirements from the pool template (requirements + labels + pool identity),
with price-ordered truncation to MAX_INSTANCE_TYPES at claim-creation time.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..api import labels as labels_mod
from ..api.objects import (
    NodeClaim,
    NodeClaimSpec,
    NodePool,
    NodeSelectorRequirement,
    ObjectMeta,
    new_uid,
)
from ..api.requirements import Operator, Requirement, Requirements
from ..cloudprovider import types as cp

MAX_INSTANCE_TYPES = 60


class NodeClaimTemplate:
    def __init__(self, node_pool: NodePool):
        self.node_pool_name = node_pool.name
        self.node_pool_uid = node_pool.uid
        self.node_pool_weight = node_pool.spec.weight
        template = node_pool.spec.template
        self.labels = dict(template.labels)
        self.labels[labels_mod.NODEPOOL_LABEL_KEY] = node_pool.name
        self.annotations = dict(template.annotations)
        self.spec = template.spec
        self.taints = list(template.spec.taints)
        self.startup_taints = list(template.spec.startup_taints)
        self.instance_type_options: List[cp.InstanceType] = []
        self.requirements = Requirements()
        self.requirements.add(
            *(r.to_requirement() for r in template.spec.requirements)
        )
        self.requirements.add(*Requirements.from_labels(self.labels).values())

    def new_claim_name(self) -> str:
        return f"{self.node_pool_name}-{new_uid()[:8]}"

    def to_node_claim(
        self, instance_type_options=None, requirements=None
    ) -> NodeClaim:
        """Materialize a NodeClaim CR, truncating instance types by price
        (nodeclaimtemplate.go:71-97).

        Callers pass the claim's OWN narrowed options/requirements (the
        reference embeds a per-claim template copy; this template object is
        shared, so the narrowing travels explicitly).
        """
        options = (
            instance_type_options
            if instance_type_options is not None
            else self.instance_type_options
        )
        reqs = Requirements(
            *(
                r
                for r in (requirements if requirements is not None else self.requirements)
                # the scheduling hostname placeholder must not reach the CR
                # (reference FinalizeScheduling, nodeclaim.go:242-258)
                if r.key != labels_mod.HOSTNAME
            )
        )
        # minValues is re-validated AFTER the 60-type truncation: the
        # cheapest prefix may span too few distinct values even though the
        # full option set satisfied the floor (nodeclaimtemplate
        # ToNodeClaim; instance_selection_test.go:1337). Solve results are
        # pre-validated (Results.truncate_instance_types); this guards
        # direct launches.
        ordered, err = cp.truncate(options, reqs, MAX_INSTANCE_TYPES)
        if err is not None:
            raise ValueError(
                "minValues requirement is not met after truncation: " + err
            )
        reqs.add(
            Requirement(
                labels_mod.INSTANCE_TYPE,
                Operator.IN,
                [it.name for it in ordered],
                min_values=reqs.get(labels_mod.INSTANCE_TYPE).min_values,
            )
        )
        name = self.new_claim_name()
        spec = NodeClaimSpec(
            requirements=[
                NodeSelectorRequirement(
                    r.key,
                    r.operator().value,
                    tuple(r.values_list()),
                    min_values=r.min_values,
                )
                for r in reqs
            ],
            taints=list(self.taints),
            startup_taints=list(self.startup_taints),
            node_class_ref=self.spec.node_class_ref,
            expire_after=self.spec.expire_after,
            termination_grace_period=self.spec.termination_grace_period,
        )
        return NodeClaim(
            metadata=ObjectMeta(
                name=name,
                labels=dict(self.labels),
                annotations=dict(self.annotations),
                owner_uids=[self.node_pool_uid],
            ),
            spec=spec,
        )
