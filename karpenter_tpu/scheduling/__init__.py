from .hostports import HostPortUsage
from .queue import Queue
from .topology import (
    Topology,
    TopologyDomainGroup,
    TopologyGroup,
    TopologyNodeFilter,
    TopologyType,
)

__all__ = [
    "HostPortUsage",
    "Queue",
    "Topology",
    "TopologyDomainGroup",
    "TopologyGroup",
    "TopologyNodeFilter",
    "TopologyType",
]
