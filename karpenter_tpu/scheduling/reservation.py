"""Reserved-capacity ledger (reference: reservationmanager.go:28-85)."""

from __future__ import annotations

from typing import Dict, List, Sequence, Set

from ..api import labels as labels_mod
from ..cloudprovider.types import Offering


class ReservationManager:
    def __init__(self, instance_types_by_pool: Dict[str, List]):
        self._capacity: Dict[str, int] = {}
        self._reservations: Dict[str, Set[str]] = {}  # hostname -> reservation ids
        for its in instance_types_by_pool.values():
            for it in its:
                for o in it.offerings:
                    if o.capacity_type() != labels_mod.CAPACITY_TYPE_RESERVED:
                        continue
                    rid = o.reservation_id()
                    # track the least capacity seen per reservation id
                    if rid not in self._capacity or self._capacity[rid] > o.reservation_capacity:
                        self._capacity[rid] = o.reservation_capacity

    def reserve(self, hostname: str, offering: Offering) -> bool:
        rid = offering.reservation_id()
        held = self._reservations.setdefault(hostname, set())
        if rid in held:
            return True  # idempotent per host
        if rid not in self._capacity:
            raise RuntimeError(f"reserving unknown reservation id {rid!r}")
        if self._capacity[rid] == 0:
            return False
        self._capacity[rid] -= 1
        held.add(rid)
        return True

    def release(self, hostname: str, *offerings: Offering) -> None:
        held = self._reservations.get(hostname)
        if not held:
            return
        for o in offerings:
            rid = o.reservation_id()
            if rid in held:
                held.discard(rid)
                self._capacity[rid] += 1
