"""Per-node volume-attachment tracking against CSI driver limits.

Mirror of the reference's pkg/scheduling/volumeusage.go: each node tracks
which unique volumes (per CSI driver) are attached; adding a pod may not push
any driver past its CSINode attach limit. The scheduler consults this from
ExistingNode.Add (existingnode.go volume-limit check); new in-flight claims
have no CSINode yet, so limits only apply to existing nodes — same as the
reference.

VolumeResolver is the single PVC -> PV / StorageClass resolution walk, shared
by attach-limit accounting (driver + volume id) and zonal topology injection
(zones) — volumetopology.py consumes the same ResolvedVolume records.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Set, Tuple

from ..api.objects import (
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
)
from ..kube.store import NotFoundError


class ResolvedVolume(NamedTuple):
    driver: str  # CSI driver ("" when unresolvable: uncounted)
    volume_id: str  # PV name when bound, else ns/claim
    zones: Tuple[str, ...]  # zonal constraint from the PV or StorageClass


class VolumeResolver:
    """Resolves a pod's PVC references to (csi driver, volume id, zones).

    Bound PVCs resolve through their PersistentVolume (volume name is the
    identity); unbound PVCs resolve through their StorageClass provisioner
    with the claim itself as identity (reference: volumeusage.go
    resolveDriver/VolumeName + volumetopology.go getPersistentVolumeTopology).
    PVCs are namespaced; PVs and StorageClasses are cluster-scoped."""

    def __init__(self, client):
        self.client = client

    def resolve(
        self, pod: Pod, strict: bool = False
    ) -> Tuple[List[ResolvedVolume], Optional[str]]:
        """Returns ([ResolvedVolume], error). A missing PVC is always an
        error; with ``strict`` a missing StorageClass on an unbound PVC is
        too (volumetopology.go:152-199's validation), otherwise the volume
        resolves driverless and uncounted (tolerant of in-tree volumes)."""
        out: List[ResolvedVolume] = []
        ns = getattr(pod.metadata, "namespace", "default")
        for ref in pod.spec.volumes:
            try:
                pvc = self.client.get(PersistentVolumeClaim, ref.claim_name, ns)
            except NotFoundError:
                return [], f"persistentvolumeclaim {ref.claim_name!r} not found"
            driver = ""
            volume_id = f"{ns}/{ref.claim_name}"
            zones: Tuple[str, ...] = ()
            if pvc.volume_name:
                pv = self.client.try_get(PersistentVolume, pvc.volume_name)
                if pv is not None:
                    driver = pv.driver
                    volume_id = pvc.volume_name
                    zones = pv.zones
            elif pvc.storage_class_name:
                sc = self.client.try_get(StorageClass, pvc.storage_class_name)
                if sc is None:
                    if strict:
                        return [], (
                            f"storageclass {pvc.storage_class_name!r} for claim"
                            f" {ref.claim_name!r} not found"
                        )
                else:
                    driver = sc.provisioner
                    zones = sc.zones
            if driver:
                out.append(ResolvedVolume(driver, volume_id, zones))
            elif zones:
                out.append(ResolvedVolume("", volume_id, zones))
        return out, None


class VolumeUsage:
    """Tracks unique volumes per CSI driver attached to one node."""

    def __init__(self):
        self._volumes: Dict[str, Set[str]] = {}  # driver -> volume ids
        self._pod_volumes: Dict[str, List[Tuple[str, str]]] = {}  # pod uid

    def add(self, pod: Pod, resolved: Sequence) -> None:
        # retract a previous resolution first: a PVC binding changes its
        # volume identity from ns/claim to the PV name
        if pod.uid in self._pod_volumes:
            self.delete_pod(pod.uid)
        counted = [(r[0], r[1]) for r in resolved if r[0]]
        self._pod_volumes[pod.uid] = counted
        for driver, vid in counted:
            self._volumes.setdefault(driver, set()).add(vid)

    def delete_pod(self, uid: str) -> None:
        resolved = self._pod_volumes.pop(uid, ())
        for driver, vid in resolved:
            vols = self._volumes.get(driver)
            if vols is None:
                continue
            # only drop the volume if no remaining pod references it
            if not any(
                (driver, vid) in other for other in self._pod_volumes.values()
            ):
                vols.discard(vid)

    def snapshot(self) -> Dict[str, List[Tuple[str, str]]]:
        """Wire-portable form: pod uid -> [(driver, volume id)]. The
        per-driver volume sets are derivable, so only the pod map ships."""
        return {uid: list(pairs) for uid, pairs in self._pod_volumes.items()}

    @classmethod
    def from_snapshot(cls, snap) -> "VolumeUsage":
        vu = cls()
        for uid, pairs in (snap or {}).items():
            counted = [(d, v) for d, v in pairs]
            vu._pod_volumes[uid] = counted
            for d, v in counted:
                if d:
                    vu._volumes.setdefault(d, set()).add(v)
        return vu

    def has(self, driver: str, volume_id: str) -> bool:
        """True when the volume is already attached to this node (the
        solver's dense attach-slot ledger routes such pods host-side:
        per-node dedup can't be expressed as a uniform request)."""
        return volume_id in self._volumes.get(driver, ())

    def attached(self) -> Iterable[Tuple[str, str]]:
        """Every (driver, volume id) currently attached — the solver's
        batch-admission precomputation."""
        for driver, vols in self._volumes.items():
            for vid in vols:
                yield (driver, vid)

    def attached_count(self, driver: str) -> int:
        """Distinct volumes attached for one driver (the encoder's
        remaining-attach-slot column derives from this)."""
        return len(self._volumes.get(driver, ()))

    def attached_counts(self) -> Dict[str, int]:
        """Per-driver distinct-volume counts (delta-tag content)."""
        return {d: len(v) for d, v in self._volumes.items()}

    def validate(self, resolved: Sequence, limits: Dict[str, int]) -> Optional[str]:
        """Error string if adding ``resolved`` would exceed any driver's
        attach limit (volumeusage.go exceedsLimits)."""
        proposed: Dict[str, Set[str]] = {}
        for r in resolved:
            driver, vid = r[0], r[1]
            if not driver:
                continue
            existing = self._volumes.get(driver, set())
            if vid in existing:
                continue
            proposed.setdefault(driver, set()).add(vid)
        for driver, new in proposed.items():
            limit = limits.get(driver)
            if limit is None:
                continue
            count = len(self._volumes.get(driver, set())) + len(new)
            if count > limit:
                return (
                    f"would exceed csi driver {driver!r} volume limit"
                    f" ({count} > {limit})"
                )
        return None

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage()
        out._volumes = {d: set(v) for d, v in self._volumes.items()}
        out._pod_volumes = {u: list(v) for u, v in self._pod_volumes.items()}
        return out
