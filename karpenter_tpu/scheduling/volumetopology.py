"""PVC zone-topology injection, run before scheduling.

Mirror of the reference's
pkg/controllers/provisioning/scheduling/volumetopology.go: pods that
reference zonal volumes (bound PVs with zone affinity, or StorageClasses
with allowed zonal topologies) must land in those zones, so the injector
rewrites the pod's required node affinity to include the zone requirement
(volumetopology.go:42-78). validate_persistent_volume_claims rejects pods
whose PVCs or StorageClasses don't exist (volumetopology.go:152-199).

Resolution itself (PVC -> PV/StorageClass walk) is shared with the
attach-limit accounting via VolumeResolver (volumeusage.py).
"""

from __future__ import annotations

from typing import List, Optional

from ..api import labels as labels_mod
from ..api.objects import NodeAffinity, NodeSelectorRequirement, Pod
from .volumeusage import VolumeResolver


class VolumeTopology:
    def __init__(self, client):
        self.resolver = VolumeResolver(client)

    # -- injection (volumetopology.go:42-78) ------------------------------

    def inject(self, pod: Pod) -> None:
        """Add zonal volume requirements to the pod's required node
        affinity. Mutates the (already deep-copied) scheduling pod."""
        resolved, _ = self.resolver.resolve(pod)
        requirements: List[NodeSelectorRequirement] = [
            NodeSelectorRequirement(labels_mod.TOPOLOGY_ZONE, "In", tuple(r.zones))
            for r in resolved
            if r.zones
        ]
        if not requirements:
            return
        if pod.spec.node_affinity is None:
            pod.spec.node_affinity = NodeAffinity()
        affinity = pod.spec.node_affinity
        if affinity.required:
            # zone requirements apply to every OR-term (volumetopology.go:66-73)
            affinity.required = [
                tuple(term) + tuple(requirements) for term in affinity.required
            ]
        else:
            affinity.required = [tuple(requirements)]

    # -- validation (volumetopology.go:152-199) ----------------------------

    def validate_persistent_volume_claims(self, pod: Pod) -> Optional[str]:
        """Error if any referenced PVC (or an unbound PVC's StorageClass)
        doesn't exist; such pods are ignored by provisioning."""
        _, err = self.resolver.resolve(pod, strict=True)
        return err
