"""Preference relaxation (reference: preferences.go:38-146).

When a pod fails to schedule, soft constraints are dropped one per attempt,
in a fixed order: extra required node-affinity OR-terms first, then preferred
pod affinity, preferred pod anti-affinity, preferred node affinity (heaviest
first), ScheduleAnyway topology spreads, and optionally a PreferNoSchedule
toleration.
"""

from __future__ import annotations

from typing import Optional

from ..api.objects import Pod, Toleration
from ..api.taints import PREFER_NO_SCHEDULE


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod: Pod) -> bool:
        relaxations = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_schedule_anyway_spread,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule_taints)
        for fn in relaxations:
            if fn(pod) is not None:
                return True
        return False

    def _remove_required_node_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.node_affinity
        if affinity is None or len(affinity.required) <= 1:
            return None  # cannot remove the last OR-term
        removed = affinity.required.pop(0)
        return f"removed required node affinity term {removed}"

    def _remove_preferred_node_affinity_term(self, pod: Pod) -> Optional[str]:
        affinity = pod.spec.node_affinity
        if affinity is None or not affinity.preferred:
            return None
        affinity.preferred.sort(key=lambda t: -t.weight)
        removed = affinity.preferred.pop(0)
        return f"removed preferred node affinity term weight={removed.weight}"

    def _remove_preferred_pod_affinity_term(self, pod: Pod) -> Optional[str]:
        if not pod.spec.preferred_pod_affinity:
            return None
        pod.spec.preferred_pod_affinity.sort(key=lambda t: -t.weight)
        removed = pod.spec.preferred_pod_affinity.pop(0)
        return f"removed preferred pod affinity weight={removed.weight}"

    def _remove_preferred_pod_anti_affinity_term(self, pod: Pod) -> Optional[str]:
        if not pod.spec.preferred_pod_anti_affinity:
            return None
        pod.spec.preferred_pod_anti_affinity.sort(key=lambda t: -t.weight)
        removed = pod.spec.preferred_pod_anti_affinity.pop(0)
        return f"removed preferred pod anti-affinity weight={removed.weight}"

    def _remove_schedule_anyway_spread(self, pod: Pod) -> Optional[str]:
        for i, tsc in enumerate(pod.spec.topology_spread_constraints):
            if tsc.when_unsatisfiable == "ScheduleAnyway":
                pod.spec.topology_spread_constraints.pop(i)
                return f"removed ScheduleAnyway spread on {tsc.topology_key}"
        return None

    def _tolerate_prefer_no_schedule_taints(self, pod: Pod) -> Optional[str]:
        for t in pod.spec.tolerations:
            if t.operator == "Exists" and t.effect == PREFER_NO_SCHEDULE and not t.key:
                return None
        pod.spec.tolerations.append(
            Toleration(operator="Exists", effect=PREFER_NO_SCHEDULE)
        )
        return "added PreferNoSchedule toleration"
