"""In-flight NodeClaim and ExistingNode simulation models.

Mirror of the reference's nodeclaim.go:83-434 and existingnode.go:31-122: the
Add(pod) discipline — taints -> host ports -> requirements compat+tighten ->
topology tighten -> instance-type filter -> reserved-offering accounting —
committing mutations only when every gate passes.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import labels as labels_mod
from ..api import resources as res
from ..api import taints as taints_mod
from ..api.objects import Pod, Taint
from ..api.requirements import Operator, Requirement, Requirements
from ..cloudprovider import types as cp
from .hostports import HostPortUsage
from .reservation import ReservationManager
from .template import NodeClaimTemplate
from .topology import Topology

# reserved-offering modes (reference: scheduler.go:49-78)
RESERVED_OFFERING_MODE_FALLBACK = "fallback"
RESERVED_OFFERING_MODE_STRICT = "strict"


class ReservedOfferingError(Exception):
    """Failure to adhere to the reservation policy; not relaxable."""


class PodData:
    """Cached per-pod scheduling data (reference: scheduler.go:136-141).
    Volume resolution is pod-scoped and node-independent, so it's cached
    here rather than re-walked per ExistingNode attempt."""

    __slots__ = (
        "requests",
        "requirements",
        "strict_requirements",
        "resolved_volumes",
        "volume_error",
    )

    def __init__(
        self,
        requests,
        requirements,
        strict_requirements,
        resolved_volumes=(),
        volume_error=None,
    ):
        self.requests = requests
        self.requirements = requirements
        self.strict_requirements = strict_requirements
        self.resolved_volumes = resolved_volumes
        self.volume_error = volume_error


def filter_instance_types(
    instance_types: Sequence[cp.InstanceType],
    requirements: Requirements,
    pod_requests: res.ResourceList,
    daemon_requests: res.ResourceList,
    total_requests: res.ResourceList,
) -> Tuple[List[cp.InstanceType], Optional[str]]:
    """compatible && fits && hasOffering filter, with minValues validation
    (reference: nodeclaim.go:363-426). Returns (remaining, error)."""
    remaining = []
    any_compat = any_fits = any_offering = False
    for it in instance_types:
        it_compat = it.requirements.intersects(requirements) is None
        it_fits = res.fits(total_requests, it.allocatable())
        it_offering = cp.has_compatible(cp.available(it.offerings), requirements)
        any_compat |= it_compat
        any_fits |= it_fits
        any_offering |= it_offering
        if it_compat and it_fits and it_offering:
            remaining.append(it)
    if requirements.has_min_values():
        _, err = cp.satisfies_min_values(remaining, requirements)
        if err is not None:
            remaining = []
    if not remaining:
        detail = (
            f"no instance type satisfied resources {res.to_string(total_requests)}"
            f" and requirements (compatible={any_compat}, fits={any_fits},"
            f" offering={any_offering})"
        )
        return [], detail
    return remaining, None


_hostname_seq = itertools.count(1)


class InFlightNodeClaim:
    """A simulated node being built up during a Solve
    (reference: nodeclaim.go:83-165)."""

    def __init__(
        self,
        template: NodeClaimTemplate,
        topology: Topology,
        daemon_resources: res.ResourceList,
        instance_types: List[cp.InstanceType],
        reservation_manager: Optional[ReservationManager] = None,
        reserved_offering_mode: str = RESERVED_OFFERING_MODE_FALLBACK,
        reserved_capacity_enabled: bool = False,
    ):
        self.template = template
        self.topology = topology
        self.hostname = f"hostname-placeholder-{next(_hostname_seq):05d}"
        self.requirements = Requirements(*template.requirements.values())
        self.requirements.add(
            Requirement(labels_mod.HOSTNAME, Operator.IN, [self.hostname])
        )
        topology.register(labels_mod.HOSTNAME, self.hostname)
        self.instance_type_options = list(instance_types)
        self.daemon_resources = daemon_resources
        self.requests: res.ResourceList = dict(daemon_resources)
        self.pods: List[Pod] = []
        self.hostport_usage = HostPortUsage()
        self.reservation_manager = reservation_manager
        self.reserved_offering_mode = reserved_offering_mode
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.reserved_offerings: List[cp.Offering] = []

    def add(self, pod: Pod, pod_data: PodData) -> Optional[str]:
        """Try to place the pod; mutates state only on success. Returns an
        error string (or raises ReservedOfferingError) on failure."""
        err = taints_mod.tolerates_pod(self.template.taints, pod)
        if err is not None:
            return err
        err = self.hostport_usage.conflicts(pod)
        if err is not None:
            return err

        claim_requirements = Requirements(*self.requirements.values())
        err = claim_requirements.compatible(
            pod_data.requirements, labels_mod.WELL_KNOWN_LABELS
        )
        if err is not None:
            return err  # kept unformatted: hot path (nodeclaim.go:125-127)
        claim_requirements.add(*pod_data.requirements.values())

        topo_requirements, err = self.topology.add_requirements(
            pod,
            self.template.taints,
            pod_data.strict_requirements,
            claim_requirements,
        )
        if err is not None:
            return err
        err = claim_requirements.compatible(topo_requirements, labels_mod.WELL_KNOWN_LABELS)
        if err is not None:
            return err
        claim_requirements.add(*topo_requirements.values())

        requests = res.merge(self.requests, pod_data.requests)
        remaining, err = filter_instance_types(
            self.instance_type_options,
            claim_requirements,
            pod_data.requests,
            self.daemon_resources,
            requests,
        )
        if err is not None:
            return err

        reserved = self._reserve_offerings(remaining, claim_requirements)

        # commit
        self.pods.append(pod)
        self.instance_type_options = remaining
        self.requests = requests
        self.requirements = claim_requirements
        self.topology.record(pod, self.template.taints, claim_requirements)
        self.hostport_usage.add(pod)
        self._release_stale_reservations(self.reserved_offerings, reserved)
        self.reserved_offerings = reserved
        return None

    # -- reserved offerings (nodeclaim.go:186-233) ------------------------

    def _reserve_offerings(
        self, instance_types: List[cp.InstanceType], requirements: Requirements
    ) -> List[cp.Offering]:
        if not self.reserved_capacity_enabled or self.reservation_manager is None:
            return []
        has_compatible = False
        reserved: List[cp.Offering] = []
        for it in instance_types:
            for o in it.offerings:
                if (
                    o.capacity_type() != labels_mod.CAPACITY_TYPE_RESERVED
                    or not o.available
                ):
                    continue
                if not requirements.is_compatible(
                    o.requirements, labels_mod.WELL_KNOWN_LABELS
                ):
                    continue
                has_compatible = True
                if self.reservation_manager.reserve(self.hostname, o):
                    reserved.append(o)
        if self.reserved_offering_mode == RESERVED_OFFERING_MODE_STRICT:
            if has_compatible and not reserved:
                raise ReservedOfferingError(
                    "compatible reserved offerings exist but could not be reserved"
                )
            if self.reserved_offerings and not reserved:
                raise ReservedOfferingError(
                    "updated constraints would remove all reserved offering options"
                )
        return reserved

    def _release_stale_reservations(
        self, current: List[cp.Offering], updated: List[cp.Offering]
    ) -> None:
        if self.reservation_manager is None:
            return
        updated_ids = {o.reservation_id() for o in updated}
        for o in current:
            if o.reservation_id() not in updated_ids:
                self.reservation_manager.release(self.hostname, o)

    def destroy(self) -> None:
        """Roll back topology/reservation registration for an unused claim
        (nodeclaim.go:235-246)."""
        self.topology.unregister(labels_mod.HOSTNAME, self.hostname)
        if self.reservation_manager is not None:
            self.reservation_manager.release(self.hostname, *self.reserved_offerings)

    def finalize(self) -> None:
        """Swap the placeholder hostname for the real claim name
        (nodeclaim.go:242-258). Only the NAME is minted here — the full CR
        materializes at launch (to_node_claim), where truncation-time
        minValues validation may still refuse it."""
        name = self.template.new_claim_name()
        self.topology.unregister(labels_mod.HOSTNAME, self.hostname)
        self.hostname = name
        self.topology.register(labels_mod.HOSTNAME, self.hostname)
        self.requirements.add(
            Requirement(labels_mod.HOSTNAME, Operator.IN, [self.hostname])
        )

    def remove_expensive_types_than(self, max_price: float, requirements: Requirements) -> bool:
        """Keep only instance types strictly cheaper than max_price
        (nodeclaim.go RemoveInstanceTypeOptionsByPriceAndMinValues).
        Returns False if that empties the options or breaks minValues."""
        kept = [
            it
            for it in self.instance_type_options
            if cp.min_compatible_price(it, requirements) < max_price
        ]
        if requirements.has_min_values():
            _, err = cp.satisfies_min_values(kept, requirements)
            if err is not None:
                return False
        if not kept:
            return False
        self.instance_type_options = kept
        return True


class ExistingNode:
    """Add(pod) against a real or in-flight cluster node
    (reference: existingnode.go:31-122)."""

    @staticmethod
    def build_requirements(state_node) -> Requirements:
        """The node's label requirements + hostname pin. Add() REPLACES
        self.requirements with a merged copy, but the TPU decode's
        existing-node fill commit mutates the container in place — so
        schedulers caching across solves must cache the (immutable)
        Requirement ENTRIES and hand each solve a fresh container
        (Scheduler._calculate_existing_nodes does exactly that)."""
        reqs = Requirements.from_labels(state_node.labels())
        reqs.add(
            Requirement(labels_mod.HOSTNAME, Operator.IN, [state_node.hostname()])
        )
        return reqs

    def __init__(
        self,
        state_node,
        topology: Topology,
        taints: List[Taint],
        daemon_resources: res.ResourceList,
        base_requirements: Requirements = None,
    ):
        self.state_node = state_node
        self.topology = topology
        self.cached_taints = taints
        self.cached_available = state_node.available()
        self.volume_usage = getattr(state_node, "volume_usage", None)
        self.volume_usage = self.volume_usage.copy() if self.volume_usage else None
        self.volume_limits = dict(getattr(state_node, "volume_limits", {}) or {})
        # daemon resources not already scheduled to the node, floored at 0
        remaining_daemons = res.subtract(
            daemon_resources, state_node.daemonset_request_total()
        )
        self.requests = {k: max(v, 0) for k, v in remaining_daemons.items()}
        self.requirements = (
            base_requirements
            if base_requirements is not None
            else self.build_requirements(state_node)
        )
        self.pods: List[Pod] = []
        self.hostport_usage = state_node.hostport_usage.copy()
        topology.register(labels_mod.HOSTNAME, state_node.hostname())

    @property
    def name(self) -> str:
        return self.state_node.name

    def initialized(self) -> bool:
        return self.state_node.initialized()

    def add(self, pod: Pod, pod_data: PodData) -> Optional[str]:
        err = taints_mod.tolerates_pod(self.cached_taints, pod)
        if err is not None:
            return err
        err = self.hostport_usage.conflicts(pod)
        if err is not None:
            return err
        # csi volume attach limits (existingnode.go volume check)
        resolved_volumes = pod_data.resolved_volumes
        if pod.spec.volumes and self.volume_usage is not None:
            if pod_data.volume_error is not None:
                return pod_data.volume_error
            err = self.volume_usage.validate(resolved_volumes, self.volume_limits)
            if err is not None:
                return err
        requests = res.merge(self.requests, pod_data.requests)
        if not res.fits(requests, self.cached_available):
            return "exceeds node resources"
        err = self.requirements.compatible(pod_data.requirements)
        if err is not None:
            return err
        node_requirements = Requirements(*self.requirements.values())
        node_requirements.add(*pod_data.requirements.values())

        topo_requirements, err = self.topology.add_requirements(
            pod, self.cached_taints, pod_data.strict_requirements, node_requirements
        )
        if err is not None:
            return err
        err = node_requirements.compatible(topo_requirements)
        if err is not None:
            return err
        node_requirements.add(*topo_requirements.values())

        # commit
        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_requirements
        self.topology.record(pod, self.cached_taints, node_requirements)
        self.hostport_usage.add(pod)
        if resolved_volumes and self.volume_usage is not None:
            self.volume_usage.add(pod, resolved_volumes)
        return None
