"""Native (C++) host solver runtime.

``solve_core_native`` is a drop-in for ops/solve.py::solve_core operating on
the same EncodedSnapshot.solve_args(...) tuple — compiled from
native/solve_core.cc and loaded through ctypes. It serves as the host
fallback when no accelerator is attached (SolverConfig.backend='native') and
as the independent implementation the JAX kernel is parity-tested against.

The shared library is built on first use with g++ (-O2 -shared -fPIC) and
cached next to the source; rebuilt when the source is newer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from .. import faults

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "solve_core.cc")
_LIB = os.path.join(_HERE, "libkt_solver.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    pass


def build(force: bool = False) -> str:
    """Compile the shared library if missing or stale; returns its path."""
    with _lock:
        if (
            not force
            and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            return _LIB
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", _LIB, _SRC,
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"g++ failed ({proc.returncode}): {proc.stderr[-2000:]}"
            )
        return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        # chaos seam: a missing toolchain / corrupt .so on a fresh host
        # surfaces as NativeBuildError, which the solver's degradation
        # ladder turns into an oracle fallback instead of a crashed solve
        faults.hit(faults.NATIVE_LOAD)
        path = build()
        lib = ctypes.CDLL(path)
        lib.kt_solve.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    try:
        _load()
        return True
    except (NativeBuildError, OSError):
        return False


def _as(arr, dtype) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(arr), dtype=dtype)


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def solve_core_native(
    g_count, g_req, g_def, g_neg, g_mask, g_hcap, g_haff,
    g_dmode, g_dkey, g_dskew, g_dmin0, g_dprior, g_dreg, g_drank,
    g_hstg, g_hscap, g_dtg,
    g_hself, g_hcontrib, g_dcontrib,
    p_def, p_neg, p_mask, p_daemon, p_limit, p_has_limit, p_tol, p_titype_ok,
    t_def, t_mask, t_alloc, t_cap,
    o_avail, o_zone, o_ct,
    a_tzc, res_cap0, a_res,
    n_def, n_mask, n_avail, n_base, n_tol, n_hcnt, n_dzone, n_dct,
    nh_cnt0, dd0, dtg_key,
    well_known,
    p_mvmin, t_mvoh,
    gk_g=None, gk_k=None, gk_w=None, goff_idx=None,
    nmax: int = 0,
    zone_kid: int = 0,
    ct_kid: int = 0,
    has_domains: bool = True,  # trace-time gate for the JAX twin; unused here
    has_contrib: bool = False,  # trace-time gate for the JAX twin; unused here
    tile_feasibility: bool = False,  # JAX execution strategy; unused here
    wf_iters: int = 32,  # JAX bisection budget; the C++ core is exact
    sparse_groups: bool = False,  # JAX table strategy; the core is sparse-always
) -> Tuple[np.ndarray, ...]:
    """Same contract as ops/solve.py::solve_core (and solve_all), on host.

    ``has_domains`` is accepted for call-site symmetry with the jitted
    kernel; the C++ core branches on g_dmode at runtime, so no gating is
    needed. The compacted segment index (gk_*/goff_idx) is likewise
    accepted for tuple symmetry but not marshalled: the core derives the
    same neutral-row mask internally (solve_core.cc feasibility section)
    and applies the identical hoisted-base + live-pair-correction
    structure unconditionally."""
    lib = _load()

    g_count = _as(g_count, np.int32)
    g_hcap = _as(g_hcap, np.int32)
    g_haff = _as(g_haff, np.uint8)
    n_hcnt = _as(n_hcnt, np.int32)
    g_req = _as(g_req, np.float32)
    g_dmode = _as(g_dmode, np.int32)
    g_dkey = _as(g_dkey, np.int32)
    g_dskew = _as(g_dskew, np.int32)
    g_dmin0 = _as(g_dmin0, np.uint8)
    g_dprior = _as(g_dprior, np.int32)
    g_dreg = _as(g_dreg, np.uint8)
    g_drank = _as(g_drank, np.int32)
    n_dzone = _as(n_dzone, np.int32)
    n_dct = _as(n_dct, np.int32)
    g_hstg = _as(g_hstg, np.int32)
    g_hscap = _as(g_hscap, np.int32)
    g_dtg = _as(g_dtg, np.int32)
    g_hself = _as(g_hself, np.uint8)
    g_hcontrib = _as(g_hcontrib, np.uint8)
    g_dcontrib = _as(g_dcontrib, np.uint8)
    nh_cnt0 = _as(nh_cnt0, np.int32)
    dd0 = _as(dd0, np.int32)
    dtg_key = _as(dtg_key, np.int32)
    res_cap0 = _as(res_cap0, np.int32)
    a_res = _as(a_res, np.uint8)
    g_def, g_neg, g_mask = (_as(x, np.uint8) for x in (g_def, g_neg, g_mask))
    p_def, p_neg, p_mask = (_as(x, np.uint8) for x in (p_def, p_neg, p_mask))
    p_daemon = _as(p_daemon, np.float32)
    p_limit = _as(p_limit, np.float32)
    p_has_limit = _as(p_has_limit, np.uint8)
    p_tol = _as(p_tol, np.uint8)
    p_titype_ok = _as(p_titype_ok, np.uint8)
    t_def, t_mask = _as(t_def, np.uint8), _as(t_mask, np.uint8)
    t_alloc, t_cap = _as(t_alloc, np.float32), _as(t_cap, np.float32)
    o_avail = _as(o_avail, np.uint8)
    o_zone, o_ct = _as(o_zone, np.int32), _as(o_ct, np.int32)
    a_tzc = _as(a_tzc, np.uint8)
    n_def, n_mask = _as(n_def, np.uint8), _as(n_mask, np.uint8)
    n_avail, n_base = _as(n_avail, np.float32), _as(n_base, np.float32)
    n_tol = _as(n_tol, np.uint8)
    well_known = _as(well_known, np.uint8)
    p_mvmin = _as(p_mvmin, np.int32)
    t_mvoh = _as(t_mvoh, np.uint8)

    G = g_count.shape[0]
    P, K = p_def.shape
    V1 = g_mask.shape[2] if G else p_mask.shape[2]
    T, R = t_alloc.shape
    O = o_avail.shape[1] if o_avail.size else 0
    N = n_avail.shape[0]
    JH = nh_cnt0.shape[1] if nh_cnt0.ndim == 2 else 1
    JD = dd0.shape[0] if dd0.ndim == 2 else 1
    NRES = res_cap0.shape[0]
    MV = p_mvmin.shape[1] if p_mvmin.ndim == 2 else 0
    MW = t_mvoh.shape[2] if t_mvoh.ndim == 3 else 1

    c_pool = np.zeros(nmax, np.int32)
    c_tmask = np.zeros((nmax, T), np.uint8)
    n_open = np.zeros(1, np.int32)
    overflow = np.zeros(1, np.uint8)
    exist_fills = np.zeros((G, max(N, 1)), np.int32)
    claim_fills = np.zeros((G, nmax), np.int32)
    unplaced = np.zeros(G, np.int32)
    c_dzone = np.full(nmax, -1, np.int32)
    c_dct = np.full(nmax, -1, np.int32)
    c_resv = np.zeros(nmax, np.uint8)

    lib.kt_solve(
        ctypes.c_int(G), ctypes.c_int(T), ctypes.c_int(P), ctypes.c_int(N),
        ctypes.c_int(R), ctypes.c_int(K), ctypes.c_int(V1), ctypes.c_int(O),
        ctypes.c_int(nmax), ctypes.c_int(zone_kid), ctypes.c_int(ct_kid),
        ctypes.c_int(JH), ctypes.c_int(JD), ctypes.c_int(NRES),
        ctypes.c_int(MV), ctypes.c_int(MW),
        _ptr(g_count), _ptr(g_req), _ptr(g_def), _ptr(g_neg), _ptr(g_mask),
        _ptr(g_hcap), _ptr(g_haff),
        _ptr(g_dmode), _ptr(g_dkey), _ptr(g_dskew), _ptr(g_dmin0),
        _ptr(g_dprior), _ptr(g_dreg), _ptr(g_drank),
        _ptr(g_hstg), _ptr(g_hscap), _ptr(g_dtg),
        _ptr(g_hself), _ptr(g_hcontrib), _ptr(g_dcontrib),
        _ptr(p_def), _ptr(p_neg), _ptr(p_mask), _ptr(p_daemon), _ptr(p_limit),
        _ptr(p_has_limit), _ptr(p_tol), _ptr(p_titype_ok),
        _ptr(t_def), _ptr(t_mask), _ptr(t_alloc), _ptr(t_cap),
        _ptr(o_avail), _ptr(o_zone), _ptr(o_ct),
        _ptr(a_tzc), _ptr(res_cap0), _ptr(a_res),
        _ptr(n_def), _ptr(n_mask), _ptr(n_avail), _ptr(n_base), _ptr(n_tol),
        _ptr(n_hcnt),
        _ptr(n_dzone), _ptr(n_dct),
        _ptr(nh_cnt0), _ptr(dd0), _ptr(dtg_key),
        _ptr(well_known),
        _ptr(p_mvmin), _ptr(t_mvoh),
        _ptr(c_pool), _ptr(c_tmask), _ptr(n_open), _ptr(overflow),
        _ptr(exist_fills), _ptr(claim_fills), _ptr(unplaced),
        _ptr(c_dzone), _ptr(c_dct), _ptr(c_resv),
    )
    return (
        c_pool,
        c_tmask.astype(bool),
        n_open[0],
        bool(overflow[0]),
        exist_fills[:, :N],
        claim_fills,
        unplaced,
        c_dzone,
        c_dct,
        c_resv.astype(bool),
    )
