// Native host solver core: the C++ twin of ops/solve.py::solve_core.
//
// Implements the same decision problem as the JAX kernel — fused feasibility
// tables (ops/feasibility.py) + grouped first-fit-decreasing packing
// (ops/packing.py) — over the identical dense snapshot arrays, with the same
// tie-breaking (greedy prefix fill over existing nodes, integer water-fill
// over open claims, highest-weight-template-first for new claims), and the
// same topology forms (per-entity hostname caps; per-step domain quotas for
// zone/capacity-type spread and affinity bootstrap). The reference's runtime
// is a compiled (Go) binary; this is the TPU build's native runtime path:
// used as the host fallback when no accelerator is attached, and as an
// independent implementation the JAX kernel is parity-tested against
// (tests/test_native.py).
//
// Scalar float math is done in float32 to match XLA's element types so the
// two implementations agree bit-for-bit on fits counts.
//
// The parity anchors below declare this twin's semantic skeleton —
// phases, shared constants, dtypes, tie-break disciplines, and the
// carried-state inventory — which karpenter_tpu/analysis/parity.py checks
// against the AST-derived skeletons of pack/pack_classed. When a semantic
// landmark moves here, move its anchor with it; when one is added to the
// JAX kernels, add the matching anchor or presubmit fails with PAR5xx.
//
// parity: dtype float32
// parity: dtype int32
// parity: dtype bool

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>
#include <cmath>
#include <limits>

namespace {

using std::int32_t;
using std::uint8_t;

constexpr float kInf = std::numeric_limits<float>::infinity();
// parity: const kBigFit = 2**30
constexpr int32_t kBigFit = 1 << 30;
// parity: const kBigDom = 2**28
constexpr int32_t kBigDom = 1 << 28;  // "unbounded" domain capacity (_BIGI)

// fits_count (ops/feasibility.py:68-80): identical float32 semantics,
// including the division epsilon.
// parity: const 1e-9
inline int32_t fits_count(const float* alloc, const float* base, const float* req,
                          int R) {
  bool ok_zero = true;
  float n = kInf;
  for (int r = 0; r < R; ++r) {
    float headroom = alloc[r] - base[r];
    if (!(req[r] > 0.0f) && !(headroom >= 0.0f)) ok_zero = false;
    float per = (req[r] > 0.0f)
                    ? std::floor(headroom / std::max(req[r], 1e-9f))
                    : kInf;
    n = std::min(n, per);
  }
  if (std::isinf(n)) n = static_cast<float>(kBigFit);
  if (!ok_zero) return 0;
  return static_cast<int32_t>(std::max(n, 0.0f));
}

struct Dims {
  int G, T, P, N, R, K, V1, O, NMAX, zone_kid, ct_kid;
};

// Type t has an available offering in domain slot d of the constrained axis
// (dkey 0 = zone-major of a_tzc, 1 = capacity-type) under `other` on the
// other axis. Callers separately require the constrained-axis mask to admit
// d (the JAX kernel's toff = einsum(other) ∧ dom-row).
inline bool off_in_domain(const uint8_t* az /* [V1, V1] */, int dkey, int d,
                          const uint8_t* other, int V1) {
  if (dkey == 0) {
    for (int c = 0; c < V1; ++c)
      if (az[d * V1 + c] && other[c]) return true;
  } else {
    for (int z = 0; z < V1; ++z)
      if (az[z * V1 + d] && other[z]) return true;
  }
  return false;
}

// greedy_prefix_fill (ops/packing.py): the running `before` total is the
// exclusive prefix sum — slot priority order is the tie rule.
// parity: tiebreak cumsum
inline void greedy_prefix_fill(const std::vector<int32_t>& cap, int32_t n,
                               std::vector<int32_t>& fill) {
  int32_t before = 0;
  for (size_t i = 0; i < cap.size(); ++i) {
    int32_t f = n - before;
    if (f < 0) f = 0;
    if (f > cap[i]) f = cap[i];
    fill[i] = f;
    before += cap[i];
  }
}

// waterfill (ops/packing.py): identical level/deficit semantics — the
// deficit layer hands out by slot index, exactly argmin's tie rule.
// parity: tiebreak argmin
inline void waterfill(const std::vector<int32_t>& npods,
                      const std::vector<int32_t>& cap, int32_t n,
                      std::vector<int32_t>& fills) {
  int64_t total_cap = 0;
  for (int32_t c : cap) total_cap += c;
  if (n > total_cap) n = static_cast<int32_t>(total_cap);
  auto f = [&](int64_t level) {
    int64_t s = 0;
    for (size_t i = 0; i < cap.size(); ++i) {
      int64_t v = level - npods[i];
      if (v < 0) v = 0;
      if (v > cap[i]) v = cap[i];
      s += v;
    }
    return s;
  };
  int64_t hi = 1;
  for (size_t i = 0; i < cap.size(); ++i)
    hi = std::max<int64_t>(hi, static_cast<int64_t>(npods[i]) + cap[i] + 1);
  int64_t lo = 0;
  while (lo + 1 < hi) {  // smallest level with f(level) >= n
    int64_t mid = (lo + hi) / 2;
    if (f(mid) >= n)
      hi = mid;
    else
      lo = mid;
  }
  int64_t level = (f(0) >= n) ? 0 : hi;
  int64_t deficit = n;
  std::vector<uint8_t> elig(cap.size(), 0);
  for (size_t i = 0; i < cap.size(); ++i) {
    int64_t base = (level - 1) - npods[i];
    if (base < 0) base = 0;
    if (base > cap[i]) base = cap[i];
    fills[i] = static_cast<int32_t>(base);
    deficit -= base;
    elig[i] = (base < cap[i]) && (npods[i] <= level - 1);
  }
  int64_t rank = 0;
  for (size_t i = 0; i < cap.size(); ++i) {
    if (elig[i]) {
      ++rank;
      if (rank <= deficit) fills[i] += 1;
    }
  }
}

// minvalues_cap (ops/packing.py): largest fill k keeping every minValues
// floor satisfied after the fill narrows options to {t : mask && fit >= k}.
// For key j / catalog value w, f_w = max fit over masked types offering w;
// the floor_j-th largest f_w (descending order statistic) is the cap for
// that key; the result is the min over constrained keys. Identical
// semantics to the JAX twin's sorted take_along_axis.
inline int32_t minvalues_cap_one(const uint8_t* tmask, const int32_t* fit,
                                 const int32_t* floors, const uint8_t* t_mvoh,
                                 int T, int MV, int MW) {
  int32_t cap = kBigDom;
  std::vector<int32_t> f(MW);
  for (int j = 0; j < MV; ++j) {
    const int32_t need = floors[j];
    if (need <= 0) continue;
    if (need > MW) return 0;  // floors beyond the catalog's value count
    std::fill(f.begin(), f.end(), 0);
    for (int t = 0; t < T; ++t) {
      if (!tmask[t] || fit[t] <= 0) continue;
      const uint8_t* row = t_mvoh + (static_cast<size_t>(t) * MV + j) * MW;
      for (int w = 0; w < MW; ++w)
        if (row[w]) f[w] = std::max(f[w], fit[t]);
    }
    std::nth_element(f.begin(), f.begin() + (need - 1), f.end(),
                     std::greater<int32_t>());
    cap = std::min(cap, f[need - 1]);
  }
  return cap;
}

}  // namespace

extern "C" {

// Returns 0 on success, 1 when NMAX overflowed (caller doubles and retries,
// matching the JAX driver's overflow loop).
int kt_solve(
    // dims
    int G, int T, int P, int N, int R, int K, int V1, int O, int NMAX,
    int zone_kid, int ct_kid, int JH, int JD, int NRES, int MV, int MW,
    // groups (FFD order)
    const int32_t* g_count, const float* g_req, const uint8_t* g_def,
    const uint8_t* g_neg, const uint8_t* g_mask,
    const int32_t* g_hcap,  // [G] per-entity hostname-topology cap
    const uint8_t* g_haff,  // [G] hostname-affinity: whole group on 1 entity
    // domain-keyed constraint descriptors (ops/packing.py DMODE_*)
    const int32_t* g_dmode, const int32_t* g_dkey, const int32_t* g_dskew,
    const uint8_t* g_dmin0,
    const int32_t* g_dprior,  // [G, V1]
    const uint8_t* g_dreg,    // [G, V1]
    const int32_t* g_drank,   // [G, V1]
    // shared-constraint slots + caps
    const int32_t* g_hstg, const int32_t* g_hscap, const int32_t* g_dtg,
    // shared-constraint roles: g_hself[G] (cap vs gate), contribution rows
    // g_hcontrib[G,JH] / g_dcontrib[G,JD] (the oracle's record() rule)
    const uint8_t* g_hself, const uint8_t* g_hcontrib,
    const uint8_t* g_dcontrib,
    // templates
    const uint8_t* p_def, const uint8_t* p_neg, const uint8_t* p_mask,
    const float* p_daemon, const float* p_limit, const uint8_t* p_has_limit,
    const uint8_t* p_tol, const uint8_t* p_titype_ok,
    // instance types
    const uint8_t* t_def, const uint8_t* t_mask, const float* t_alloc,
    const float* t_cap,
    // offerings
    const uint8_t* o_avail, const int32_t* o_zone, const int32_t* o_ct,
    const uint8_t* a_tzc,   // [T, V1, V1] (reserved offerings excluded
                            // when the reservation ledger is active)
    const int32_t* res_cap0,  // [NRES] reservation capacities
    const uint8_t* a_res,     // [NRES, T, V1, V1] per-reservation availability
    // existing nodes
    const uint8_t* n_def, const uint8_t* n_mask, const float* n_avail,
    const float* n_base, const uint8_t* n_tol,
    const int32_t* n_hcnt,  // [N, G] prior selected-pod counts
    const int32_t* n_dzone, const int32_t* n_dct,  // [N] domain value ids
    const int32_t* nh_cnt0,  // [N, JH] shared hostname-constraint priors
    const int32_t* dd0,      // [JD, V1] shared domain carry init
    const int32_t* dtg_key,  // [JD] shared domain-constraint axis (0=zone)
    const uint8_t* well_known,
    const int32_t* p_mvmin,  // [P, MV] per-template minValues floors
    const uint8_t* t_mvoh,   // [T, MV, MW] per-type catalog-value one-hots
    // outputs
    int32_t* out_c_pool,      // [NMAX]
    uint8_t* out_c_tmask,     // [NMAX, T]
    int32_t* out_n_open,      // [1]
    uint8_t* out_overflow,    // [1]
    int32_t* out_exist_fills, // [G, N]
    int32_t* out_claim_fills, // [G, NMAX]
    int32_t* out_unplaced,    // [G]
    int32_t* out_c_dzone,     // [NMAX] pinned zone value id (-1 = unpinned)
    int32_t* out_c_dct,       // [NMAX] pinned capacity-type value id
    uint8_t* out_c_resv       // [NMAX] claim holds its reservations
) {
  const int KV = K * V1;
  const int NSLOT = V1 + 2;  // V1 domains + ANY + DEAD
  const int ANY = V1, DEAD = V1 + 1;

  // ---- feasibility tables (ops/feasibility.py) ------------------------
  // compat_pg [P,G], type_ok_pgt [P,G,T], n_fit_pgt [P,G,T]
  //
  // Sparse/segment mirror of fresh_claim_feasibility_sparse: a (group,
  // key) pair is *live* when its requirement row differs from the neutral
  // (undefined, non-negated, all-true) row — the same live set the
  // encoder's compacted nonzero-mask index (encode.build_segment_index)
  // names for the JAX twins. Neutral rows collapse every intersect term
  // to the group-independent template-vs-type base, so the base is
  // hoisted out of the G loop and each group touches only its live keys;
  // cost scales with live pairs instead of G x K x V1.
  std::vector<uint8_t> compat_pg(P * G);
  std::vector<uint8_t> type_ok_pgt(static_cast<size_t>(P) * G * T);
  std::vector<int32_t> n_fit_pgt(static_cast<size_t>(P) * G * T);

  // live (group, key) pairs, CSR-style per group
  std::vector<int32_t> live_start(G + 1, 0);
  std::vector<int32_t> live_key;
  live_key.reserve(G);
  for (int g = 0; g < G; ++g) {
    for (int k = 0; k < K; ++k) {
      bool neutral = !g_def[g * K + k] && !g_neg[g * K + k];
      if (neutral) {
        const uint8_t* gm = g_mask + g * KV + k * V1;
        for (int v = 0; v < V1 && neutral; ++v) neutral = gm[v];
      }
      if (!neutral) live_key.push_back(k);
    }
    live_start[g + 1] = static_cast<int32_t>(live_key.size());
  }

  // hoisted per-(p,t,k) base term + per-(p,t) failure totals
  // base_ok = any_v(t_mask & p_mask) | !(t_def & p_def)
  std::vector<uint8_t> base_fail_ptk(static_cast<size_t>(P) * T * K);
  std::vector<int32_t> base_total_pt(static_cast<size_t>(P) * T, 0);
  std::vector<uint8_t> off_base_pt(static_cast<size_t>(P) * T);
  for (int p = 0; p < P; ++p) {
    for (int t = 0; t < T; ++t) {
      int32_t total = 0;
      for (int k = 0; k < K; ++k) {
        bool overlap = false;
        const uint8_t* tm = t_mask + t * KV + k * V1;
        const uint8_t* pm = p_mask + p * KV + k * V1;
        for (int v = 0; v < V1 && !overlap; ++v) overlap = tm[v] && pm[v];
        bool ok = overlap || !(t_def[t * K + k] && p_def[p * K + k]);
        base_fail_ptk[(static_cast<size_t>(p) * T + t) * K + k] = !ok;
        total += !ok;
      }
      base_total_pt[static_cast<size_t>(p) * T + t] = total;
      // template-only offering base (neutral zone/ct rows leave the
      // merged mask equal to the template's)
      bool off = false;
      const uint8_t* pzm = p_mask + p * KV + zone_kid * V1;
      const uint8_t* pcm = p_mask + p * KV + ct_kid * V1;
      for (int o = 0; o < O && !off; ++o) {
        if (!o_avail[t * O + o]) continue;
        int32_t z = o_zone[t * O + o], c = o_ct[t * O + o];
        off = ((z < 0) || pzm[z]) && ((c < 0) || pcm[c]);
      }
      off_base_pt[static_cast<size_t>(p) * T + t] = off;
    }
  }

  for (int p = 0; p < P; ++p) {
    for (int g = 0; g < G; ++g) {
      int32_t ls = live_start[g], le = live_start[g + 1];
      // pod-vs-template compatibility: neutral keys are vacuous for both
      // the intersect term and the custom-label allowance
      bool compat = p_tol[p * G + g];
      for (int32_t l = ls; l < le && compat; ++l) {
        int k = live_key[l];
        bool overlap = false;
        const uint8_t* pm = p_mask + p * KV + k * V1;
        const uint8_t* gm = g_mask + g * KV + k * V1;
        for (int v = 0; v < V1 && !overlap; ++v) overlap = pm[v] && gm[v];
        bool exempt = p_neg[p * K + k] && g_neg[g * K + k];
        bool both = p_def[p * K + k] && g_def[g * K + k];
        bool custom = !g_def[g * K + k] || well_known[k] ||
                      p_def[p * K + k] || g_neg[g * K + k];
        compat = (overlap || exempt || !both) && custom;
      }
      compat_pg[p * G + g] = compat;
      // merged zone/ct offering rows only differ when those rows are live
      bool zc_live = false;
      for (int32_t l = ls; l < le && !zc_live; ++l)
        zc_live = live_key[l] == zone_kid || live_key[l] == ct_kid;
      for (int t = 0; t < T; ++t) {
        size_t idx = (static_cast<size_t>(p) * G + g) * T + t;
        int32_t nf = fits_count(t_alloc + t * R, p_daemon + p * R,
                                g_req + g * R, R);
        n_fit_pgt[idx] = nf;
        // type intersect: hoisted base failures +/- live-pair corrections
        int32_t fail = base_total_pt[static_cast<size_t>(p) * T + t];
        for (int32_t l = ls; l < le; ++l) {
          int k = live_key[l];
          bool overlap3 = false;
          const uint8_t* tm = t_mask + t * KV + k * V1;
          const uint8_t* pm = p_mask + p * KV + k * V1;
          const uint8_t* gm = g_mask + g * KV + k * V1;
          for (int v = 0; v < V1 && !overlap3; ++v)
            overlap3 = tm[v] && pm[v] && gm[v];
          bool cdef = p_def[p * K + k] || g_def[g * K + k];
          bool pair_ok = overlap3 || !(t_def[t * K + k] && cdef);
          fail += static_cast<int32_t>(!pair_ok) -
                  base_fail_ptk[(static_cast<size_t>(p) * T + t) * K + k];
        }
        bool tc = fail == 0;
        bool off;
        if (zc_live) {
          off = false;
          const uint8_t* pzm = p_mask + p * KV + zone_kid * V1;
          const uint8_t* pcm = p_mask + p * KV + ct_kid * V1;
          const uint8_t* gzm = g_mask + g * KV + zone_kid * V1;
          const uint8_t* gcm = g_mask + g * KV + ct_kid * V1;
          for (int o = 0; o < O && !off; ++o) {
            if (!o_avail[t * O + o]) continue;
            int32_t z = o_zone[t * O + o], c = o_ct[t * O + o];
            off = ((z < 0) || (pzm[z] && gzm[z])) &&
                  ((c < 0) || (pcm[c] && gcm[c]));
          }
        } else {
          off = off_base_pt[static_cast<size_t>(p) * T + t];
        }
        type_ok_pgt[idx] = tc && off && (nf >= 1) &&
                           p_titype_ok[p * T + t] && compat;
      }
    }
  }

  // cap_ng [N, G] (existing_node_feasibility; strict compatibility —
  // same live-pair contraction: neutral keys are vacuous node-side too)
  std::vector<int32_t> cap_ng(static_cast<size_t>(N) * G, 0);
  for (int n = 0; n < N; ++n) {
    for (int g = 0; g < G; ++g) {
      if (!n_tol[n * G + g]) continue;
      bool compat = true;
      for (int32_t l = live_start[g]; l < live_start[g + 1] && compat; ++l) {
        int k = live_key[l];
        bool overlap = false;
        const uint8_t* nm = n_mask + n * KV + k * V1;
        const uint8_t* gm = g_mask + g * KV + k * V1;
        for (int v = 0; v < V1 && !overlap; ++v) overlap = nm[v] && gm[v];
        bool both = n_def[n * K + k] && g_def[g * K + k];
        bool custom = !g_def[g * K + k] || n_def[n * K + k] ||
                      g_neg[g * K + k];
        compat = (overlap || !both) && custom;
      }
      if (!compat) continue;
      cap_ng[static_cast<size_t>(n) * G + g] =
          fits_count(n_avail + n * R, n_base + n * R, g_req + g * R, R);
    }
  }

  // ---- pack state ------------------------------------------------------
  // the carried-state inventory, one variable per PackState field
  // parity: state exist_used, c_used, c_npods, c_active, c_pool, c_tmask
  // parity: state c_def, c_neg, c_mask, c_dzone, c_dct
  // parity: state ch_cnt, nhc, ddc, res_rem, c_resv
  // parity: state pool_rem, n_open, overflow
  std::vector<float> exist_used(n_base, n_base + static_cast<size_t>(N) * R);
  std::vector<float> c_used(static_cast<size_t>(NMAX) * R, 0.0f);
  std::vector<int32_t> c_npods(NMAX, 0);
  std::vector<uint8_t> c_active(NMAX, 0);
  std::vector<int32_t> c_pool(NMAX, 0);
  std::vector<uint8_t> c_tmask(static_cast<size_t>(NMAX) * T, 0);
  std::vector<uint8_t> c_def(static_cast<size_t>(NMAX) * K, 0);
  std::vector<uint8_t> c_neg(static_cast<size_t>(NMAX) * K, 0);
  std::vector<uint8_t> c_mask(static_cast<size_t>(NMAX) * KV, 1);
  std::vector<int32_t> c_dzone(NMAX, -1), c_dct(NMAX, -1);
  // shared-constraint carries (counts accumulate across groups)
  std::vector<int32_t> ch_cnt(static_cast<size_t>(NMAX) * JH, 0);
  std::vector<int32_t> nhc(nh_cnt0, nh_cnt0 + static_cast<size_t>(N) * JH);
  std::vector<int32_t> ddc(dd0, dd0 + static_cast<size_t>(JD) * V1);
  // reservation ledger (reservationmanager.go:28-85): availability views
  // for unheld placements (a_step: live reservations only) and for claims
  // already holding reservations (a_held: all reservations)
  const size_t a_sz = static_cast<size_t>(T) * V1 * V1;
  std::vector<int32_t> res_rem(res_cap0, res_cap0 + NRES);
  std::vector<uint8_t> c_resv(NMAX, 0);
  std::vector<uint8_t> a_step(a_tzc, a_tzc + a_sz);
  std::vector<uint8_t> a_held(a_tzc, a_tzc + a_sz);
  auto refresh_a_step = [&]() {
    std::copy(a_tzc, a_tzc + a_sz, a_step.begin());
    for (int r = 0; r < NRES; ++r) {
      if (res_rem[r] <= 0) continue;
      const uint8_t* ar = a_res + static_cast<size_t>(r) * a_sz;
      for (size_t i = 0; i < a_sz; ++i) a_step[i] |= ar[i];
    }
  };
  refresh_a_step();
  for (int r = 0; r < NRES; ++r) {
    const uint8_t* ar = a_res + static_cast<size_t>(r) * a_sz;
    for (size_t i = 0; i < a_sz; ++i) a_held[i] |= ar[i];
  }
  auto a_for_claim = [&](int s) -> const uint8_t* {
    return (NRES && c_resv[s]) ? a_held.data() : a_step.data();
  };
  std::vector<float> pool_rem(p_limit, p_limit + static_cast<size_t>(P) * R);
  int32_t n_open = 0;
  bool overflow = false;

  std::memset(out_exist_fills, 0, sizeof(int32_t) * G * N);
  std::memset(out_claim_fills, 0, sizeof(int32_t) * G * NMAX);
  std::memset(out_unplaced, 0, sizeof(int32_t) * G);

  std::vector<int32_t> exist_cap(N), exist_fill(N);
  std::vector<int32_t> claim_cap(NMAX), claim_fill(NMAX);
  std::vector<int32_t> c_slot(NMAX);
  std::vector<int32_t> qd(NSLOT), qrem(NSLOT);
  std::vector<int32_t> wf_npods(NMAX), wf_cap(NMAX), wf_fill(NMAX);
  std::vector<uint8_t> other_row(V1);
  // batch-level domain presence (the JAX kernels' has_domains static):
  // gates the tier-3 balanced-bulk-birth rule below
  bool has_domains = false;
  for (int g = 0; g < G; ++g)
    if (g_dmode[g] > 0) {
      has_domains = true;
      break;
    }

  for (int gi = 0; gi < G; ++gi) {
    int32_t count = g_count[gi];
    const float* req = g_req + gi * R;
    const uint8_t* gdef = g_def + gi * K;
    const uint8_t* gneg = g_neg + gi * K;
    const uint8_t* gmask = g_mask + gi * KV;

    // hostname-topology per-entity cap (see ops/packing.py step): spread's
    // skew bound collapses to "<= maxSkew selected pods per node/claim"
    // because hostname domains have a global min of 0.
    const int32_t hc = g_hcap[gi];
    // hostname-affinity single-entity pin (topologygroup.go:277-324
    // hostname case); n_hcnt rows hold the matching-pod priors for these
    // groups (the cap combo is demoted at encode time)
    const bool haff = g_haff[gi];

    // domain-keyed constraint descriptors
    const int32_t mode = g_dmode[gi];
    const bool dyn = mode > 0;
    const int dkey = g_dkey[gi];
    const int kid_sel = (dkey == 0) ? zone_kid : ct_kid;
    const int other_kid = (dkey == 0) ? ct_kid : zone_kid;
    const int32_t skew = g_dskew[gi];
    const bool min0 = g_dmin0[gi];
    const uint8_t* reg = g_dreg + static_cast<size_t>(gi) * V1;
    const int32_t* drank = g_drank + static_cast<size_t>(gi) * V1;
    // shared constraints: counts from the carries. Self owners (hself) are
    // capped at scap_h minus the entity's count and counted; gate owners
    // are blocked where the count exceeds the threshold, never counted.
    const int32_t jh = g_hstg[gi];
    const bool has_h = jh >= 0;
    const bool hself = has_h && g_hself[gi];
    const int32_t scap_h = g_hscap[gi];
    auto h_allow = [&](int32_t cnt) -> int32_t {
      if (!has_h) return kBigFit;
      if (hself) return std::max(scap_h - cnt, 0);
      return (cnt > scap_h) ? 0 : kBigFit;
    };
    const int32_t jd = g_dtg[gi];
    const bool has_d = jd >= 0;
    std::vector<int32_t> D0v(V1);
    for (int v = 0; v < V1; ++v)
      D0v[v] = g_dprior[static_cast<size_t>(gi) * V1 + v] +
               (has_d ? ddc[static_cast<size_t>(jd) * V1 + v] : 0);
    const int32_t* D0 = D0v.data();

    // parity: phase min-values
    // dense minValues: per-claim cap on this step's joins so the narrowed
    // option set keeps every constrained key's distinct-value floor
    // satisfied (the oracle's per-Add SatisfiesMinValues recount). Mirrors
    // ops/packing.py's cap_mv over tm = c_tmask ∧ type_ok ∧ off ∧ fits.
    std::vector<int32_t> cap_mv(MV ? NMAX : 0, kBigDom);
    if (MV) {
      std::vector<uint8_t> mv_mask(T);
      std::vector<int32_t> mv_fit(T);
      for (int s = 0; s < NMAX; ++s) {
        if (!c_active[s]) continue;
        const int pp = c_pool[s];
        const int32_t* floors = p_mvmin + static_cast<size_t>(pp) * MV;
        bool any_floor = false;
        for (int j = 0; j < MV; ++j) any_floor = any_floor || floors[j] > 0;
        if (!any_floor) continue;
        const uint8_t* sm = c_mask.data() + static_cast<size_t>(s) * KV;
        for (int t = 0; t < T; ++t) {
          mv_mask[t] = 0;
          mv_fit[t] = 0;
          if (!c_tmask[static_cast<size_t>(s) * T + t]) continue;
          if (!type_ok_pgt[(static_cast<size_t>(pp) * G + gi) * T + t])
            continue;
          int32_t add = fits_count(
              t_alloc + t * R, c_used.data() + static_cast<size_t>(s) * R,
              req, R);
          if (add < 1) continue;
          bool off = false;
          const uint8_t* az =
              a_for_claim(s) + static_cast<size_t>(t) * V1 * V1;
          for (int z = 0; z < V1 && !off; ++z) {
            if (!(sm[zone_kid * V1 + z] && gmask[zone_kid * V1 + z]))
              continue;
            for (int c = 0; c < V1; ++c)
              if (az[z * V1 + c] && sm[ct_kid * V1 + c] &&
                  gmask[ct_kid * V1 + c]) {
                off = true;
                break;
              }
          }
          if (!off) continue;
          mv_mask[t] = 1;
          mv_fit[t] = add;
        }
        cap_mv[s] = minvalues_cap_one(mv_mask.data(), mv_fit.data(), floors,
                                      t_mvoh, T, MV, MW);
      }
    }

    // parity: phase existing-nodes
    // ---- 1. existing nodes, fixed priority order ----
    for (int n = 0; n < N; ++n) {
      exist_cap[n] =
          (cap_ng[static_cast<size_t>(n) * G + gi] > 0)
              ? fits_count(n_avail + n * R, exist_used.data() + n * R, req, R)
              : 0;
      exist_cap[n] = std::min(
          exist_cap[n],
          std::max(hc - n_hcnt[static_cast<size_t>(n) * G + gi], 0));
      if (has_h)
        exist_cap[n] = std::min(
            exist_cap[n], h_allow(nhc[static_cast<size_t>(n) * JH + jh]));
    }
    bool haff_exist_served = false;
    if (haff && N) {
      bool has_prior = false;
      for (int n = 0; n < N; ++n)
        if (n_hcnt[static_cast<size_t>(n) * G + gi] > 0) {
          has_prior = true;
          break;
        }
      if (has_prior) {
        // candidates are exactly the prior-holding nodes (nonempty domains)
        for (int n = 0; n < N; ++n)
          if (n_hcnt[static_cast<size_t>(n) * G + gi] <= 0) exist_cap[n] = 0;
        haff_exist_served = true;
      } else {
        // bootstrap: the first node with capacity hosts everyone
        int first_free = -1;
        for (int n = 0; n < N; ++n)
          if (exist_cap[n] >= 1) {
            first_free = n;
            break;
          }
        for (int n = 0; n < N; ++n)
          if (n != first_free) exist_cap[n] = 0;
        haff_exist_served = first_free >= 0;
      }
    }

    // node domain slot on the constrained axis
    std::vector<int32_t> nd_slot(N, ANY);
    if (dyn) {
      for (int n = 0; n < N; ++n) {
        int32_t d = (dkey == 0) ? n_dzone[n] : n_dct[n];
        nd_slot[n] = (d >= 0 && d < V1 && reg[d]) ? d : DEAD;
      }
    }

    // ---- domain quota qd[NSLOT] (ops/packing.py step) ------------------
    std::fill(qd.begin(), qd.end(), 0);
    if (!dyn) {
      qd[ANY] = count;
    } else {
      std::vector<int32_t> czcap(V1, 0);
      for (int n = 0; n < N; ++n)
        if (nd_slot[n] < V1) czcap[nd_slot[n]] += exist_cap[n];
      // fresh_ok_d: any (template, type) feasible with an offering in d,
      // under the template∪group masks on both axes
      std::vector<uint8_t> fresh_ok(V1, 0);
      for (int p = 0; p < P; ++p) {
        const uint8_t* pm = p_mask + static_cast<size_t>(p) * KV;
        for (int v = 0; v < V1; ++v)
          other_row[v] = pm[other_kid * V1 + v] && gmask[other_kid * V1 + v];
        for (int t = 0; t < T; ++t) {
          if (!type_ok_pgt[(static_cast<size_t>(p) * G + gi) * T + t]) continue;
          const uint8_t* az = a_step.data() + static_cast<size_t>(t) * V1 * V1;
          for (int d = 0; d < V1; ++d) {
            if (fresh_ok[d]) continue;
            if (!(pm[kid_sel * V1 + d] && gmask[kid_sel * V1 + d])) continue;
            if (off_in_domain(az, dkey, d, other_row.data(), V1))
              fresh_ok[d] = 1;
          }
        }
      }
      std::vector<int32_t> realcap(V1);
      for (int d = 0; d < V1; ++d)
        realcap[d] =
            std::min<int32_t>(czcap[d] + (fresh_ok[d] ? kBigDom : 0), kBigDom);
      if (mode == 3 || mode == 4) {
        // GATE modes (DMODE_GATE_SPREAD / DMODE_GATE_AFF): the group is
        // constrained by the carry-evolved counts but never moves them.
        // gate-spread admits domains within skew of the STATIC min
        // (topologygroup.go:233-244 with selects=false); gate-affinity
        // admits currently nonempty domains (:277-290). Capacity within a
        // domain is unbounded, so the per-domain cap is just feasibility.
        int32_t mstat = kBigDom;
        for (int d = 0; d < V1; ++d)
          if (reg[d]) mstat = std::min(mstat, D0[d]);
        if (min0) mstat = 0;
        std::vector<int32_t> npods(V1), scap(V1);
        for (int d = 0; d < V1; ++d) {
          npods[d] = reg[d] ? D0[d] : kBigDom;
          bool allowed =
              reg[d] && (mode == 3 ? (D0[d] - mstat <= skew) : (D0[d] > 0));
          scap[d] = allowed ? std::min(realcap[d], count) : 0;
        }
        std::vector<int32_t> qfill(V1);
        waterfill(npods, scap, count, qfill);
        for (int d = 0; d < V1; ++d) qd[d] = qfill[d];
      } else if (mode == 1 /* DMODE_SPREAD */) {
        // L* = maxSkew + min over registered domains of (D0 + cap): the
        // closed form of sequential min-count-within-maxSkew selection
        // (topologygroup.go:205-251); minDomains pins the min to 0
        int32_t mfloor = kBigDom;
        for (int d = 0; d < V1; ++d)
          if (reg[d]) mfloor = std::min(mfloor, D0[d] + realcap[d]);
        if (min0) mfloor = 0;
        int64_t lstar = static_cast<int64_t>(skew) + mfloor;
        std::vector<int32_t> npods(V1), scap(V1);
        for (int d = 0; d < V1; ++d) {
          npods[d] = reg[d] ? D0[d] : kBigDom;
          int64_t c = reg[d] ? std::max<int64_t>(lstar - D0[d], 0) : 0;
          scap[d] = static_cast<int32_t>(
              std::min<int64_t>(c, realcap[d]));
        }
        std::vector<int32_t> qfill(V1);
        waterfill(npods, scap, count, qfill);
        for (int d = 0; d < V1; ++d) qd[d] = qfill[d];
      } else {  // DMODE_AFFINITY: bootstrap pins the group to one domain;
        // with a shared carry, a nonempty domain binds every follower
        int32_t d_aff = -1;
        int32_t best_follow = kBigDom;
        for (int d = 0; d < V1; ++d)
          if (D0[d] > 0 && reg[d] && drank[d] < best_follow) {
            best_follow = drank[d];
            d_aff = d;
          }
        for (int n = 0; n < N && d_aff < 0; ++n)
          if (exist_cap[n] >= 1 && nd_slot[n] < V1) d_aff = nd_slot[n];
        if (d_aff < 0) {
          // claim anchor (mirrors ops/packing.py): the oracle's bootstrap
          // pod walks open claims least-loaded-first before opening
          // fresh, so the least-loaded eligible PINNED claim's domain
          // binds the family
          int32_t best_load = kBigDom;
          for (int s = 0; s < NMAX; ++s) {
            if (!c_active[s]) continue;
            int32_t pin = (dkey == 0) ? c_dzone[s] : c_dct[s];
            if (pin < 0) continue;
            if (c_npods[s] >= best_load) continue;
            if (hc < 1) continue;
            if (has_h &&
                h_allow(ch_cnt[static_cast<size_t>(s) * JH + jh]) < 1)
              continue;
            const uint8_t* sm = c_mask.data() + static_cast<size_t>(s) * KV;
            const uint8_t* sd = c_def.data() + static_cast<size_t>(s) * K;
            const uint8_t* sn = c_neg.data() + static_cast<size_t>(s) * K;
            bool compat = true;
            for (int k = 0; k < K && compat; ++k) {
              bool overlap = false;
              for (int v = 0; v < V1; ++v)
                if (sm[k * V1 + v] && gmask[k * V1 + v]) {
                  overlap = true;
                  break;
                }
              bool exempt = sn[k] && gneg[k];
              if (!(overlap || exempt || !(sd[k] && gdef[k]))) compat = false;
              if (gdef[k] && !well_known[k] && !sd[k] && !gneg[k])
                compat = false;
            }
            int pp = c_pool[s];
            compat = compat && p_tol[pp * G + gi] && compat_pg[pp * G + gi];
            if (!compat) continue;
            bool fits1 = false;
            for (int t = 0; t < T && !fits1; ++t) {
              if (!c_tmask[static_cast<size_t>(s) * T + t]) continue;
              if (!type_ok_pgt[(static_cast<size_t>(pp) * G + gi) * T + t])
                continue;
              if (fits_count(t_alloc + t * R,
                             c_used.data() + static_cast<size_t>(s) * R, req,
                             R) < 1)
                continue;
              const uint8_t* azt =
                  a_for_claim(s) + static_cast<size_t>(t) * V1 * V1;
              for (int z = 0; z < V1 && !fits1; ++z) {
                if (!(sm[zone_kid * V1 + z] && gmask[zone_kid * V1 + z]))
                  continue;
                for (int c = 0; c < V1; ++c)
                  if (azt[z * V1 + c] && sm[ct_kid * V1 + c] &&
                      gmask[ct_kid * V1 + c]) {
                    fits1 = true;
                    break;
                  }
              }
            }
            if (!fits1) continue;
            best_load = c_npods[s];
            d_aff = pin;
          }
        }
        if (d_aff < 0) {
          int32_t best_rank = kBigDom;
          for (int d = 0; d < V1; ++d)
            if (fresh_ok[d] && reg[d] && drank[d] < best_rank) {
              best_rank = drank[d];
              d_aff = d;
            }
        }
        if (d_aff >= 0) qd[d_aff] = count;
      }
    }
    std::copy(qd.begin(), qd.end(), qrem.begin());

    // tier-1 fill under per-domain budgets (prefix order within each slot)
    {
      std::vector<int32_t> placed(NSLOT, 0);
      for (int n = 0; n < N; ++n) {
        int32_t f = qd[nd_slot[n]] - placed[nd_slot[n]];
        if (f < 0) f = 0;
        if (f > exist_cap[n]) f = exist_cap[n];
        exist_fill[n] = f;
        placed[nd_slot[n]] += f;
      }
      for (int n = 0; n < N; ++n) {
        if (exist_fill[n] > 0) {
          for (int r = 0; r < R; ++r)
            exist_used[static_cast<size_t>(n) * R + r] += exist_fill[n] * req[r];
          out_exist_fills[static_cast<size_t>(gi) * N + n] = exist_fill[n];
          qrem[nd_slot[n]] -= exist_fill[n];
          if (hself) nhc[static_cast<size_t>(n) * JH + jh] += exist_fill[n];
        }
      }
    }
    // a served existing-entity pin absorbs what fits; the remainder of a
    // hostname-affinity group errors rather than spilling to claims
    if (haff && haff_exist_served) std::fill(qrem.begin(), qrem.end(), 0);

    // parity: phase open-claims
    // ---- 2. open claims, least-loaded first ----
    std::vector<uint8_t> got(NMAX, 0);
    std::vector<int32_t> percap_d(dyn ? static_cast<size_t>(NMAX) * V1 : 0, 0);
    std::vector<uint8_t> adm_any(dyn ? NMAX : 0, 0);
    for (int s = 0; s < NMAX; ++s) {
      claim_cap[s] = 0;
      claim_fill[s] = 0;
      c_slot[s] = dyn ? DEAD : ANY;
      if (!c_active[s]) continue;
      // claim-vs-group key compatibility (overlap | exempt | not both
      // defined) + custom-label rule + template tolerance/compat
      bool compat = true;
      const uint8_t* sm = c_mask.data() + static_cast<size_t>(s) * KV;
      const uint8_t* sd = c_def.data() + static_cast<size_t>(s) * K;
      const uint8_t* sn = c_neg.data() + static_cast<size_t>(s) * K;
      for (int k = 0; k < K && compat; ++k) {
        bool overlap = false;
        for (int v = 0; v < V1; ++v)
          if (sm[k * V1 + v] && gmask[k * V1 + v]) {
            overlap = true;
            break;
          }
        bool exempt = sn[k] && gneg[k];
        if (!(overlap || exempt || !(sd[k] && gdef[k]))) compat = false;
        if (gdef[k] && !well_known[k] && !sd[k] && !gneg[k]) compat = false;
      }
      int pp = c_pool[s];
      compat = compat && p_tol[pp * G + gi] && compat_pg[pp * G + gi];
      if (!compat) continue;
      // per-type: options ∧ template-group table ∧ fits under load ∧
      // offering under merged masks (per admissible domain when dynamic)
      for (int v = 0; v < V1; ++v)
        other_row[v] = sm[other_kid * V1 + v] && gmask[other_kid * V1 + v];
      int32_t best = 0;
      for (int t = 0; t < T; ++t) {
        if (!c_tmask[static_cast<size_t>(s) * T + t]) continue;
        if (!type_ok_pgt[(static_cast<size_t>(pp) * G + gi) * T + t]) continue;
        int32_t add = fits_count(t_alloc + t * R,
                                 c_used.data() + static_cast<size_t>(s) * R,
                                 req, R);
        if (add < 1) continue;
        // offering over merged zone/ct masks via the ledger-aware view
        bool off = false;
        const uint8_t* az = a_for_claim(s) + static_cast<size_t>(t) * V1 * V1;
        for (int z = 0; z < V1 && !off; ++z) {
          if (!(sm[zone_kid * V1 + z] && gmask[zone_kid * V1 + z])) continue;
          for (int c = 0; c < V1; ++c) {
            if (az[z * V1 + c] && sm[ct_kid * V1 + c] &&
                gmask[ct_kid * V1 + c]) {
              off = true;
              break;
            }
          }
        }
        if (!off) continue;
        if (add > best) best = add;
        if (dyn) {
          for (int d = 0; d < V1; ++d) {
            if (!(sm[kid_sel * V1 + d] && gmask[kid_sel * V1 + d])) continue;
            if (off_in_domain(az, dkey, d, other_row.data(), V1)) {
              int32_t& pc = percap_d[static_cast<size_t>(s) * V1 + d];
              pc = std::max(pc, add);
            }
          }
        }
      }
      if (dyn) {
        // domain assignment is deferred to the quota-proportional pass
        // below (it needs the eligible-claim count first)
        for (int d = 0; d < V1; ++d) {
          if (percap_d[static_cast<size_t>(s) * V1 + d] >= 1 &&
              qrem[d] >= 1) {
            adm_any[s] = 1;
            break;
          }
        }
        continue;
      }
      claim_cap[s] = best;
      claim_cap[s] = std::min(claim_cap[s], hc);  // open claims carry no prior
      if (MV) claim_cap[s] = std::min(claim_cap[s], cap_mv[s]);
      if (has_h)
        claim_cap[s] = std::min(
            claim_cap[s], h_allow(ch_cnt[static_cast<size_t>(s) * JH + jh]));
    }
    if (dyn) {
      // quota-proportional claim spread (mirrors ops/packing.py tier-2):
      // eligible claims are ranked in slot order and cut by cumulative
      // quota; a claim whose proportional domain is inadmissible falls
      // back to the largest-remaining-quota pick (ties by lowest d).
      int32_t total_q = 0;
      int n_elig = 0;
      for (int d = 0; d < V1; ++d) total_q += std::max(qrem[d], 0);
      for (int s = 0; s < NMAX; ++s) n_elig += adm_any[s] ? 1 : 0;
      std::vector<float> cumf(V1, 0.0f);
      {
        int32_t acc = 0;
        const float denom = static_cast<float>(std::max(total_q, 1));
        // parity: const 0.5
        for (int d = 0; d < V1; ++d) {
          acc += std::max(qrem[d], 0);
          cumf[d] = static_cast<float>(acc) / denom;
        }
      }
      int rank = 0;
      for (int s = 0; s < NMAX; ++s) {
        if (!adm_any[s]) continue;
        const float x = (static_cast<float>(rank) + 0.5f) /
                        static_cast<float>(std::max(n_elig, 1));
        ++rank;
        // first cumulative-quota bucket >= x: searchsorted's left rule
        // parity: tiebreak searchsorted
        int d_prop = V1 - 1;
        for (int d = 0; d < V1; ++d)
          if (cumf[d] >= x) {
            d_prop = d;
            break;
          }
        int d_star;
        // proportional spread applies to self-selecting spread only
        // (mode == DMODE_SPREAD); gate/affinity modes keep the greedy
        // pick — identical to ops/packing.py's `prop_ok & (mode ==
        // DMODE_SPREAD)` gate
        if (mode == 1 &&
            percap_d[static_cast<size_t>(s) * V1 + d_prop] >= 1 &&
            qrem[d_prop] >= 1) {
          d_star = d_prop;
        } else {
          int32_t best_q = -1;
          d_star = DEAD;
          for (int d = 0; d < V1; ++d) {
            if (percap_d[static_cast<size_t>(s) * V1 + d] < 1) continue;
            if (qrem[d] < 1) continue;
            if (qrem[d] > best_q) {
              best_q = qrem[d];
              d_star = d;
            }
          }
        }
        c_slot[s] = d_star;
        claim_cap[s] =
            (d_star < V1) ? percap_d[static_cast<size_t>(s) * V1 + d_star] : 0;
        claim_cap[s] = std::min(claim_cap[s], hc);
        if (MV) claim_cap[s] = std::min(claim_cap[s], cap_mv[s]);
        if (has_h)
          claim_cap[s] = std::min(
              claim_cap[s], h_allow(ch_cnt[static_cast<size_t>(s) * JH + jh]));
      }
    }
    // hostname-affinity: restrict tier 2 to the least-loaded eligible open
    // claim (the oracle's in-flight order) — one entity only
    bool haff_claim_served = false;
    if (haff) {
      int tstar = -1;
      int32_t bestload = kBigDom;
      for (int s = 0; s < NMAX; ++s)
        if (c_slot[s] == ANY && claim_cap[s] >= 1 && c_npods[s] < bestload) {
          bestload = c_npods[s];
          tstar = s;
        }
      for (int s = 0; s < NMAX; ++s)
        if (s != tstar) claim_cap[s] = 0;
      haff_claim_served = tstar >= 0;
    }
    // per-slot water-fill with the slot's remaining quota as budget
    for (int sl = 0; sl < NSLOT; ++sl) {
      if (qrem[sl] <= 0) continue;
      bool any = false;
      for (int s = 0; s < NMAX; ++s) {
        bool in = (c_slot[s] == sl);
        wf_npods[s] = in ? c_npods[s] : kBigDom;
        wf_cap[s] = in ? claim_cap[s] : 0;
        any = any || (in && claim_cap[s] > 0);
      }
      if (!any) continue;
      waterfill(wf_npods, wf_cap, qrem[sl], wf_fill);
      for (int s = 0; s < NMAX; ++s)
        if (wf_fill[s] > 0) {
          claim_fill[s] = wf_fill[s];
          qrem[sl] -= wf_fill[s];
        }
    }
    if (haff && haff_claim_served) std::fill(qrem.begin(), qrem.end(), 0);
    for (int s = 0; s < NMAX; ++s) {
      if (claim_fill[s] <= 0) continue;
      got[s] = 1;
      c_npods[s] += claim_fill[s];
      if (hself) ch_cnt[static_cast<size_t>(s) * JH + jh] += claim_fill[s];
      for (int r = 0; r < R; ++r)
        c_used[static_cast<size_t>(s) * R + r] += claim_fill[s] * req[r];
      out_claim_fills[static_cast<size_t>(gi) * NMAX + s] = claim_fill[s];
    }
    // commit claim requirement/type-mask mutations for claims that got pods
    for (int s = 0; s < NMAX; ++s) {
      if (!got[s]) continue;
      uint8_t* sm = c_mask.data() + static_cast<size_t>(s) * KV;
      uint8_t* sd = c_def.data() + static_cast<size_t>(s) * K;
      uint8_t* sn = c_neg.data() + static_cast<size_t>(s) * K;
      int pp = c_pool[s];
      const bool tighten = dyn && c_slot[s] < V1;
      for (int k = 0; k < K; ++k) {
        sd[k] = sd[k] || gdef[k];
        sn[k] = sn[k] && gneg[k];
        for (int v = 0; v < V1; ++v) sm[k * V1 + v] = sm[k * V1 + v] && gmask[k * V1 + v];
      }
      if (tighten) {
        // pin the claim to the selected domain (the oracle tightens node
        // requirements to the chosen single domain, topology.go:220-242)
        for (int v = 0; v < V1; ++v)
          if (v != c_slot[s]) sm[kid_sel * V1 + v] = 0;
        if (dkey == 0)
          c_dzone[s] = c_slot[s];
        else
          c_dct[s] = c_slot[s];
      }
      for (int t = 0; t < T; ++t) {
        if (!c_tmask[static_cast<size_t>(s) * T + t]) continue;
        bool keep = type_ok_pgt[(static_cast<size_t>(pp) * G + gi) * T + t];
        if (keep) {
          // offering under the (now merged, possibly pinned) masks
          bool off = false;
          const uint8_t* az =
              a_for_claim(s) + static_cast<size_t>(t) * V1 * V1;
          for (int z = 0; z < V1 && !off; ++z) {
            if (!sm[zone_kid * V1 + z]) continue;
            for (int c = 0; c < V1; ++c)
              if (az[z * V1 + c] && sm[ct_kid * V1 + c]) {
                off = true;
                break;
              }
          }
          keep = off;
        }
        if (keep) {
          for (int r = 0; r < R; ++r)
            if (t_alloc[t * R + r] < c_used[static_cast<size_t>(s) * R + r]) {
              keep = false;
              break;
            }
        }
        c_tmask[static_cast<size_t>(s) * T + t] = keep;
      }
    }

    // parity: phase fresh-claims
    // ---- 3. new claims from highest-weight feasible template ----
    // Serve one domain slot per iteration (largest remaining quota — the
    // argmax pick, first-hit ties by lowest slot index); a no-progress
    // slot is retired so other domains still get served.
    // parity: tiebreak argmax
    std::vector<uint8_t> ddead(NSLOT, 0);
    ddead[DEAD] = 1;
    while (!overflow) {
      int d_sel = -1;
      int32_t best_q = 0;
      for (int sl = 0; sl < NSLOT; ++sl)
        if (!ddead[sl] && qrem[sl] > best_q) {
          best_q = qrem[sl];
          d_sel = sl;
        }
      if (d_sel < 0) break;
      const bool is_any = (d_sel == ANY);

      // template/type availability in the selected domain
      auto type_avail = [&](int p, int t) -> bool {
        if (!type_ok_pgt[(static_cast<size_t>(p) * G + gi) * T + t])
          return false;
        if (p_has_limit[p]) {
          for (int r = 0; r < R; ++r)
            if (t_cap[t * R + r] > pool_rem[static_cast<size_t>(p) * R + r])
              return false;
        }
        if (!is_any) {
          const uint8_t* pm = p_mask + static_cast<size_t>(p) * KV;
          if (!(pm[kid_sel * V1 + d_sel] && gmask[kid_sel * V1 + d_sel]))
            return false;
          for (int v = 0; v < V1; ++v)
            other_row[v] =
                pm[other_kid * V1 + v] && gmask[other_kid * V1 + v];
          if (!off_in_domain(a_step.data() + static_cast<size_t>(t) * V1 * V1,
                             dkey, d_sel, other_row.data(), V1))
            return false;
        }
        if (NRES) {
          // the static type_ok table saw the full catalog; re-gate on the
          // ledger-aware view under the template∪group zone/ct masks
          const uint8_t* pm = p_mask + static_cast<size_t>(p) * KV;
          const uint8_t* az = a_step.data() + static_cast<size_t>(t) * V1 * V1;
          bool any = false;
          for (int z = 0; z < V1 && !any; ++z) {
            if (!(pm[zone_kid * V1 + z] && gmask[zone_kid * V1 + z])) continue;
            for (int c = 0; c < V1; ++c)
              if (az[z * V1 + c] && pm[ct_kid * V1 + c] &&
                  gmask[ct_kid * V1 + c]) {
                any = true;
                break;
              }
          }
          if (!any) return false;
        }
        return true;
      };

      int p_star = -1;
      int32_t mv_cap_sel = kBigDom;
      std::vector<uint8_t> mv_av(MV ? T : 0);
      std::vector<int32_t> mv_ft(MV ? T : 0);
      for (int p = 0; p < P && p_star < 0; ++p) {
        bool anyt = false;
        for (int t = 0; t < T; ++t)
          if (type_avail(p, t)) {
            anyt = true;
            break;
          }
        if (!anyt) continue;
        if (MV) {
          // a template whose available set cannot satisfy its floors is
          // infeasible for this bulk (filter_instance_types' minValues
          // validation) — fall through to the next template in weight order
          for (int t = 0; t < T; ++t) {
            mv_av[t] = type_avail(p, t);
            mv_ft[t] =
                n_fit_pgt[(static_cast<size_t>(p) * G + gi) * T + t];
          }
          int32_t mc = minvalues_cap_one(
              mv_av.data(), mv_ft.data(),
              p_mvmin + static_cast<size_t>(p) * MV, t_mvoh, T, MV, MW);
          if (mc < 1) continue;
          mv_cap_sel = mc;
        }
        p_star = p;
      }
      if (p_star < 0) {
        ddead[d_sel] = 1;
        continue;
      }
      // one BULK of identical claims for this domain (frozen avail set),
      // matching the JAX body: k bounded by demand, the pool-limit ledger
      // (identical debit per claim) and the remaining slots
      std::vector<uint8_t> avail_t(T);
      int32_t n_per = 0;
      std::vector<float> debit(R, 0.0f);
      for (int t = 0; t < T; ++t) {
        avail_t[t] = type_avail(p_star, t);
        if (!avail_t[t]) continue;
        n_per = std::max(
            n_per, n_fit_pgt[(static_cast<size_t>(p_star) * G + gi) * T + t]);
        for (int r = 0; r < R; ++r)
          debit[r] = std::max(debit[r], t_cap[t * R + r]);
      }
      n_per = std::min(n_per, hc);
      if (MV) n_per = std::min(n_per, mv_cap_sel);
      // fresh claims have count 0: self owners cap at scap_h; gate owners
      // are unblocked (0 never exceeds the threshold)
      if (hself) n_per = std::min(n_per, scap_h);
      if (n_per <= 0) {
        ddead[d_sel] = 1;
        continue;
      }
      const int32_t rem_d = qrem[d_sel];
      // reservation clamp: every claim of the bulk reserves one slot per
      // compatible reservation (idempotent per hostname)
      bool any_resv = false;
      std::vector<uint8_t> r_compat(NRES ? NRES : 1, 0);
      int64_t k_resv = kBigFit;
      if (NRES) {
        const uint8_t* pm = p_mask + static_cast<size_t>(p_star) * KV;
        // domain-pinned bulks only count reservations usable in the pin
        const bool pin_z = !is_any && dkey == 0;
        const bool pin_c = !is_any && dkey == 1;
        for (int r = 0; r < NRES; ++r) {
          if (res_rem[r] <= 0) continue;
          bool compat = false;
          for (int t = 0; t < T && !compat; ++t) {
            if (!avail_t[t]) continue;
            const uint8_t* ar =
                a_res + (static_cast<size_t>(r) * T + t) * V1 * V1;
            for (int z = 0; z < V1 && !compat; ++z) {
              if (pin_z && z != d_sel) continue;
              if (!(pm[zone_kid * V1 + z] && gmask[zone_kid * V1 + z]))
                continue;
              for (int c = 0; c < V1; ++c) {
                if (pin_c && c != d_sel) continue;
                if (ar[z * V1 + c] && pm[ct_kid * V1 + c] &&
                    gmask[ct_kid * V1 + c]) {
                  compat = true;
                  break;
                }
              }
            }
          }
          if (compat) {
            r_compat[r] = 1;
            any_resv = true;
            k_resv = std::min<int64_t>(k_resv, res_rem[r]);
          }
        }
      }
      int64_t k_limit = kBigFit;
      if (p_has_limit[p_star]) {
        for (int r = 0; r < R; ++r)
          if (debit[r] > 0.0f)
            k_limit = std::min<int64_t>(
                k_limit,
                static_cast<int64_t>(std::floor(
                    pool_rem[static_cast<size_t>(p_star) * R + r] /
                    std::max(debit[r], 1e-9f))));
      }
      int64_t k_want = std::min<int64_t>(
          (rem_d + n_per - 1) / n_per, std::max<int64_t>(k_limit, 0));
      if (any_resv) k_want = std::min(k_want, k_resv);
      // hostname-affinity: ONE fresh claim hosts the bootstrap
      if (haff) k_want = std::min<int64_t>(k_want, 1);
      int64_t k_slots = NMAX - n_open;
      if (k_want > k_slots) overflow = true;
      int64_t k = std::min(k_want, k_slots);
      if (k <= 0) {
        ddead[d_sel] = 1;
        continue;
      }
      int32_t placed = 0;
      // bulk births mirror ops/packing.py tier-3: domain-pinned bulks —
      // and ALL bulks of a domain-constrained batch — split rem_d evenly
      // (base + 1-pod remainders); ANY bulks of domain-free batches keep
      // the concentrating full-then-partial fill
      const bool even_bulk = has_domains || !is_any;
      const int32_t served =
          static_cast<int32_t>(std::min<int64_t>(rem_d, k * n_per));
      const int32_t base_take = static_cast<int32_t>(served / k);
      const int32_t extra_take = static_cast<int32_t>(served - base_take * k);
      for (int64_t i = 0; i < k; ++i) {
        int32_t n_take =
            even_bulk
                ? base_take + (i < extra_take ? 1 : 0)
                : std::min<int32_t>(rem_d - static_cast<int32_t>(i) * n_per,
                                    n_per);
        int slot = n_open++;
        c_active[slot] = 1;
        c_pool[slot] = p_star;
        c_npods[slot] = n_take;
        for (int r = 0; r < R; ++r)
          c_used[static_cast<size_t>(slot) * R + r] =
              p_daemon[static_cast<size_t>(p_star) * R + r] + n_take * req[r];
        for (int t = 0; t < T; ++t)
          c_tmask[static_cast<size_t>(slot) * T + t] =
              avail_t[t] &&
              (n_fit_pgt[(static_cast<size_t>(p_star) * G + gi) * T + t] >=
               n_take);
        std::memcpy(c_def.data() + static_cast<size_t>(slot) * K, gdef, K);
        std::memcpy(c_neg.data() + static_cast<size_t>(slot) * K, gneg, K);
        std::memcpy(c_mask.data() + static_cast<size_t>(slot) * KV, gmask, KV);
        if (dyn && !is_any) {
          // claims opened for a dynamic group are domain-pinned from birth
          uint8_t* sm = c_mask.data() + static_cast<size_t>(slot) * KV;
          for (int v = 0; v < V1; ++v)
            if (v != d_sel) sm[kid_sel * V1 + v] = 0;
          if (dkey == 0)
            c_dzone[slot] = d_sel;
          else
            c_dct[slot] = d_sel;
        }
        out_claim_fills[static_cast<size_t>(gi) * NMAX + slot] = n_take;
        if (hself) ch_cnt[static_cast<size_t>(slot) * JH + jh] = n_take;
        c_resv[slot] = any_resv;
        placed += n_take;
      }
      if (any_resv) {
        for (int r = 0; r < NRES; ++r)
          if (r_compat[r]) res_rem[r] -= static_cast<int32_t>(k);
        refresh_a_step();
      }
      if (p_has_limit[p_star])
        for (int r = 0; r < R; ++r)
          pool_rem[static_cast<size_t>(p_star) * R + r] -=
              debit[r] * static_cast<float>(k);
      qrem[d_sel] -= placed;
      if (placed == 0) ddead[d_sel] = 1;
      // haff: a second trip would open a second entity — retire the slot
      if (haff) ddead[d_sel] = 1;
    }
    // parity: phase spread-counters
    // shared domain carry: a SELF owner's per-domain placements feed the
    // next sharing group's counts (gate modes never count themselves)
    if (has_d && mode <= 2)
      for (int d = 0; d < V1; ++d)
        ddc[static_cast<size_t>(jd) * V1 + d] += qd[d] - qrem[d];
    // contributor counting (the oracle's record() rule,
    // scheduling/topology.py:491-498): existing-node placements count by
    // the node's domain; claim placements count only when the claim's key
    // axis is pinned to a single value (hostname is always single per
    // claim, so ch_cnt takes every claim fill).
    {
      bool anyh = false, anyd = false;
      for (int j = 0; j < JH; ++j)
        anyh = anyh || g_hcontrib[static_cast<size_t>(gi) * JH + j];
      for (int j = 0; j < JD; ++j)
        anyd = anyd || g_dcontrib[static_cast<size_t>(gi) * JD + j];
      if (anyh) {
        for (int j = 0; j < JH; ++j) {
          if (!g_hcontrib[static_cast<size_t>(gi) * JH + j]) continue;
          for (int n = 0; n < N; ++n)
            nhc[static_cast<size_t>(n) * JH + j] +=
                out_exist_fills[static_cast<size_t>(gi) * N + n];
          for (int s = 0; s < NMAX; ++s)
            ch_cnt[static_cast<size_t>(s) * JH + j] +=
                out_claim_fills[static_cast<size_t>(gi) * NMAX + s];
        }
      }
      if (anyd) {
        std::vector<int32_t> cnt_z(V1, 0), cnt_c(V1, 0);
        for (int n = 0; n < N; ++n) {
          int32_t f = out_exist_fills[static_cast<size_t>(gi) * N + n];
          if (!f) continue;
          if (n_dzone[n] >= 0 && n_dzone[n] < V1) cnt_z[n_dzone[n]] += f;
          if (n_dct[n] >= 0 && n_dct[n] < V1) cnt_c[n_dct[n]] += f;
        }
        for (int s = 0; s < NMAX; ++s) {
          int32_t f = out_claim_fills[static_cast<size_t>(gi) * NMAX + s];
          if (!f) continue;
          const uint8_t* sm = c_mask.data() + static_cast<size_t>(s) * KV;
          int zn = 0, zlast = -1, cn = 0, clast = -1;
          for (int v = 0; v < V1; ++v) {
            if (sm[zone_kid * V1 + v]) { ++zn; zlast = v; }
            if (sm[ct_kid * V1 + v]) { ++cn; clast = v; }
          }
          if (zn == 1) cnt_z[zlast] += f;
          if (cn == 1) cnt_c[clast] += f;
        }
        for (int j = 0; j < JD; ++j) {
          if (!g_dcontrib[static_cast<size_t>(gi) * JD + j]) continue;
          const int32_t* src = (dtg_key[j] == 0) ? cnt_z.data() : cnt_c.data();
          for (int d = 0; d < V1; ++d)
            ddc[static_cast<size_t>(j) * V1 + d] += src[d];
        }
      }
    }
    // fill-based, matching the JAX kernel's count - sum(fills): quota
    // bookkeeping under-reports here — the haff path zeroes qrem after a
    // served pin precisely so the remainder errors instead of spilling
    int64_t placed_total = 0;
    for (int n = 0; n < N; ++n)
      placed_total += out_exist_fills[static_cast<size_t>(gi) * N + n];
    for (int s = 0; s < NMAX; ++s)
      placed_total += out_claim_fills[static_cast<size_t>(gi) * NMAX + s];
    out_unplaced[gi] = count - static_cast<int32_t>(placed_total);
  }

  std::memcpy(out_c_pool, c_pool.data(), sizeof(int32_t) * NMAX);
  std::memcpy(out_c_tmask, c_tmask.data(), sizeof(uint8_t) * NMAX * T);
  std::memcpy(out_c_dzone, c_dzone.data(), sizeof(int32_t) * NMAX);
  std::memcpy(out_c_dct, c_dct.data(), sizeof(int32_t) * NMAX);
  std::memcpy(out_c_resv, c_resv.data(), sizeof(uint8_t) * NMAX);
  out_n_open[0] = n_open;
  out_overflow[0] = overflow ? 1 : 0;
  return overflow ? 1 : 0;
}

}  // extern "C"
