"""Label vocabulary interning: Requirements -> fixed-width boolean masks.

The tensor solver needs every requirement as a dense mask over a closed
per-key value vocabulary. Complement sets (NotIn/Exists) are exact over a
closed universe plus one reserved OVERFLOW slot per key that witnesses "some
value outside the vocabulary": a complement set always admits unseen values,
a concrete set never does. Gt/Lt bounds are evaluated per vocabulary value at
encode time; the overflow slot under bounds is set iff the open integer band
contains a value not in the vocabulary.

Array shapes are bucketed to powers of two so XLA recompiles only when the
snapshot outgrows the previous bucket.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as labels_mod
from ..api.requirements import Operator, Requirement, Requirements


def _next_pow2(n: int, floor: int = 4) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


_VOCAB_SERIAL = itertools.count(1)


class Vocab:
    """Interned label keys and per-key value vocabularies."""

    def __init__(self):
        # distinguishes vocab INSTANCES in cache-validity tags (id() can
        # be reused after GC; this never is). itertools.count is atomic
        # under the GIL — concurrent sidecar solves construct Vocabs
        # without an instance-level lock in scope.
        self.serial = next(_VOCAB_SERIAL)
        self.key_ids: Dict[str, int] = {}
        self.keys: List[str] = []
        self.value_ids: List[Dict[str, int]] = []  # per key
        self.values: List[List[str]] = []

    def key_id(self, key: str) -> int:
        kid = self.key_ids.get(key)
        if kid is None:
            kid = len(self.keys)
            self.key_ids[key] = kid
            self.keys.append(key)
            self.value_ids.append({})
            self.values.append([])
        return kid

    def value_id(self, key: str, value: str) -> int:
        kid = self.key_id(key)
        vid = self.value_ids[kid].get(value)
        if vid is None:
            vid = len(self.values[kid])
            self.value_ids[kid][value] = vid
            self.values[kid].append(value)
        return vid

    def observe(self, reqs: Requirements) -> None:
        """Register keys AND values. Only constraint-side entities (pods,
        templates) register values; provider-side entities (instance types,
        node labels) use observe_keys + the overflow slot, keeping the value
        axis small (800 instance-type names would otherwise inflate V1 for
        every key)."""
        for r in reqs:
            self.key_id(r.key)
            # CONTENT-ordered interning: Requirement.values is a set, and
            # bare set iteration assigns value ids in PYTHONHASHSEED order
            # — two processes would intern the same zones/hostnames at
            # different ids, and every argmin/argmax tie-break over value
            # ids (domain picks, hostname slots) would diverge, moving
            # packing cost ~0.2% across processes (PARITY.md round 13).
            # Sorting pins the id order to the values themselves
            # (tests/test_solver_parity.py two-process determinism pin).
            for v in sorted(r.values):
                self.value_id(r.key, v)

    def observe_keys(self, reqs: Requirements) -> None:
        for r in reqs:
            self.key_id(r.key)

    def observe_label_keys(self, labels: Dict[str, str]) -> None:
        for k in labels:
            self.key_id(k)

    @property
    def n_keys(self) -> int:
        return len(self.keys)

    def padded_shape(self) -> Tuple[int, int]:
        """(K, V+1) with V bucketed; last slot is OVERFLOW."""
        max_vals = max((len(v) for v in self.values), default=0)
        return _next_pow2(self.n_keys), _next_pow2(max_vals + 1)

    def well_known_mask(self, K: int) -> np.ndarray:
        out = np.zeros(K, dtype=bool)
        for key, kid in self.key_ids.items():
            out[kid] = key in labels_mod.WELL_KNOWN_LABELS
        return out

    # -- encoding ---------------------------------------------------------

    def _band_has_unseen(self, kid: int, gt: Optional[int], lt: Optional[int]) -> bool:
        """Does the integer band (gt, lt) contain a value not in the vocab?"""
        lo = gt + 1 if gt is not None else None
        hi = lt - 1 if lt is not None else None
        if lo is None or hi is None:
            return True  # open-ended band is infinite
        if lo > hi:
            return False
        band = hi - lo + 1
        if band > 4096:
            return True  # cheaper than scanning; a wide band surely has unseen values
        seen = 0
        for v in self.values[kid]:
            try:
                iv = int(v)
            except ValueError:
                continue
            if lo <= iv <= hi:
                seen += 1
        return seen < band

    def encode_requirement(
        self, r: Requirement, mask_row: np.ndarray
    ) -> None:
        """Fill mask_row (V+1 bools, last=overflow) with r's allowed set.

        Concrete values absent from the vocabulary set the OVERFLOW slot:
        "admits some value outside the vocabulary". Sound as long as two
        unseen-value sets are never intersected with each other — guaranteed
        because all constraint-side (pod/template) values are registered and
        provider-side entities are only ever compared against
        constraint-side masks.
        """
        kid = self.key_ids[r.key]
        vals = self.values[kid]
        ids = self.value_ids[kid]
        gt, lt = r.greater_than, r.less_than
        if r.complement:
            for i, v in enumerate(vals):
                mask_row[i] = v not in r.values and _within(v, gt, lt)
            mask_row[-1] = self._band_has_unseen(kid, gt, lt) if (gt is not None or lt is not None) else True
        else:
            # idempotent mask bit-sets keyed by interned value id, so the
            # analysis: sanctioned[DET1101] order cannot reach the row bytes
            for v in r.values:
                # concrete sets have bounds stripped by intersection, but a
                # raw Gt-filtered In set may carry them
                if not _within(v, gt, lt):
                    continue
                vid = ids.get(v)
                if vid is None:
                    mask_row[-1] = True  # unseen concrete value
                else:
                    mask_row[vid] = True

    def encode(
        self, reqs: Requirements, K: int, V1: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Requirements -> (defined[K], neg[K], mask[K, V1]).

        Undefined keys get the all-true mask (Exists semantics) so kernels
        can intersect unconditionally; ``defined`` gates the custom-label
        rule, ``neg`` marks NotIn/DoesNotExist for the double-negation
        exemption (requirements.go:247-254).
        """
        defined = np.zeros(K, dtype=bool)
        neg = np.zeros(K, dtype=bool)
        mask = np.ones((K, V1), dtype=bool)
        for r in reqs:
            kid = self.key_ids[r.key]
            defined[kid] = True
            op = r.operator()
            neg[kid] = op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST)
            row = np.zeros(V1, dtype=bool)
            self.encode_requirement(r, row)
            mask[kid] = row
        return defined, neg, mask


def _within(value: str, gt: Optional[int], lt: Optional[int]) -> bool:
    if gt is None and lt is None:
        return True
    try:
        iv = int(value)
    except ValueError:
        return False
    if gt is not None and iv <= gt:
        return False
    if lt is not None and iv >= lt:
        return False
    return True
