"""TpuSolver: the batched solver behind the Scheduler seam.

Routes pods between the TPU fast path and the host oracle:

- *Tensorizable* pods (no pod-affinity/spread/host-port/minValues/Gt-Lt
  state — solver/encode.py:is_tensorizable) are grouped, encoded to dense
  arrays, and solved by the jitted feasibility + grouped-FFD kernels
  (ops/feasibility.py, ops/packing.py).
- Everything else falls through to the exact host oracle
  (scheduling/scheduler.py) in the same solve, sharing existing-node
  capacity with the TPU placements.

The oracle remains the semantic source of truth; parity tests assert the two
paths agree on node count and packing cost (tests/test_solver_parity.py).
"""

from __future__ import annotations


import logging
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..api import labels as labels_mod
from ..api import resources as res
from ..api.objects import NodePool, Pod
from ..api.requirements import Operator, Requirement, Requirements
from ..cloudprovider import types as cp
from ..faults.guard import (
    DecodeCommitError,
    SolverIntegrityError,
    check_solution,
)
from ..scheduling.inflight import RESERVED_OFFERING_MODE_STRICT
from ..scheduling.scheduler import Results, Scheduler
from ..scheduling.template import NodeClaimTemplate
from ..scheduling.topology import Topology
from ..utils.pretty import ChangeMonitor
from . import encode as enc
from .residency import DispatchQueue

_LOG = logging.getLogger("karpenter_tpu.solver")
# once per pod (24h TTL), not once per batch walk: long-pending pods are
# re-partitioned every provisioning round (pretty.ChangeMonitor — the
# reference gates its scheduling-relegation lines the same way,
# provisioner.go:80,187-199)
_ORACLE_ROUTE_CM = ChangeMonitor()


class EncodeCache:
    """Catalog-fingerprinted encode cache that outlives TpuSolver instances.

    The Provisioner (and the solver sidecar) build a fresh TpuSolver per
    solve, so the instance-type/template encode reuse (see encode.encode)
    only pays off if the vocab + static arrays survive across solvers. The
    fingerprint covers everything the static arrays are derived from; any
    catalog change (new types, price/availability flips, new limits)
    resets the cache."""

    def __init__(self, owner: str = ""):
        import threading

        # multi-tenant attribution (solver/tenancy.py): the control plane
        # this cache's warm state belongs to. Rides the ENCODE_DELTA fault
        # ctx so chaos plans can pin corrupt-delta rules to one tenant's
        # leases; "" for single-operator deployments.
        self.owner = owner
        self._fingerprint = None
        # short content hash of the current catalog fingerprint — the
        # encode_hash every decision audit record carries. Computed once
        # per catalog change (repr of the full fingerprint is megabytes on
        # an 800-type catalog; per-solve hashing would eat the <2% bench
        # budget), read per solve.
        self.content_hash = ""
        self.vocab = enc.Vocab()
        self.cache: dict = {}
        # incremental always-warm solving (ISSUE 8): the persistent
        # cluster encoding (content-keyed row banks + prior-snapshot fast
        # path) and the device-resident argument store both outlive
        # TpuSolver instances with this cache; a catalog change resets
        # them along with the vocab (lease() below)
        self.cluster = enc.ClusterEncoding(owner=owner)
        self.device_store = None  # solver/residency.py, built lazily
        # scenario-build warm path (ISSUE 10 satellite): consolidation
        # searches encode a DIFFERENT workload shape than provisioning
        # (union of candidates' pods + pending over the full node set), so
        # alternating provisioning and simulation solves through ONE
        # ClusterEncoding would thrash its prior-snapshot fast path. The
        # scenario paths get their own encoding + device store: repeated
        # searches within a reconcile pass (multi-node then single-node)
        # hit the content-hash REUSE outcome instead of re-paying the
        # ~130 ms cold encode per fresh environment.
        self.scenario_cluster = enc.ClusterEncoding(owner=owner)
        self.scenario_device_store = None
        # pure per-node scheduler model inputs (taints, daemon remainder,
        # label requirements) keyed by object resource versions — catalog-
        # independent, so it survives fingerprint resets. Consolidation
        # probes and successive provisioning rounds over a stable cluster
        # hit this instead of rebuilding every ExistingNode model.
        self.node_models: dict = {}
        # encode mutates the shared vocab/static arrays; concurrent solves
        # (the gRPC sidecar) serialize the host-side encode on this lock
        self.lock = threading.RLock()

    @staticmethod
    def _type_static_fp(it) -> tuple:
        """The immutable per-type fingerprint part (name, capacity,
        requirement content), memoized ON the InstanceType object: the
        provider hands the same objects back every reconcile (ICE masking
        builds fresh copies, which recompute), and repr(requirements)
        over an 800-type catalog was the dominant cost of every lease —
        a steady-state tax the warm encode path can't afford. Offering
        price/availability is NOT memoized: it changes per solve and is
        fingerprinted fresh below."""
        fp = getattr(it, "_ktpu_static_fp", None)
        if fp is None:
            fp = (
                it.name,
                tuple(sorted(it.capacity.items())),
                repr(it.requirements),
            )
            try:
                object.__setattr__(it, "_ktpu_static_fp", fp)
            except (AttributeError, TypeError):
                pass  # slotted/frozen types just recompute per lease
        return fp

    @staticmethod
    def _template_fp(nct) -> tuple:
        """Content tuple for one template. repr(requirements) omits
        min_values (Requirement.__repr__ prints key/operator/values only),
        and the dense minValues tables (p_mvmin/t_mvoh) live in the leased
        static cache — so the floors are fingerprinted explicitly or a
        NodePool minValues edit would serve stale floors until an
        unrelated catalog change."""
        return (
            nct.node_pool_name,
            nct.node_pool_weight,
            tuple(sorted(nct.labels.items())),
            tuple((t.key, t.value, t.effect) for t in nct.taints),
            repr(nct.requirements),
            tuple(
                sorted(
                    (r.key, r.min_values)
                    for r in nct.requirements
                    if r.min_values is not None
                )
            ),
        )

    @staticmethod
    def fingerprint(templates, its_by_pool, daemon_overhead, pool_limits):
        tpl = tuple(EncodeCache._template_fp(nct) for nct in templates)
        # content-addressed (NOT id()): the gRPC sidecar decodes a fresh
        # InstanceType object per request, and the cache must still hit on
        # an unchanged catalog
        types = tuple(
            (
                pool,
                tuple(
                    EncodeCache._type_static_fp(it)
                    + (tuple((o.price, o.available, o.reservation_capacity)
                             for o in it.offerings),)
                    for it in its
                ),
            )
            for pool, its in sorted(its_by_pool.items())
        )
        overhead = tuple(
            sorted(
                (nct.node_pool_name, tuple(sorted(rl.items())))
                for nct, rl in (daemon_overhead or {}).items()
            )
        )
        limits = tuple(
            sorted(
                (pool, tuple(sorted(rl.items())))
                for pool, rl in (pool_limits or {}).items()
            )
        )
        return (tpl, types, overhead, limits)

    def lease(self, templates, its_by_pool, daemon_overhead, pool_limits):
        """Vocab + cache dict for this catalog; resets on fingerprint change.

        An identity fast path skips the deep content fingerprint when the
        provider hands back the SAME InstanceType objects as last lease
        (the steady-state reconcile shape — kwok/fake return their cached
        list; availability changes arrive as fresh masked copies via the
        ICE cache, which breaks identity and recomputes). The per-object
        contract: a live catalog object's content is immutable — changed
        offerings come as new objects, never in-place flips. Strong refs
        to the keyed objects are held so a recycled id can never alias."""
        prekey = (
            tuple(EncodeCache._template_fp(nct) for nct in templates),
            tuple(
                (pool, tuple(map(id, its)))
                for pool, its in sorted(its_by_pool.items())
            ),
            tuple(
                sorted(
                    (nct.node_pool_name, tuple(sorted(rl.items())))
                    for nct, rl in (daemon_overhead or {}).items()
                )
            ),
            tuple(
                sorted(
                    (pool, tuple(sorted(rl.items())))
                    for pool, rl in (pool_limits or {}).items()
                )
            ),
        )
        if prekey == getattr(self, "_prekey", None):
            return self.vocab, self.cache
        fp = self.fingerprint(templates, its_by_pool, daemon_overhead, pool_limits)
        self._prekey = prekey
        # keep the id()-keyed objects alive: a GC'd type whose id is
        # recycled could otherwise satisfy the prekey with different content
        self._prekey_refs = [list(its) for its in its_by_pool.values()]
        if fp != self._fingerprint:
            import hashlib

            self._fingerprint = fp
            self.content_hash = hashlib.blake2b(
                repr(fp).encode(), digest_size=8
            ).hexdigest()
            self.vocab = enc.Vocab()
            self.cache = {}
            # the warm encoding and device buffers are catalog-derived:
            # a changed catalog invalidates both (next encode is full)
            self.cluster.invalidate("catalog changed")
            self.scenario_cluster.invalidate("catalog changed")
            if self.device_store is not None:
                self.device_store.reset()
            if self.scenario_device_store is not None:
                self.scenario_device_store.reset()
        return self.vocab, self.cache

    def lease_device_store(self, scenario: bool = False):
        """The device-resident argument store (created on first use so
        the native backend never imports residency/jax machinery).
        ``scenario`` selects the scenario-side store (paired with
        ``scenario_cluster``) so consolidation searches don't evict the
        provisioning path's buffers and vice versa."""
        from .residency import DeviceResidentArgs

        if scenario:
            if self.scenario_device_store is None:
                self.scenario_device_store = DeviceResidentArgs(
                    owner=self.owner
                )
            return self.scenario_device_store
        if self.device_store is None:
            self.device_store = DeviceResidentArgs(owner=self.owner)
        return self.device_store


@dataclass
class SolverConfig:
    max_claims: Optional[int] = None  # NMAX override; default auto-estimated
    force_oracle: bool = False  # route everything host-side (debugging)
    # "tpu": jitted JAX kernel (ops/solve.py). "native": the C++ host core
    # (native/solve_core.cc) — same contract, no accelerator needed.
    backend: str = "tpu"
    # multi-chip: a jax.sharding.Mesh (parallel.mesh.make_mesh) to shard
    # the solve over — ('scenario', 'data', 'model'): consolidation
    # scenarios lead, the segment live-pair axis data-shards, instance
    # types tensor-shard; group/node state stays replicated so the
    # sequential packing scan never pays per-step collectives — or "auto"
    # to build one over all local devices when more than one is present.
    # None = single device. Only meaningful with backend="tpu".
    mesh: Optional[object] = None
    # class-batched kernel (ops/packing.py:pack_classed): one scan step per
    # feasibility class instead of per group — the structural fix for
    # many-tiny-group batches (the reference's diverse mix fragments 5k
    # pods into ~1.9k groups sharing ~30 classes). None = auto-route when
    # the mean class size crosses _CLASSED_MIN_MEAN_SIZE; True/False force.
    classed: Optional[bool] = None
    # shared degradation ladder (faults/breaker.py:SolverHealth): gates the
    # batched/kernel rungs, absorbs dispatch failures and invariant-guard
    # quarantines into oracle fallbacks. None (the default, and every
    # direct-test construction) keeps the old contract: kernel errors
    # propagate to the caller.
    health: Optional[object] = None
    # per-call gRPC deadline for RemoteSolver dispatches (seconds)
    solve_deadline: float = 30.0
    # convex-relaxation bulk pre-solver (ops/relax.py): closed-form bulk
    # placement of separable plain runs in one batched dispatch, residual
    # on the exact kernel. None = auto (on for the plain single-device
    # jit path; KTPU_RELAX=0 disables); True/False force.
    relax: Optional[bool] = None
    # multi-tenant attribution (solver/tenancy.py): which control plane
    # this solve belongs to. Rides the decision audit records' attrs and
    # the sidecar's per-tenant spans; "" (single-operator) adds nothing.
    tenant: str = ""


def _clone_existing_node(en):
    """A fill-isolated copy of an ExistingNode model: decode mutates pods/
    requests/requirements, and scenario fan-out must not leak one scenario's
    placements into another's (or into the shared oracle models)."""
    import copy

    c = copy.copy(en)
    c.pods = list(en.pods)
    c.requests = dict(en.requests)
    c.requirements = Requirements(*en.requirements.values())
    c.volume_usage = en.volume_usage.copy() if en.volume_usage else None
    return c


@dataclass
class Scenario:
    """One cluster what-if for TpuSolver.solve_scenarios: ``pods`` is the
    scenario's workload (a subset of the union the solver encodes) and
    ``excluded_provider_ids`` names the existing nodes absent from the
    cluster in this scenario (consolidation candidates being removed)."""

    pods: List[Pod]
    excluded_provider_ids: frozenset = frozenset()


@dataclass
class DecodedClaim:
    """A claim produced by the TPU path; duck-types InFlightNodeClaim for
    Results consumers (pods, instance_type_options, requirements,
    template, reserved_offerings)."""

    template: NodeClaimTemplate
    pods: List[Pod]
    instance_type_options: List[cp.InstanceType]
    requirements: Requirements
    reserved_offerings: List[cp.Offering] = field(default_factory=list)

    def finalize(self) -> None:  # parity with InFlightNodeClaim
        pass


class TpuSolver:
    """Drop-in Solve() accelerator at the Scheduler seam."""

    def __init__(
        self,
        node_pools: Sequence[NodePool],
        instance_types: Dict[str, List[cp.InstanceType]],
        topology: Topology,
        state_nodes: Sequence = (),
        daemonset_pods: Sequence[Pod] = (),
        config: Optional[SolverConfig] = None,
        encode_cache: Optional[EncodeCache] = None,
        **scheduler_kwargs,
    ):
        self.config = config or SolverConfig()
        # encode reuse: with a shared EncodeCache the instance-type/template
        # side survives across TpuSolver instances (the Provisioner builds
        # one per solve); standalone, it still de-dups repeat solves on this
        # instance
        self._shared_cache = encode_cache or EncodeCache()
        # the oracle scheduler provides template prefiltering, daemon
        # overhead, existing-node models, and the fallback solve loop
        self.oracle = Scheduler(
            node_pools,
            instance_types,
            topology,
            state_nodes=state_nodes,
            daemonset_pods=daemonset_pods,
            node_model_cache=self._shared_cache.node_models,
            **scheduler_kwargs,
        )
        self.pool_limits = {
            np_.name: dict(np_.spec.limits) for np_ in node_pools if np_.spec.limits
        }
        # kernel dispatch count of the last solve_scenarios call (bench
        # telemetry: the whole probe set should cost <= 2 dispatches)
        self.last_scenario_dispatches = 0
        # per-solve audit state (obs/audit.py): which rung produced the
        # committed answer, what the invariant guard said, and any crash
        # that made the scenario batch decline
        self.last_dispatches = 0
        self._audit_rung = "kernel"
        self._audit_guard = "ok"
        self._audit_error = ""
        # incremental-encode telemetry of the last solve: whether the
        # prior snapshot / device buffers were reused, and how many rows
        # rode the delta (audit fields + provisioner metrics)
        self.last_encode_reused = False
        self.last_delta_rows = 0
        self._last_incremental = False
        # sequential-fallback telemetry (ISSUE 10): how often work fell off
        # the dense path for REPRESENTABILITY reasons — oracle-routed pods,
        # gated solve routes, scenario-batch declines. The reference
        # configs must drive this to zero (bench.py fallback_solves column;
        # scheduler_sequential_fallback_total in the provisioner).
        self.fallback_solves = 0
        self.last_fallback_reasons: List[str] = []
        # relaxation pre-solver telemetry of the last solve (bench
        # relax_routed_fraction / residual_pods columns): pods the bulk
        # pre-solver placed, claims it opened, and guard rejections that
        # shed the combined solve back to the full exact kernel
        self.last_relax_pods = 0
        self.last_relax_claims = 0
        self.last_relax_residual_pods = 0
        self.relax_rejects = 0
        # per-solve volume routing state (prepare_volume_routing)
        self._vol_resolved: Dict[str, list] = {}
        # two-slot async dispatch window: a submitted kernel computes
        # while the host encodes the next batch or decodes the previous
        # one (solver/residency.py)
        self._queue = DispatchQueue()

    def _note_fallback(self, reason: str) -> None:
        self.fallback_solves += 1
        self.last_fallback_reasons.append(reason)
        obs.event("solver.sequential_fallback", reason=reason)

    # -- solve ------------------------------------------------------------

    def solve(self, pods: Sequence[Pod]) -> Results:
        """One committed decision: the routed solve (below) inside a span,
        followed by the decision audit record. Neither instrument touches
        the decision itself (byte-identical-decisions contract,
        tests/test_obs.py)."""
        self.last_dispatches = 0
        self._audit_rung = "kernel"
        self._audit_guard = "ok"
        self.last_encode_reused = False
        self.last_delta_rows = 0
        self._last_incremental = False
        fault_mark = self._fault_log_mark()
        # one duration clock captured per solve: the tracer's injected
        # clock under tracing (replay-deterministic), the monotonic
        # PerfClock seam otherwise — never a raw wall-clock read in the
        # solve path (CLK10xx), and never RealClock for a DELTA (an NTP
        # step between the two reads would record a negative duration)
        dclk = obs.duration_clock()
        t0 = dclk.now()
        with obs.span("solve", pods=len(pods)) as sp:
            results = self._solve_routed(pods)
        self._emit_audit(
            "solve", sp, dclk, t0, fault_mark,
            pods=len(pods),
            claims=len(results.new_node_claims),
            errors=len(results.pod_errors),
            scenario_count=0,
            dispatches=self.last_dispatches,
            # cost enrichment only under tracing: total_price walks every
            # claim's options, and the untraced audit path must stay O(1)
            cost=(
                results.total_price() if obs.active() is not None else None
            ),
        )
        return results

    @staticmethod
    def _fault_log_mark() -> int:
        from .. import faults

        inj = faults.active()
        return len(inj.log) if inj is not None else 0

    def _drain_host(self, out):
        """The single blessed device->host readback of the queued dispatch
        path: every kernel's outputs (plain, classed, and scenario-batched)
        cross here, immediately ahead of the pre-decode invariant guard.
        PARITY.md's device-residency contract lists exactly this drain
        point plus the sharded-mesh readback — the queue refactor collapsed
        the former per-path readbacks into it."""
        import jax

        # analysis: sanctioned[DTX906] blessed decode boundary: the dispatch queue's single drain point (PARITY.md device-residency contract)
        return [np.asarray(x) for x in jax.device_get(out)]

    def _delta_fallback(self, reason: str) -> None:
        """Corrupt-delta half-step: invalidate the warm cluster encodings
        and the device-resident buffers so the retry re-encodes and
        re-transfers from scratch. Half a rung: the kernel breaker is NOT
        tripped — only the incremental state is shed."""
        self._shared_cache.cluster.invalidate(reason)
        self._shared_cache.scenario_cluster.invalidate(reason)
        for store in (
            self._shared_cache.device_store,
            self._shared_cache.scenario_device_store,
        ):
            if store is not None:
                store.reset()
        health = self.config.health
        if health is not None:
            health.delta_fallback(reason)  # counts + publishes the event
        else:
            obs.event("solver.delta_fallback", reason=reason[:200])

    def _emit_audit(self, kind, sp, dclk, t0, fault_mark, **fields) -> None:
        from .. import faults

        inj = faults.active()
        fired = (
            sorted({s for s, _, _ in inj.log[fault_mark:]})
            if inj is not None
            else []
        )
        # per-tenant attribution on the audit trail: a constant per
        # configured solver, so canonical replay identity is unmoved;
        # merged into any caller-supplied attrs (scenario error details)
        attrs = dict(fields.pop("attrs", None) or {})
        if self.config.tenant:
            attrs.setdefault("tenant", self.config.tenant)
        obs.AUDIT.record(
            kind=kind,
            trace_id=getattr(sp, "trace_id", ""),
            # same clock OBJECT as t0: an install/uninstall racing the
            # solve cannot mix timebases into one delta
            duration_ms=round((dclk.now() - t0) * 1000, 3),
            encode_hash=self._shared_cache.content_hash,
            rung=self._audit_rung,
            guard=self._audit_guard,
            fault_sites=fired,
            encode_reused=self.last_encode_reused,
            delta_rows=self.last_delta_rows,
            attrs=attrs,
            **fields,
        )

    def _solve_routed(self, pods: Sequence[Pod]) -> Results:
        if self.config.force_oracle:
            self._audit_rung = "oracle"
            return self.oracle.solve(pods)
        health = self.config.health
        if health is not None and not health.allow_kernel():
            # kernel rung is open (tripped breaker / quarantine cool-down):
            # the oracle rung is always available and exact
            self._audit_rung = "oracle"
            return self.oracle.solve(pods)
        if (
            self.oracle.reserved_capacity_enabled
            and self.oracle.reserved_offering_mode
            == RESERVED_OFFERING_MODE_STRICT
        ):
            # DOCUMENTED REMNANT GATE (ISSUE 10): strict reservation policy
            # raises mid-Add and blocks pool fallback (scheduler.py:244-258)
            # — inherently sequential; the kernel ledger covers the default
            # fallback mode. minValues pools, volumes, and topology all ride
            # the kernel now (dense distinct-value counting, attach-slot
            # ledger columns, domain counters) — this mode and pod-side
            # sequential state (host ports, preference relaxation, Gt/Lt,
            # pod-level minValues) are what remains of the old fallback.
            self._audit_rung = "oracle"
            self._note_fallback("strict-reservation-mode")
            return self.oracle.solve(pods)
        groups, rest = enc.partition_and_group(
            pods,
            topology=self.oracle.topology,
            # the merge's exactness argument needs state-independent
            # bootstrap inputs: a reservation ledger makes offering
            # availability evolve across scan steps
            merge_bootstrap_affinity=not self.oracle.reserved_capacity_enabled,
            volume_shapes=self.prepare_volume_routing(pods),
        )
        if rest:
            self._note_fallback(f"oracle-routed-pods:{len(rest)}")

        if rest and _LOG.isEnabledFor(logging.DEBUG):
            for p in rest:
                if _ORACLE_ROUTE_CM.has_changed(p.uid, "oracle-routed"):
                    _LOG.debug(
                        "pod %s routed to the host oracle (non-tensorizable"
                        " constraints)",
                        p.metadata.name,
                    )
        tpu_claims: List[DecodedClaim] = []
        tpu_errors: Dict[str, object] = {}
        if groups:
            try:
                try:
                    tpu_claims, tpu_errors = self._solve_fast(groups)
                except SolverIntegrityError as exc:
                    if not self._last_incremental:
                        raise
                    # degradation half-step: the violating solve ran on a
                    # delta-applied / reused encoding — before quarantining
                    # the kernel rung, drop the warm state (banks, prior
                    # snapshot, device buffers) and retry ONCE on a full
                    # re-encode. A corrupt delta never commits a stale
                    # snapshot (the guard rejected it pre-decode) and never
                    # costs the rung if the fresh encoding solves clean.
                    self._delta_fallback(str(exc))
                    tpu_claims, tpu_errors = self._solve_fast(groups)
            except SolverIntegrityError as exc:
                # the invariant guard runs on the RAW kernel outputs, before
                # any decode — nothing was committed, so the whole batch
                # re-solves host-side while the kernel rung sits quarantined
                self._audit_guard = f"quarantined: {exc}"
                self._audit_rung = "oracle"
                if health is None:
                    raise
                health.quarantine("kernel", str(exc))
                return self.oracle.solve(pods)
            except DecodeCommitError as exc:
                # decode crashed AFTER fills landed on the live node
                # models: an oracle re-solve HERE would double-count them,
                # so drop the whole batch — pods stay pending and the next
                # cycle re-solves on a fresh solver with clean models
                self._audit_guard = f"quarantined: {exc}"
                self._audit_rung = "dropped"
                if health is None:
                    raise
                health.quarantine("kernel", str(exc))
                return Results(
                    new_node_claims=[],
                    existing_nodes=[],
                    pod_errors={
                        p.uid: "solver decode aborted mid-commit; "
                        "batch re-queued" for p in pods
                    },
                )
            except Exception as exc:
                # dispatch/backend failure (XLA error, native load failure,
                # injected fault): count toward the breaker and degrade
                self._audit_rung = "oracle"
                if health is None:
                    raise
                health.record_kernel(
                    False, reason=f"{type(exc).__name__}: {exc}"
                )
                return self.oracle.solve(pods)
            if health is not None:
                health.record_kernel(True)
            # the oracle's ReservationManager must see the fast path's
            # holdings before it solves the remainder, or a mixed batch
            # double-books reservation capacity
            rm = self.oracle.reservation_manager
            for i, claim in enumerate(tpu_claims):
                for o in claim.reserved_offerings:
                    rm.reserve(f"tpu-claim-{i}", o)

        if not groups:
            # nothing rode the kernel: the oracle rung made this decision
            self._audit_rung = "oracle"
        results = self.oracle.solve(rest) if rest else Results(
            new_node_claims=[], existing_nodes=self.oracle.existing_nodes, pod_errors={}
        )
        results.new_node_claims = list(results.new_node_claims) + list(tpu_claims)
        results.pod_errors.update(tpu_errors)
        # kernel claims get the same post-solve truncation/minValues
        # validation the oracle's claims got (scheduler.go:249-267);
        # oracle claims are already truncated, so this is a no-op for them
        return results.truncate_instance_types()

    def prepare_volume_routing(
        self, pods: Sequence[Pod]
    ) -> Optional[Dict[str, tuple]]:
        """Per-solve volume resolution for the dense attach-slot ledger.

        Returns the ``volume_shapes`` map partition_and_group consumes:
        uid -> ((shape key), {synthetic resource: request}) for every pod
        whose volumes the kernel can ledger — resolvable, counted volumes
        that are FRESH (not attached to any node) and UNSHARED within the
        batch, so "one pod = len(volumes) new attach slots per driver" is
        exact. Everything else (missing PVC, RWX sharing, re-attachment of
        an existing volume, no resolver) routes host-side. Zonal
        constraints were already injected as node affinity upstream
        (VolumeTopology.inject), so only the attach accounting lives here.
        """
        resolver = getattr(self.oracle, "volume_resolver", None)
        if resolver is None:
            return None
        candidates = [p for p in pods if p.spec.volumes]
        if not candidates:
            return None
        self._vol_resolved = {}
        seen: Dict[tuple, int] = {}
        resolved_by_uid: Dict[str, list] = {}
        for p in candidates:
            resolved, err = resolver.resolve(p)
            if err is not None:
                continue
            resolved_by_uid[p.uid] = resolved
            for r in resolved:
                if r[0]:
                    seen[(r[0], r[1])] = seen.get((r[0], r[1]), 0) + 1
        # attached (driver, vid) pairs across the cluster, computed ONCE:
        # the admission loop below must stay O(volumes), not O(volumes x
        # nodes), on the hot provisioning path
        attached: set = set()
        for en in self.oracle.existing_nodes:
            if en.volume_usage is not None:
                attached.update(en.volume_usage.attached())
        out: Dict[str, tuple] = {}
        for p in candidates:
            resolved = resolved_by_uid.get(p.uid)
            if resolved is None:
                continue
            counted = [(r[0], r[1]) for r in resolved if r[0]]
            if any(seen[c] > 1 for c in counted):
                continue  # shared volume: distinct-id dedup breaks the ledger
            if any(c in attached for c in counted):
                continue  # already attached somewhere: per-node dedup differs
            per_driver: Dict[str, int] = {}
            for d, _vid in counted:
                per_driver[d] = per_driver.get(d, 0) + 1
            shape = tuple(sorted(per_driver.items()))
            reqs = {
                enc.VOL_RES_PREFIX + d: n * res.MILLI
                for d, n in per_driver.items()
            }
            out[p.uid] = (shape, reqs)
            self._vol_resolved[p.uid] = resolved
        return out or None

    # -- scenario axis ----------------------------------------------------

    # scenario-count buckets: pad S to a power of two so repeat searches
    # (and both dispatches of one search) reuse compiled programs
    _SCENARIO_FLOOR = 8

    def solve_scenarios(
        self, scenarios: Sequence[Scenario]
    ) -> Optional[List[Results]]:
        """Solve every scenario of one cluster snapshot in a single vmapped
        kernel dispatch (ops/solve.py:solve_all_scenarios_packed).

        The solver must have been constructed with the FULL node set (no
        candidates pre-removed); each scenario masks its removed nodes and
        activates its workload subset over one shared encoding — with
        topology priors batched as per-scenario contribution deltas
        (_plan_scenario_topology) and the reservation ledger replayed per
        scenario. Returns per-scenario Results aligned with ``scenarios``,
        or None when the batch cannot be represented scenario-batched (the
        documented remnants: oracle-routed pods, strict-mode reservations,
        topology shapes the prior deltas cannot express) — in which case
        the caller falls back to per-scenario solve()s and the decline is
        counted in ``fallback_solves``. ``last_scenario_dispatches``
        records the kernel dispatch count of the last successful call.

        Internally split into :meth:`submit_scenarios` (host-side prep +
        one async queued dispatch — never blocks on XLA) and
        :meth:`collect_scenarios` (drain, guard, decode, audit): the
        consolidation sweep submits chunk n+1 while chunk n's outputs are
        still on device (double-buffered prefetch, disruption/methods.py).
        """
        return self.collect_scenarios(self.submit_scenarios(scenarios))

    def submit_scenarios(self, scenarios: Sequence[Scenario]):
        """Stage one scenario batch and submit its kernel dispatch into
        the two-slot queue, without blocking on XLA. Returns an opaque
        token for collect_scenarios, or None when the batch cannot be
        represented (same decline conditions as solve_scenarios)."""
        self.last_scenario_dispatches = 0
        if not scenarios:
            return {"empty": True}
        if self.config.force_oracle or self.config.backend != "tpu":
            return None
        health = self.config.health
        if health is not None and not health.allow_batched():
            # batched rung is open: callers fall back to per-probe solves
            # (themselves ladder-gated) — rung 2 of the degradation ladder
            return None
        if (
            self.oracle.reserved_capacity_enabled
            and self.oracle.reserved_offering_mode
            == RESERVED_OFFERING_MODE_STRICT
        ):
            # documented remnant: strict mode raises mid-Add (see
            # _solve_routed) — the default fallback mode rides the batched
            # ledger with a fresh per-scenario replay in decode
            self._note_fallback("scenario-strict-reservation")
            return None
        # union workload across scenarios, deduped by pod identity
        union: List[Pod] = []
        seen: set = set()
        for sc in scenarios:
            for p in sc.pods:
                if p.uid not in seen:
                    seen.add(p.uid)
                    union.append(p)
        topo = self.oracle.topology
        if not self.oracle.templates:
            return None
        groups, rest = enc.partition_and_group(
            union,
            topology=topo,
            merge_bootstrap_affinity=not self.oracle.reserved_capacity_enabled,
        )
        if rest:
            self._note_fallback(f"scenario-oracle-routed:{len(rest)}")
            return None
        if not groups:
            return {"noop": True, "scenarios": list(scenarios)}
        # topology priors (domain counts, per-node selected-pod counts)
        # depend on which candidate nodes remain: bound pods of an INCLUDED
        # candidate count as priors, an excluded one's ride the workload.
        # The plan decomposes them into per-candidate contribution deltas
        # applied to per-scenario copies of (g_dprior, n_hcnt, nh_cnt0,
        # dd0) — the kernel math is untouched, the scenario vmap simply
        # maps four more inputs (ops/solve.py SCENARIO_TOPO_BATCHED_ARGS).
        # Shapes the deltas cannot express exactly decline to the
        # sequential reference (documented remnants: candidate pods owning
        # anti-affinity or selected by affinity-type / statically-folded
        # constraints, out-of-catalog candidate domains).
        topo_plan = None
        if topo.topology_groups or topo.inverse_topology_groups:
            topo_plan = self._plan_scenario_topology(scenarios, groups, topo)
            if topo_plan is None:
                self._note_fallback("scenario-topology-unrepresentable")
                return None

        # the duration clock starts at submit so a prefetched batch's
        # audit record reports wall time of the whole decision, overlap
        # included
        dclk = obs.duration_clock()
        t0 = dclk.now()
        fault_mark = self._fault_log_mark()
        with obs.span("solve.encode", groups=len(groups)):
            snap, avail, nmax_hint, lease_cache, delta = self._encode_batch(
                groups, scenario=True
            )
        a_tzc, res_cap0, a_res = avail
        fit = self._fit_matrix(snap)
        nmax = self._select_nmax(snap, fit, nmax_hint)
        # no G floor here, unlike _solve_fast: under vmap the empty-step
        # skip (lax.cond) lowers to select, so every padded step runs at
        # full cost for every scenario — pad only to the next power of two
        G = enc._next_pow2(len(snap.groups), floor=1)
        N = (
            enc._next_pow2(len(snap.existing_names), floor=1)
            if snap.existing_names
            else 0
        )
        statics = self._kernel_statics(snap, G)
        snap_run = snap.padded(G, N)
        args = list(snap_run.solve_args(a_tzc, res_cap0, a_res))

        # scenario-major mesh: consolidation's S scenarios are
        # embarrassingly parallel, so the configured mesh's devices
        # re-factorize onto the leading 'scenario' axis
        # (parallel/mesh.py:scenario_mesh) and the whole probe set still
        # costs <= 2 dispatches. Shared args pad/shard per ARG_SPECS; the
        # per-scenario stacks shard on 'scenario' (S is pow2-bucketed, so
        # the axis divides).
        mesh = self._resolve_mesh()
        smesh = None
        if mesh is not None:
            from ..parallel.mesh import pad_args_for_mesh, scenario_mesh

            smesh = scenario_mesh(mesh, enc._next_pow2(
                len(scenarios), floor=self._SCENARIO_FLOOR
            ))
            args = list(pad_args_for_mesh(tuple(args), smesh))

        # per-scenario arrays over the shared encoding
        uid_to_group: Dict[str, int] = {}
        for gi, g in enumerate(snap.groups):
            for p in g.pods:
                uid_to_group[p.uid] = gi
        pid_to_node: Dict[str, int] = {}
        for ni, en in enumerate(self.oracle.existing_nodes):
            pid = getattr(en.state_node, "provider_id", None)
            if pid is not None:
                pid_to_node[pid] = ni
        S_real = len(scenarios)
        S = enc._next_pow2(S_real, floor=self._SCENARIO_FLOOR)
        Gb, Nb = len(snap_run.g_count), snap_run.n_tol.shape[0]
        g_count_s = np.zeros((S, Gb), np.int32)
        n_tol_s = np.zeros((S, Nb, max(Gb, 1)), bool)
        scen_group_pods: List[List[List[Pod]]] = []
        for si, sc in enumerate(scenarios):
            per_group: List[List[Pod]] = [[] for _ in snap.groups]
            for p in sc.pods:
                per_group[uid_to_group[p.uid]].append(p)
            scen_group_pods.append(per_group)
            for gi, pl in enumerate(per_group):
                g_count_s[si, gi] = len(pl)
            ntol = snap_run.n_tol
            if sc.excluded_provider_ids:
                ntol = ntol.copy()
                for pid in sc.excluded_provider_ids:
                    ni = pid_to_node.get(pid)
                    if ni is not None:
                        # a node no group tolerates receives no fills: the
                        # kernel-visible form of "this node is gone"
                        ntol[ni, :] = False
            n_tol_s[si] = ntol
        idx_g_count = enc.SOLVE_ARG_NAMES.index("g_count")
        idx_n_tol = enc.SOLVE_ARG_NAMES.index("n_tol")

        import jax
        import jax.numpy as jnp

        fills_dtype = (
            jnp.int16 if self._fill_bound(snap, fit) < 2**15 else jnp.int32
        )
        # device residency over the SHARED encoding; the per-scenario
        # stacks (g_count, n_tol — plus the topology prior arrays when the
        # plan carries corrections) are rebuilt per call and ride the
        # dispatch as host arrays
        batch_topo = bool(topo_plan and topo_plan["batch"])
        skip = {"g_count", "n_tol"}
        if batch_topo:
            skip |= {"g_dprior", "n_hcnt", "nh_cnt0", "dd0"}
        store = self._shared_cache.lease_device_store(scenario=True)
        scen_shardings = None
        if smesh is not None:
            from ..parallel.mesh import arg_shardings

            scen_shardings = arg_shardings(smesh)
        with obs.span(
            "solve.transfer",
            reused=bool(delta.reused),
            delta_rows=int(delta.delta_rows),
        ):
            args = store.stage(
                enc.SOLVE_ARG_NAMES, args, delta, skip=frozenset(skip),
                shardings=scen_shardings, mesh_key=smesh,
            )
            if obs.active() is not None:
                jax.block_until_ready(
                    [a for a in args if not isinstance(a, np.ndarray)]
                )
        args[idx_g_count] = g_count_s
        args[idx_n_tol] = n_tol_s
        if batch_topo:
            gp_s, nh_s, nh0_s, dd0_s = self._scenario_topo_arrays(
                topo_plan, snap, snap_run, scenarios, S
            )
            args[enc.SOLVE_ARG_NAMES.index("g_dprior")] = gp_s
            args[enc.SOLVE_ARG_NAMES.index("n_hcnt")] = nh_s
            args[enc.SOLVE_ARG_NAMES.index("nh_cnt0")] = nh0_s
            args[enc.SOLVE_ARG_NAMES.index("dd0")] = dd0_s
        incremental = store.last_incremental or delta.reused

        token = {
            "batch_topo": batch_topo,
            "mesh": smesh,
            "scenarios": list(scenarios),
            "snap": snap,
            "snap_run": snap_run,
            "args": args,
            "statics": statics,
            "nmax": nmax,
            "fills_dtype": fills_dtype,
            "g_count_s": g_count_s,
            "scen_group_pods": scen_group_pods,
            "S_real": S_real,
            "lease_cache": lease_cache,
            "delta": delta,
            "incremental": incremental,
            "dclk": dclk,
            "t0": t0,
            "fault_mark": fault_mark,
            "retry_ok": True,
            "dispatches": 0,
        }
        try:
            token["slot"] = self._submit_scenario_dispatch(token)
        except Exception as exc:
            # submit-time crash (trace/compile error, injected fault):
            # nothing decoded, nothing committed — degrade like a dispatch
            # failure; collect_scenarios turns the token into the audited
            # decline
            if health is None:
                raise
            health.record_batched(
                False, reason=f"{type(exc).__name__}: {exc}"
            )
            token["error"] = f"{type(exc).__name__}: {exc}"
        return token

    def _submit_scenario_dispatch(self, token):
        from ..ops.solve import (
            dispatch_scenarios_mesh_packed,
            dispatch_scenarios_packed,
        )

        args = token["args"]
        nmax = token["nmax"]
        smesh = token.get("mesh")
        if smesh is not None:
            from ..parallel.mesh import sharded_scenarios_fn

            fn = sharded_scenarios_fn(
                smesh, token["fills_dtype"],
                token.get("batch_topo", False),
                nmax=nmax, **token["statics"],
            )
            return self._queue.submit(
                "scenarios-mesh",
                lambda: dispatch_scenarios_mesh_packed(fn, args, smesh),
            )
        return self._queue.submit(
            "scenarios",
            lambda: dispatch_scenarios_packed(
                *args, nmax=nmax, fills_dtype=token["fills_dtype"],
                batch_topo=token.get("batch_topo", False),
                **token["statics"],
            ),
        )

    def _plan_scenario_topology(self, scenarios, groups, topo):
        """Per-candidate topology-prior contribution plan for one scenario
        batch, or None when the deltas cannot express the sequential
        reference exactly (the caller declines to per-probe solves).

        A scenario's priors differ from the shared (union) encoding only
        by the bound pods of its INCLUDED candidates: the union topology
        treats every candidate's reschedulable pods as pending, so for a
        scenario keeping candidate c, c's pods must be re-counted as
        priors. Each such pod counts toward exactly the constraints whose
        selector matches it — its own group's self-selecting dynamic
        state, and the shared descriptors the group owns or contributes
        to — through four channels:

          nh   n_hcnt[row, gi]     private hostname cap priors
          nh0  nh_cnt0[row, slot]  shared hostname carry priors
          gpr  g_dprior[gi, vid]   private domain-spread priors
          dd0  dd0[slot, vid]      shared domain carry init

        Declines (documented remnants): candidate pods owning
        anti-affinity (the sequential path gates them through the oracle's
        inverse machinery), candidate pods selected by affinity-type or
        statically-folded constraints (their folds baked union counts
        in), hostname folds over several constraints, haff pins, and
        candidate nodes carrying out-of-catalog spread domains (their
        registration would differ per scenario)."""
        from ..scheduling.topology import TopologyType

        cand_pids: set = set()
        for sc in scenarios:
            cand_pids |= set(sc.excluded_provider_ids)
        if not cand_pids:
            return {"by_pid": {}, "cand_pids": cand_pids, "batch": False}
        row_by_name: Dict[str, tuple] = {}
        for ni, en in enumerate(self.oracle.existing_nodes):
            row_by_name[en.name] = (
                ni, getattr(en.state_node, "provider_id", None), en
            )
        # candidate-bound pods of the union, aggregated per (pid, row, gi)
        per: Dict[tuple, int] = {}
        for gi, g in enumerate(groups):
            for p in g.pods:
                nn = p.spec.node_name
                if not nn:
                    continue
                ent = row_by_name.get(nn)
                if ent is None or ent[1] not in cand_pids:
                    continue
                if p.spec.pod_anti_affinity:
                    return None
                per[(ent[1], ent[0], gi)] = per.get(
                    (ent[1], ent[0], gi), 0
                ) + 1
        # out-of-catalog candidate domains: removing the node would
        # unregister the domain in the sequential path, shifting the
        # spread min — checked on candidate NODES (registration is
        # node-based), pods or not
        dyn_keys = {
            g.topo.dkey
            for g in groups
            if g.topo is not None
            and g.topo.dmode
            in (enc.DMODE_SPREAD, enc.DMODE_GATE_SPREAD)
            and g.topo.dkey
        }
        if dyn_keys:
            cand_rows = {
                ni
                for ni, pid, _en in row_by_name.values()
                if pid in cand_pids
            }
            # the loop only ever returns None; which key trips it first is
            # analysis: sanctioned[DET1101] any-mismatch early-return
            for key in dyn_keys:
                catalog = topo.domain_groups.get(key)
                universe = catalog.domains() if catalog is not None else set()
                # analysis: sanctioned[DET1101] same any-mismatch shape
                for ni in cand_rows:
                    en = self.oracle.existing_nodes[ni]
                    dom = enc._node_single_value(en, key)
                    if dom is not None and dom not in universe:
                        return None
        if not per:
            return {"by_pid": {}, "cand_pids": cand_pids, "batch": False}
        static_folds = list(getattr(topo, "kernel_static_folds", ()))
        aff_tgs = [
            tg
            for tg in topo.topology_groups.values()
            if tg.type is TopologyType.POD_AFFINITY
        ]
        h_slots, d_slots = enc.shared_slot_ids(groups)
        by_pid: Dict[str, list] = {}
        sel_memo: Dict[tuple, bool] = {}
        for (pid, ni, gi), m in sorted(per.items()):
            g = groups[gi]
            rep = g.pods[0]
            en = self.oracle.existing_nodes[ni]
            for tg in static_folds + aff_tgs:
                memo_key = (gi, id(tg))
                hit = sel_memo.get(memo_key)
                if hit is None:
                    hit = sel_memo[memo_key] = tg.selects(rep)
                if hit:
                    return None
            t = g.topo
            if t is None:
                continue  # selected by nothing admitted: no counting
            if t.haff or t.dmode == enc.DMODE_AFFINITY:
                return None
            ch = by_pid.setdefault(pid, [])
            taints = en.cached_taints
            node_reqs = en.requirements
            if t.host_cap is not None:
                if len(t.src_h) != 1 or t.host_nsrc != 1:
                    return None
                if t.src_h[0].node_filter.matches(taints, node_reqs):
                    ch.append(("nh", ni, gi, m))
            desc = t.shared_h if t.h_self else None
            if desc is not None:
                if desc.tg is None:
                    return None
                if desc.tg.node_filter.matches(taints, node_reqs):
                    ch.append(("nh0", ni, h_slots[id(desc)], m))
            for desc in t.contrib_h:
                if desc.tg is None:
                    return None
                if desc.tg.node_filter.matches(taints, node_reqs):
                    ch.append(("nh0", ni, h_slots[id(desc)], m))
            dom_descs = []
            if t.dmode == enc.DMODE_SPREAD and t.shared_d is None:
                if t.src_d is None:
                    return None
                dom = enc._node_single_value(en, t.dkey)
                if (
                    dom is not None
                    and dom in t.dreg
                    and t.src_d.node_filter.matches(taints, node_reqs)
                ):
                    axis = 0 if t.dkey == labels_mod.TOPOLOGY_ZONE else 1
                    ch.append(("gpr", gi, axis, ni, m))
            if t.shared_d is not None and t.dmode == enc.DMODE_SPREAD:
                dom_descs.append(t.shared_d)
            for desc in t.contrib_d:
                if desc.mode != enc.DMODE_SPREAD:
                    return None  # affinity options evolve: sequential
                dom_descs.append(desc)
            for desc in dom_descs:
                if desc.tg is None:
                    return None
                dom = enc._node_single_value(en, desc.key)
                if (
                    dom is not None
                    and dom in desc.reg
                    and desc.tg.node_filter.matches(taints, node_reqs)
                ):
                    axis = 0 if desc.key == labels_mod.TOPOLOGY_ZONE else 1
                    ch.append(("dd0", d_slots[id(desc)], axis, ni, m))
        return {
            "by_pid": by_pid,
            "cand_pids": cand_pids,
            "batch": any(by_pid.values()),
        }

    def _scenario_topo_arrays(self, plan, snap, snap_run, scenarios, S):
        """Per-scenario copies of the topology prior arrays with each
        scenario's included-candidate contributions applied (see
        _plan_scenario_topology). Domain value ids come from the shared
        encoding's node rows — the correction's domain IS the candidate
        node's own zone/capacity-type slot."""
        g_dprior_s = np.repeat(snap_run.g_dprior[None], S, axis=0)
        n_hcnt_s = np.repeat(snap_run.n_hcnt[None], S, axis=0)
        nh0_s = np.repeat(snap_run.nh_cnt0[None], S, axis=0)
        dd0_s = np.repeat(snap_run.dd0[None], S, axis=0)
        by_pid = plan["by_pid"]
        cand_pids = plan["cand_pids"]
        for si, sc in enumerate(scenarios):
            for pid in sorted(cand_pids - set(sc.excluded_provider_ids)):
                for chan in by_pid.get(pid, ()):
                    kind = chan[0]
                    if kind == "nh":
                        _, ni, gi, m = chan
                        n_hcnt_s[si, ni, gi] += m
                    elif kind == "nh0":
                        _, ni, slot, m = chan
                        nh0_s[si, ni, slot] += m
                    elif kind == "gpr":
                        _, gi, axis, ni, m = chan
                        vid = (
                            snap.n_dzone[ni] if axis == 0 else snap.n_dct[ni]
                        )
                        if vid >= 0:
                            g_dprior_s[si, gi, vid] += m
                    else:  # dd0
                        _, slot, axis, ni, m = chan
                        vid = (
                            snap.n_dzone[ni] if axis == 0 else snap.n_dct[ni]
                        )
                        if vid >= 0:
                            dd0_s[si, slot, vid] += m
        return g_dprior_s, n_hcnt_s, nh0_s, dd0_s

    def collect_scenarios(self, token) -> Optional[List[Results]]:
        """Drain, guard, decode, and audit a batch submitted by
        submit_scenarios. Returns per-scenario Results aligned with the
        submitted scenarios, or None on decline/failure (same contract as
        solve_scenarios)."""
        if token is None:
            return None
        if token.get("empty"):
            return []
        if token.get("noop"):
            return [
                Results(
                    new_node_claims=[],
                    existing_nodes=self.oracle.existing_nodes,
                    pod_errors={},
                )
                for _ in token["scenarios"]
            ]
        self._audit_rung = "batched"
        self._audit_guard = "ok"
        self._audit_error = ""
        self.last_encode_reused = token["delta"].reused
        self.last_delta_rows = token["delta"].delta_rows
        self._last_incremental = token["incremental"]
        scenarios = token["scenarios"]
        with obs.span("scenarios", scenarios=len(scenarios)) as sp:
            if token.get("error"):
                self._audit_error = token["error"]
                results = None
            else:
                results = self._collect_scenarios_impl(token)
        if (
            results is not None
            or self._audit_guard != "ok"
            or self._audit_error
        ):
            # completed batched decisions, quarantined ones, AND crashed
            # dispatch/decode attempts — the audit trail must show WHY the
            # caller replayed per-probe in every failure shape;
            # representability declines solved nothing and stay silent
            obs_claims = sum(
                len(r.new_node_claims) for r in (results or [])
            )
            self._emit_audit(
                "scenarios", sp, token["dclk"], token["t0"],
                token["fault_mark"],
                pods=sum(len(s.pods) for s in scenarios),
                claims=obs_claims,
                errors=sum(len(r.pod_errors) for r in (results or [])),
                scenario_count=len(scenarios),
                dispatches=self.last_scenario_dispatches,
                cost=(
                    sum(r.total_price() for r in (results or []))
                    if obs.active() is not None
                    else None
                ),
                attrs=(
                    {"error": self._audit_error}
                    if self._audit_error
                    else {}
                ),
            )
        return results

    def _collect_scenarios_impl(self, token) -> Optional[List[Results]]:
        health = self.config.health
        snap, snap_run = token["snap"], token["snap_run"]
        g_count_s = token["g_count_s"]
        scen_group_pods = token["scen_group_pods"]
        S_real = token["S_real"]
        nmax = token["nmax"]
        slot = token["slot"]
        dispatches = token["dispatches"]
        try:
            while True:
                with obs.span("solve.dispatch", nmax=nmax, scenarios=S_real):
                    (c_pool, packed, n_open, overflow,
                     exist_fills, claim_fills, unplaced, c_dzone, c_dct,
                     c_resv) = self._drain_host(self._queue.drain(slot))
                dispatches += 1
                if not overflow.any():
                    break
                nmax *= 2
                token["nmax"] = nmax
                slot = self._submit_scenario_dispatch(token)
        except Exception as exc:
            # batched dispatch failed mid-search: nothing decoded, nothing
            # committed — record the rung failure and decline, so the
            # caller replays per-probe (the documented fallback contract);
            # the crash still lands in the audit trail (wrapper above)
            self._audit_error = f"{type(exc).__name__}: {exc}"
            if health is None:
                raise
            health.record_batched(
                False, reason=f"{type(exc).__name__}: {exc}"
            )
            return None
        self.last_scenario_dispatches = dispatches
        # invariant guard per scenario, still pre-decode: one corrupt
        # scenario poisons the whole batch (they share one dispatch)
        try:
            with obs.span("solve.guard", scenarios=S_real):
                for si in range(S_real):
                    self._verify_solution(
                        snap, snap_run, c_pool[si], packed[si],
                        int(n_open[si]),
                        exist_fills[si], claim_fills[si], unplaced[si], nmax,
                        g_count=g_count_s[si],
                        c_dzone=c_dzone[si], c_dct=c_dct[si],
                    )
        except SolverIntegrityError as exc:
            if token.get("retry_ok") and self._last_incremental:
                # degradation half-step (as in _solve_routed): the
                # violating batch ran on an incremental encoding — shed
                # the warm state and retry the whole batch ONCE on a full
                # re-encode before quarantining the rung
                self._delta_fallback(str(exc))
                retry = self.submit_scenarios(scenarios=token["scenarios"])
                if (
                    retry is not None
                    and not retry.get("error")
                    and retry.get("slot") is not None
                ):
                    retry["retry_ok"] = False
                    self._last_incremental = retry["incremental"]
                    # the audit provenance must describe the encode that
                    # actually produced the committed answer (the full
                    # re-encode), not the discarded incremental attempt
                    self.last_encode_reused = retry["delta"].reused
                    self.last_delta_rows = retry["delta"].delta_rows
                    return self._collect_scenarios_impl(retry)
            self._audit_guard = f"quarantined: {exc}"
            if health is None:
                raise
            health.quarantine("batched", str(exc))
            return None
        if health is not None:
            health.record_batched(True)
        if self.config.max_claims is None and S_real:
            lease_cache = token["lease_cache"]
            with self._shared_cache.lock:
                lease_cache["nmax_hint"] = max(
                    lease_cache.get("nmax_hint", 0),
                    int(n_open[:S_real].max()),
                )

        results: List[Results] = []
        try:
            with obs.span("solve.decode", scenarios=S_real):
                for si in range(S_real):
                    # fills commit onto per-scenario node clones so
                    # scenarios never observe each other's placements (only
                    # touched nodes clone; the rest share the untouched
                    # oracle models)
                    nodes = list(self.oracle.existing_nodes)
                    for ni in np.nonzero(exist_fills[si].any(axis=0))[0]:
                        if ni < len(nodes):
                            nodes[ni] = _clone_existing_node(nodes[ni])
                    claims, errors = self._decode(
                        snap,
                        c_pool[si].astype(np.int32),
                        packed[si],
                        int(n_open[si]),
                        exist_fills[si].astype(np.int32),
                        claim_fills[si].astype(np.int32),
                        unplaced[si],
                        c_dzone[si].astype(np.int32),
                        c_dct[si].astype(np.int32),
                        c_resv[si].astype(bool),
                        group_pods=scen_group_pods[si],
                        existing_nodes=nodes,
                    )
                    results.append(
                        Results(
                            new_node_claims=claims,
                            existing_nodes=nodes,
                            pod_errors=errors,
                        ).truncate_instance_types()
                    )
        except Exception as exc:
            # scenario decode commits onto clones, so a crash pollutes
            # nothing shared — decline the batch and let the caller replay
            # per-probe (which re-guards and re-decodes independently)
            self._audit_error = f"{type(exc).__name__}: {exc}"
            if health is None:
                raise
            health.record_batched(
                False, reason=f"{type(exc).__name__}: {exc}"
            )
            return None
        return results

    # -- fast path --------------------------------------------------------

    def _solve_fast(
        self, groups: List[enc.PodGroup]
    ) -> Tuple[List[DecodedClaim], Dict[str, object]]:
        templates = self.oracle.templates
        if not templates:
            return [], {
                p.uid: "no nodepool matched pod"
                for g in groups
                for p in g.pods
            }
        with obs.span("solve.encode", groups=len(groups)):
            snap, avail, nmax_hint, lease_cache, delta = self._encode_batch(
                groups
            )
        self.last_encode_reused = delta.reused
        self.last_delta_rows = delta.delta_rows
        obs.event(
            "encode.delta", reused=delta.reused, delta_rows=delta.delta_rows
        )
        a_tzc, res_cap0, a_res = avail
        fit = self._fit_matrix(snap)
        # adaptive sizing inside _select_nmax: the a-priori estimate sums
        # per-group worst cases and overshoots shared packing by 2-4x; once
        # a solve of this catalog has run, size off the observed claim
        # count's own pow2 bucket (floored at the hard pods-capacity
        # bound). Every [NMAX, T] op in the scan scales with this.
        # Undershoot is caught by the overflow-doubling retry below.
        nmax = self._select_nmax(snap, fit, nmax_hint)
        P = len(snap.templates)
        T = len(snap.instance_types)
        # bucketed axis sizes: the kernel runs on the padded snapshot, so
        # every shape-derived decision below must use these
        G = enc._next_pow2(len(snap.groups), floor=8)
        N = enc._next_pow2(len(snap.existing_names), floor=1) if snap.existing_names else 0
        statics = self._kernel_statics(snap, G)
        # bucket the G/N axes to powers of two: repeat solves of nearby
        # shapes (consolidation's binary-search probes, incremental
        # provisioning rounds) reuse one compiled program instead of paying
        # XLA compilation per solve. The native backend has no compilation
        # to amortize, so it runs the exact shapes.
        mesh = (
            self._resolve_mesh() if self.config.backend == "tpu" else None
        )
        if mesh is not None and not statics.get("sparse_groups"):
            # the dense/tiled kernel never reads the 'data'-sharded
            # segment index: re-factorize so the devices shard the type
            # tables instead of replicating the whole program
            from ..parallel.mesh import dense_mesh

            mesh = dense_mesh(mesh)
        if self.config.backend == "tpu":
            snap_run = snap.padded(G, N)
            args = snap_run.solve_args(a_tzc, res_cap0, a_res)
            if mesh is not None:
                # shard-divisible axes BEFORE staging: the resident
                # buffers must hold the mesh-padded shapes the sharded
                # program was compiled for (T to 'model', the segment
                # live-pair axis to 'data'; group/node arrays are
                # replicated in the r06 layout and stay untouched)
                from ..parallel.mesh import pad_args_for_mesh

                args = pad_args_for_mesh(args, mesh)
        else:
            snap_run = snap
            args = snap.solve_args(a_tzc, res_cap0, a_res)

        if self.config.backend == "tpu":
            # device residency: the encoded cluster tensors stay resident
            # on device between solves (buffers keyed by the encode delta's
            # class versions, solver/residency.py), so this stage transfers
            # only the changed rows — or nothing at all on the content-hash
            # fast path. jit accepts committed device buffers identically
            # to host arrays, so decisions don't change
            # (tests/test_delta_encode.py pins byte-identical results).
            # Under a mesh the same store stages each buffer against its
            # ARG_SPECS NamedSharding — REUSE/row-delta outcomes survive
            # partitioning (tests/test_parallel.py pins parity).
            import jax

            shardings = None
            if mesh is not None:
                from ..parallel.mesh import arg_shardings

                shardings = arg_shardings(mesh)
            store = self._shared_cache.lease_device_store()
            with obs.span(
                "solve.transfer",
                reused=bool(delta.reused),
                delta_rows=int(delta.delta_rows),
            ):
                args = store.stage(
                    enc.SOLVE_ARG_NAMES, list(args), delta,
                    shardings=shardings, mesh_key=mesh,
                )
                if obs.active() is not None:
                    # traced runs block so transfer time stays attributable
                    # apart from kernel time; untraced runs let the async
                    # dispatch overlap the transfer with host work
                    jax.block_until_ready(args)
            self._last_incremental = store.last_incremental or delta.reused

        relax_plan = None  # set on the plain single-device jit path only

        if self.config.backend == "native":
            from .. import native

            def call(nmax):
                return native.solve_core_native(*args, nmax=nmax, **statics)

        elif self.config.backend == "tpu" and mesh is not None:
            # multi-chip: shard the whole solve over the configured mesh
            # (SURVEY §5 — pjit across cores behind the Solver seam).
            # Inputs were mesh-padded and staged sharded above; the
            # wire-packed outputs come back replicated, ride the two-slot
            # queue, and cross at the single blessed drain exactly like
            # the single-device path — the former per-mesh-solve readback
            # site is gone (PARITY.md device-residency contract).
            # The relaxation pre-solver stays off under a mesh (it is a
            # host-side bulk placement around the plain jit path; its
            # separability planning is mesh-agnostic follow-up work).
            import jax.numpy as jnp

            from ..ops.solve import dispatch_mesh_packed
            from ..parallel.mesh import sharded_solve_packed_fn

            fills_dtype = (
                jnp.int16 if self._fill_bound(snap, fit) < 2**15 else jnp.int32
            )

            def call(nmax):
                fn = sharded_solve_packed_fn(
                    mesh, fills_dtype, nmax=nmax, **statics
                )
                slot = self._queue.submit(
                    "mesh", lambda: dispatch_mesh_packed(fn, args, mesh)
                )
                (c_pool, packed, n_open, overflow,
                 exist_fills, claim_fills, unplaced, c_dzone, c_dct,
                 c_resv) = self._drain_host(self._queue.drain(slot))
                return (
                    c_pool.astype(np.int32), packed, n_open, overflow,
                    exist_fills.astype(np.int32),
                    claim_fills.astype(np.int32), unplaced,
                    c_dzone.astype(np.int32), c_dct.astype(np.int32),
                    c_resv.astype(bool),
                )

        elif self.config.backend == "tpu":
            # imported lazily so backend="native" serves accelerator-less
            # (and jax-less) hosts
            import jax
            import jax.numpy as jnp

            from ..ops.solve import (
                dispatch_classed_packed,
                dispatch_packed,
            )

            # args ride WITH the dispatch (no separate device_put leg: the
            # tunnel charges fixed latency per RPC, and jit transfers host
            # arrays as part of the call); outputs travel bit-packed/narrowed
            # and are widened here
            n_types = snap.t_alloc.shape[0]
            # fill entries are capped at n_fit = capacity/request per claim
            # (packing.py), so this host-side bound proves int16 safety
            fills_dtype = (
                jnp.int16 if self._fill_bound(snap, fit) < 2**15 else jnp.int32
            )

            classed_args = self._classed_partition(snap_run, res_cap0)

            # relaxation bulk pre-solver (ops/relax.py): when the planner
            # proves part of the batch is separable easy mass, its counts
            # are zeroed for the exact dispatch and the bulk is placed by
            # the closed-form relaxed solve, merged before guard/decode.
            # Only the g_count ARG is overridden (scenario-style): the
            # device-resident buffers keep staging the true encode, so
            # warm REUSE/row-delta is untouched.
            use_relax = self.config.relax
            if use_relax is None:
                use_relax = os.environ.get("KTPU_RELAX") != "0"
            if use_relax:
                from ..ops import relax as relax_mod

                relax_plan = relax_mod.plan_bulk(
                    snap_run,
                    res_cap0=res_cap0,
                    n_exist=len(snap.existing_names),
                )
            else:
                relax_plan = None
            args = list(args)
            true_g_count = args[0]
            if relax_plan is not None:
                g_count_res = np.asarray(snap_run.g_count).copy()
                g_count_res[relax_plan.easy_gids] = 0
                args[0] = g_count_res

            def call(nmax):
                # the dispatch rides the two-slot queue: submit is async
                # (XLA computes while any remaining host work runs), and
                # the outputs cross back at the single blessed drain point
                if classed_args is not None:
                    cls_arrays, lmax = classed_args
                    slot = self._queue.submit(
                        "pack_classed",
                        lambda: dispatch_classed_packed(
                            *args, *cls_arrays, nmax=nmax, lmax=lmax,
                            fills_dtype=fills_dtype, **statics,
                        ),
                    )
                else:
                    slot = self._queue.submit(
                        "pack",
                        lambda: dispatch_packed(
                            *args, nmax=nmax, fills_dtype=fills_dtype,
                            **statics,
                        ),
                    )
                (c_pool, packed, n_open, overflow,
                 exist_fills, claim_fills, unplaced, c_dzone, c_dct,
                 c_resv) = self._drain_host(self._queue.drain(slot))
                # the type mask stays bit-packed: _decode unpacks only the
                # distinct rows it actually touches (n_open can be in the
                # thousands; a global unpack costs ~20 ms on the 50k shape)
                return (
                    c_pool.astype(np.int32), packed, n_open, overflow,
                    exist_fills.astype(np.int32),
                    claim_fills.astype(np.int32), unplaced,
                    c_dzone.astype(np.int32), c_dct.astype(np.int32),
                    c_resv.astype(bool),
                )

        else:
            raise ValueError(
                f"unknown solver backend {self.config.backend!r}"
                " (expected 'tpu' or 'native')"
            )

        def run_dispatch():
            nonlocal nmax
            while True:
                with obs.span("solve.dispatch", nmax=nmax):
                    outs = call(nmax)
                self.last_dispatches += 1
                if not outs[3]:  # overflow
                    return outs
                nmax *= 2

        outs = run_dispatch()
        self.last_relax_pods = 0
        self.last_relax_claims = 0
        self.last_relax_residual_pods = 0
        total_nmax = nmax
        if relax_plan is not None:
            from .. import faults
            from ..ops import relax as relax_mod

            try:
                with obs.span("solve.relax", pods=relax_plan.easy_pods):
                    bulk = relax_mod.solve_bulk(relax_plan, snap_run)
                # chaos seam: a corrupt bulk must trip the combined guard
                # below and shed to the full exact solve, never commit
                bulk = faults.mutate(faults.RELAX_OUTPUT, bulk)
                outs_c, total_nmax = self._merge_relax(
                    outs, relax_plan, bulk, nmax
                )
                # invariant guard over the COMBINED solve (exact residual
                # + relaxed bulk), against the TRUE group counts
                with obs.span("solve.guard"):
                    self._verify_solution(
                        snap, snap_run, outs_c[0], outs_c[1],
                        int(outs_c[2]), outs_c[4], outs_c[5], outs_c[6],
                        total_nmax, c_dzone=outs_c[7], c_dct=outs_c[8],
                    )
                outs = outs_c
                self.last_relax_pods = relax_plan.easy_pods
                self.last_relax_claims = int(bulk[0])
                self.last_relax_residual_pods = int(
                    np.asarray(true_g_count).sum()
                ) - relax_plan.easy_pods
                obs.event(
                    "solve.relax",
                    pods=relax_plan.easy_pods,
                    claims=int(bulk[0]),
                    runs=len(relax_plan.run_head),
                )
            except SolverIntegrityError:
                # rejected rounding: shed the whole batch to the full
                # exact solve (the documented guard interaction). The
                # exact re-solve runs against the true counts and the
                # normal guard below.
                self.relax_rejects += 1
                obs.event("solve.relax_rejected")
                args[0] = true_g_count
                relax_plan = None
                outs = run_dispatch()
                total_nmax = nmax
        (c_pool, c_tmask, n_open, overflow,
         exist_fills, claim_fills, unplaced, c_dzone, c_dct,
         c_resv) = outs
        # invariant guard BEFORE decode: a violating solve is discarded
        # with zero state mutated (faults/guard.py — conservation,
        # capacity, pool limits, domain-pin ranges), so the oracle
        # fallback is exact. (Relax-combined solves were already guarded
        # above — re-checking the merged arrays is a few host matmuls.)
        with obs.span("solve.guard"):
            self._verify_solution(
                snap, snap_run, c_pool, c_tmask, int(n_open),
                exist_fills, claim_fills, unplaced, total_nmax,
                c_dzone=c_dzone, c_dct=c_dct,
            )
        if self.config.max_claims is None:
            # the hint sizes the EXACT kernel's NMAX bucket, so bulk
            # claims the relaxation placed are excluded — a relax-reject
            # re-solve that needs the full count is covered by the
            # overflow-doubling retry
            with self._shared_cache.lock:
                lease_cache["nmax_hint"] = max(
                    lease_cache.get("nmax_hint", 0),
                    int(n_open) - self.last_relax_claims,
                )
        try:
            with obs.span("solve.decode", claims=int(n_open)):
                return self._decode(
                    snap, c_pool, c_tmask, int(n_open), exist_fills,
                    claim_fills, unplaced, c_dzone, c_dct, c_resv,
                )
        except Exception as exc:
            # decode mutates the live existing-node models as it walks
            # (driver._decode); a crash here may have HALF-committed —
            # flag it so solve() drops the batch instead of re-solving
            # over the polluted models (pods re-queue on a fresh solver)
            raise DecodeCommitError(
                f"decode aborted mid-commit: {type(exc).__name__}: {exc}"
            ) from exc

    @staticmethod
    def _merge_relax(outs, plan, bulk, nmax):
        """Append the relaxed bulk's claims after the exact residual's.

        Claim slot NUMBERING differs from a pure-exact interleaved solve
        (slots are anonymous — decode mints claim identities from slot
        order), but the decisions — which pods land on which claims of
        which template with which surviving type sets — are identical by
        the separability proof in ops/relax.py (pinned by
        tests/test_relax.py). Returns (combined outs, combined nmax)."""
        (c_pool, packed, n_open, overflow, exist_fills, claim_fills,
         unplaced, c_dzone, c_dct, c_resv) = outs
        n_open = int(n_open)
        n_r, r_pool, r_tmask, r_fills, r_unplaced = bulk
        c_pool = np.asarray(c_pool)
        packed = np.asarray(packed)
        claim_fills = np.asarray(claim_fills)
        G = claim_fills.shape[0]
        # bit-pack the bulk's type masks exactly like ops/solve._wire_pack
        # (MSB-first uint8 rows) so decode's lazy unpack sees one layout.
        # Relax only routes on the plain single-device jit path, whose
        # outputs are always uint8-packed (native/mesh set relax_plan to
        # None), so no raw-bool layout can reach this merge.
        assert packed.dtype == np.uint8, "relax merge requires packed masks"
        r_tmask = np.asarray(r_tmask)
        T = r_tmask.shape[1]
        pad = (-T) % 8
        r_packed = np.packbits(
            np.pad(r_tmask, ((0, 0), (0, pad))), axis=1
        )
        c_pool_c = np.concatenate(
            [c_pool[:n_open], np.asarray(r_pool).astype(c_pool.dtype)]
        )
        packed_c = np.concatenate([packed[:n_open], r_packed], axis=0)
        fills_r = np.zeros((G, int(n_r)), claim_fills.dtype)
        fills_r[plan.easy_gids] = np.asarray(r_fills)
        claim_fills_c = np.concatenate(
            [claim_fills[:, :n_open], fills_r], axis=1
        )
        unplaced_c = np.asarray(unplaced).copy()
        unplaced_c[plan.easy_gids] += np.asarray(r_unplaced)
        c_dzone_c = np.concatenate(
            [np.asarray(c_dzone)[:n_open],
             np.full((int(n_r),), -1, np.asarray(c_dzone).dtype)]
        )
        c_dct_c = np.concatenate(
            [np.asarray(c_dct)[:n_open],
             np.full((int(n_r),), -1, np.asarray(c_dct).dtype)]
        )
        c_resv_c = np.concatenate(
            [np.asarray(c_resv)[:n_open], np.zeros((int(n_r),), bool)]
        )
        return (
            (c_pool_c, packed_c, n_open + int(n_r), overflow, exist_fills,
             claim_fills_c, unplaced_c, c_dzone_c, c_dct_c, c_resv_c),
            nmax + int(n_r),
        )

    @staticmethod
    def _vocab_bound(snap, kid: int) -> int:
        """Valid value-id bound for a vocab key id (0 when absent)."""
        if 0 <= kid < len(snap.vocab.values):
            return len(snap.vocab.values[kid])
        return 0

    def _verify_solution(
        self, snap, snap_run, c_pool, c_tmask, n_open,
        exist_fills, claim_fills, unplaced, nmax, g_count=None,
        c_dzone=None, c_dct=None,
    ) -> None:
        """Raise SolverIntegrityError if the raw kernel outputs violate a
        post-solve invariant. Runs on every solve (a few small host
        matmuls); the caller quarantines the kernel rung on failure.
        ``g_count`` overrides the run snapshot's counts for scenario
        fan-out, where each scenario activates its own subset."""
        violations = check_solution(
            g_count=snap_run.g_count if g_count is None else g_count,
            g_req=snap_run.g_req,
            c_pool=c_pool,
            c_tmask=c_tmask,
            n_open=n_open,
            exist_fills=exist_fills,
            claim_fills=claim_fills,
            unplaced=unplaced,
            t_alloc=snap.t_alloc,
            n_avail=snap.n_avail,
            nmax=nmax,
            P=len(snap.templates),
            templates_pool=[
                nct.node_pool_name for nct in snap.templates
            ],
            p_limit=snap.p_limit,
            p_has_limit=snap.p_has_limit,
            c_dzone=c_dzone,
            c_dct=c_dct,
            zone_vals=self._vocab_bound(snap, snap.zone_kid),
            ct_vals=self._vocab_bound(snap, snap.ct_kid),
        )
        if violations:
            raise SolverIntegrityError(violations)

    def _encode_batch(self, groups: List[enc.PodGroup], scenario: bool = False):
        """Encode ``groups`` against the shared cache. Returns
        (snap, (a_tzc, res_cap0, a_res), nmax_hint, cache, delta) —
        ``cache`` is the LEASED dict this encode ran against; post-solve
        hint writes must target it (not a re-fetched
        self._shared_cache.cache, which a concurrent lease under a changed
        catalog may have replaced — a stale hint written into a fresh
        catalog's dict would mis-size that catalog's first NMAX).
        ``delta`` is the ClusterEncoding's EncodeDelta for this encode
        (what the device-residency staging transfers). ``scenario``
        selects the scenario-side ClusterEncoding so consolidation
        searches warm independently of the provisioning path."""
        templates = self.oracle.templates
        its_by_pool = {
            nct.node_pool_name: nct.instance_type_options for nct in templates
        }
        with self._shared_cache.lock:
            vocab, cache = self._shared_cache.lease(
                templates, its_by_pool, self.oracle.daemon_overhead,
                self.pool_limits,
            )
            cluster = (
                self._shared_cache.scenario_cluster
                if scenario
                else self._shared_cache.cluster
            )
            snap = enc.encode(
                groups,
                templates,
                its_by_pool,
                existing_nodes=self.oracle.existing_nodes,
                daemon_overhead=self.oracle.daemon_overhead,
                pool_limits=self.pool_limits,
                vocab=vocab,
                cache=cache,
                cluster=cluster,
            )
            delta = cluster.last_delta
            reserved_enabled = self.oracle.reserved_capacity_enabled
            avail_key = ("a_tzc", reserved_enabled) + snap.vocab.padded_shape()
            avail = cache.get(avail_key)
            if avail is None:
                avail = cache[avail_key] = self._offering_availability(
                    snap, reserved_enabled
                )
            nmax_hint = cache.get("nmax_hint")
        return snap, avail, nmax_hint, cache, delta

    def _select_nmax(self, snap: enc.EncodedSnapshot, fit, nmax_hint) -> int:
        """NMAX for this snapshot: config override, else the a-priori
        estimate, tightened by the observed-claim-count hint when one has
        been recorded for this catalog."""
        nmax = self.config.max_claims or self._estimate_nmax(snap, fit)
        if self.config.max_claims is None and nmax_hint:
            # size to the observed claim count's own power-of-two bucket
            # (+8 slack), not 1.5x it: the old headroom pushed any hint in
            # (0.66, 1.0] of a bucket into the NEXT one, doubling every
            # [NMAX] op in the scan (diverse-ref: 1000 claims ran at 2048).
            # Claim-count growth past the bucket is caught by the
            # overflow-doubling retry — one extra dispatch on the rare
            # solve that crosses a boundary, instead of 2x kernel cost on
            # every solve that doesn't.
            adaptive = max(
                enc._next_pow2(int(nmax_hint) + 8, floor=8),
                enc._next_pow2(self._nmax_floor(snap, fit), floor=8),
            )
            nmax = min(nmax, adaptive)
        return nmax

    def _kernel_statics(self, snap: enc.EncodedSnapshot, G: int) -> dict:
        """The static (compile-time) kernel arguments for this snapshot;
        ``G`` is the bucketed group-axis size the kernel will run at."""
        P = len(snap.templates)
        T = len(snap.instance_types)
        # HBM-scaling gate (SURVEY §7.4.6): beyond ~1.5 GiB of
        # feasibility tables, the scan computes per-group rows instead.
        # Computed ONCE: sparse_groups must stay its inverse (the tiled
        # mode passes zero-G placeholder tables the sparse index never
        # consults).
        tiled = P * G * T * 5 > (3 << 29)
        return dict(
            zone_kid=snap.zone_kid,
            ct_kid=snap.ct_kid,
            # static gate: topology-free batches trace out the per-domain
            # offering tensors and quota machinery entirely
            has_domains=bool((snap.g_dmode > 0).any()),
            # static gate: contributor counting (cross-group shared
            # constraints) traced out unless some group feeds a carry
            has_contrib=bool(snap.g_hcontrib.any() or snap.g_dcontrib.any()),
            tile_feasibility=tiled,
            # waterfill bisection budget: every trip is a serial reduction
            # on the scan-step critical path, so prove the tightest level
            # bound the snapshot allows (see _wf_iters)
            wf_iters=self._wf_iters(snap),
            # segment-contraction feasibility (ops/feasibility.py:*_sparse):
            # cost scales with the encoder's live (group, key) pairs instead
            # of the dense G x K join — always on outside the tiled mode,
            # which computes its own per-step rows (KTPU_SPARSE_FEAS=0
            # pins the dense twins for A/B verification)
            sparse_groups=(
                not tiled and os.environ.get("KTPU_SPARSE_FEAS") != "0"
            ),
        )

    # below this mean (real groups per feasibility class), per-class head
    # amortization cannot beat the per-group scan's simpler carry
    _CLASSED_MIN_MEAN_SIZE = 4.0

    def _classed_partition(self, snap_run, res_cap0):
        """Class arrays for the class-batched kernel, or None to use the
        per-group scan. Auto mode routes by mean class size: batches like
        the diverse mix (~63 groups/class) win big; batches where every
        group is its own class (constrained/mixed) stay on pack(). The
        reservation ledger evolves offering availability across members,
        so NRES > 0 always uses pack(). KTPU_CLASSED=1/0 overrides auto
        (the test suite uses it to force every scenario through the
        classed kernel for equivalence coverage)."""
        cfg = self.config.classed
        if cfg is None:
            env = os.environ.get("KTPU_CLASSED")
            if env is not None:
                cfg = env == "1"
        if cfg is False or res_cap0.shape[0] != 0:
            return None
        if (
            cfg is not True
            and snap_run.p_mvmin.shape[1]
            and bool((snap_run.g_dmode > 0).any())
        ):
            # minValues + domain-dynamic groups auto-route to pack(): the
            # classed kernel's maintained mv summary is exact under
            # same-request decrements but approximates across in-class
            # domain PINS, where pack() recomputes the cap from the
            # narrowed mask each step. Pin-free minValues batches keep the
            # classed amortization.
            return None
        out = enc.class_partition(
            snap_run,
            min_mean_size=0.0 if cfg is True else self._CLASSED_MIN_MEAN_SIZE,
        )
        if out is None:
            return None
        cs, cl, cdyn, cdk, inv, lmax = out
        if cfg is not True:
            n_classes = int((cl > 0).sum())
            if (
                n_classes == 0
                or len(snap_run.groups) / n_classes < self._CLASSED_MIN_MEAN_SIZE
            ):
                return None
        return (cs, cl, cdyn, cdk, inv), lmax

    def _resolve_mesh(self):
        """The mesh to shard the solve over, or None for single-device.
        "auto" builds a ('scenario', 'data', 'model') mesh over all local
        devices once more than one is present (single-device auto stays on
        the plain jit path — no GSPMD overhead for nothing)."""
        m = self.config.mesh
        if m is None:
            return None
        if m == "auto":
            cached = getattr(self, "_auto_mesh", None)
            if cached is not None:
                return cached
            import jax

            if len(jax.devices()) < 2:
                return None
            from ..parallel.mesh import make_mesh

            self._auto_mesh = make_mesh()
            return self._auto_mesh
        return m

    def _fit_matrix(self, snap: enc.EncodedSnapshot) -> np.ndarray:
        """[G, T] unconstrained pods-per-node fit (inf where a group has no
        positive request). Shared by the NMAX estimate and the fill bound."""
        alloc = snap.t_alloc[None, :, :] - np.min(snap.p_daemon, axis=0)[None, None, :]
        req = snap.g_req[:, None, :]
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(req > 0, np.floor(alloc / np.maximum(req, 1e-9)), np.inf)
        return np.min(per, axis=-1)

    def _fill_bound(self, snap: enc.EncodedSnapshot, fit: np.ndarray) -> int:
        """Largest pod count one claim/node can take from one group: per
        group, min(best type fit, group size); the max over groups bounds
        every fill entry, proving narrow output dtypes safe."""
        best = fit.max(axis=1)  # [G] best type fit (may be inf)
        if snap.n_avail.shape[0]:
            req = snap.g_req[:, None, :]
            with np.errstate(divide="ignore", invalid="ignore"):
                per_n = np.where(
                    req > 0,
                    np.floor(snap.n_avail[None, :, :] / np.maximum(req, 1e-9)),
                    np.inf,
                )
            best = np.maximum(best, np.min(per_n, axis=-1).max(axis=1))
        # the hostname-topology caps (private and shared) bound every fill;
        # gate-role g_hscap values are thresholds, not caps, so they only
        # bound self-counted groups
        shared_cap = np.where(snap.g_hself, snap.g_hscap, enc.HCAP_NONE)
        best = np.minimum(np.minimum(best, snap.g_hcap), shared_cap)
        capped = np.minimum(best, snap.g_count.astype(np.float64))
        return int(capped.max()) if capped.size else 0

    def _wf_iters(self, snap: enc.EncodedSnapshot) -> int:
        """Static bisection budget for the kernel's waterfills.

        Every water level the scan can ever probe is bounded by
        (slot prior) + (slot capacity): claim slots carry at most the
        pods-per-entity capacity (the "pods" resource column when tracked,
        else the batch total), domain slots at most the cluster prior plus
        one group's count. ceil(log2(bound)) + 1 trips pin the bisection;
        32 is the int32-safe fallback."""
        total = int(snap.g_count.sum())
        npods_bound = total
        if "pods" in snap.resource_names:
            col = snap.resource_names.index("pods")
            caps = []
            if snap.t_cap.size:
                caps.append(float(np.max(snap.t_cap[:, col])))
            if snap.n_avail.size:
                caps.append(float(np.max(snap.n_avail[:, col])))
            if caps:
                npods_bound = min(total, int(max(caps)))
        prior_bound = int(snap.g_dprior.max()) if snap.g_dprior.size else 0
        # shared-domain carries accumulate other groups' placements into D0
        # across steps, so the domain level can reach priors + batch total
        if (snap.g_dtg >= 0).any() or snap.g_dcontrib.any():
            prior_bound += total
        count_bound = int(snap.g_count.max()) if snap.g_count.size else 0
        level_bound = max(npods_bound, prior_bound) + count_bound + 2
        need = max(1, int(level_bound).bit_length() + 1)
        # bucket to {8, 16, 32}: wf_iters is a static jit arg, and a raw
        # bit_length would fork the compile cache on mere pod-count changes
        # across solves whose bucketed G/N shapes are otherwise identical
        for bucket in (8, 16, 32):
            if need <= bucket:
                return bucket
        return 32

    def _estimate_nmax(self, snap: enc.EncodedSnapshot, fit: np.ndarray) -> int:
        """Host-side claim-count bound: pods per node by the best
        unconstrained fit, clamped by the hostname-topology per-entity cap
        (a maxSkew=1 hostname spread means one claim per pod). Compatibility
        can only shrink the real fit, so this may undershoot; the overflow
        retry doubles NMAX in that case."""
        n_fit = np.where(np.isfinite(fit), fit, 0)
        shared_cap = np.where(snap.g_hself, snap.g_hscap, enc.HCAP_NONE)
        best = np.maximum(
            np.minimum(np.minimum(n_fit.max(axis=1), snap.g_hcap), shared_cap),
            1,
        )
        per_group = np.ceil(snap.g_count / best)
        # hostname-capped groups (spread/anti) SHARE claims: each claim
        # takes up to cap pods from EVERY such group, so their demand is
        # the max, not the sum (summing overestimated a 20-deployment
        # hostname-spread mix 30x, quadrupling kernel time). Resource
        # pressure that breaks sharing is caught by the overflow retry.
        # EXCEPT groups feeding one shared constraint slot: the cap counts
        # their placements jointly (a cross-shape anti-affinity Deployment
        # needs one claim per pod across ALL its shape groups), so demand
        # within a slot sums; distinct slots still share claims.
        capped, demand = self._capped_demand(snap, per_group)
        base = int(per_group[~capped].sum()) + demand
        # domain-constrained groups open claims per domain (zonal spread
        # water-fills across zones), so each may strand partial claims
        # beyond its ceil — at most one per extra domain, and never more
        # than its pod count affords (a 1-pod group strands none)
        dyn = snap.g_dmode > 0
        if len(snap.groups):
            dregs = snap.g_dreg.sum(axis=1)
            extra_per = np.clip(
                np.minimum(snap.g_count - per_group, dregs - 1), 0, None
            )
            extra = int(extra_per[dyn].sum())
        else:
            extra = 0
        # per-group partial-claim slack: only groups with >= 2 pods can
        # leave a partial claim beyond their ceil
        slack = int((snap.g_count >= 2).sum())
        return enc._next_pow2(base + slack + extra + 8, floor=8)

    def _capped_demand(self, snap: enc.EncodedSnapshot, per_group):
        """(capped mask, claim demand) of hostname-capped groups: private
        caps share claims (max); groups on one shared slot count jointly
        (sum within slot, max across)."""
        shared_cap = np.where(snap.g_hself, snap.g_hscap, enc.HCAP_NONE)
        priv_capped = (snap.g_hcap < enc.HCAP_NONE) & ~(
            snap.g_hself & (snap.g_hstg >= 0)
        )
        shared_self = (shared_cap < enc.HCAP_NONE) & (snap.g_hstg >= 0)
        capped = priv_capped | shared_self
        demands = []
        if priv_capped.any():
            demands.append(per_group[priv_capped].max())
        for slot in np.unique(snap.g_hstg[shared_self]):
            demands.append(
                per_group[shared_self & (snap.g_hstg == slot)].sum()
            )
        return capped, (int(max(demands)) if demands else 0)

    def _nmax_floor(self, snap: enc.EncodedSnapshot, fit: np.ndarray) -> int:
        """Hard lower bound on claims: total pods over the largest
        pods-per-claim capacity, plus the hostname-capped demand (an
        anti-affinity group needs a claim per pod regardless of capacity).
        Keeps the adaptive hint from starting a doubling ladder far below
        any feasible size."""
        total = int(snap.g_count.sum())
        cap = total
        if "pods" in snap.resource_names and snap.t_cap.size:
            col = snap.resource_names.index("pods")
            cap = max(1, int(np.max(snap.t_cap[:, col])))
        n_fit = np.where(np.isfinite(fit), fit, 0)
        shared_cap = np.where(snap.g_hself, snap.g_hscap, enc.HCAP_NONE)
        best = np.maximum(
            np.minimum(np.minimum(n_fit.max(axis=1), snap.g_hcap), shared_cap),
            1,
        )
        _, demand = self._capped_demand(snap, np.ceil(snap.g_count / best))
        return max(-(-total // max(cap, 1)), demand)

    def _offering_availability(
        self, snap: enc.EncodedSnapshot, reserved_enabled: bool = False
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(A[T, Vz, Vc], res_cap0[NRES], a_res[NRES, T, Vz, Vc]).

        A: type t has an available offering in (zone z, ct c). With the
        reservation ledger active, reserved offerings are EXCLUDED from A
        and contribute per-reservation availability in a_res instead; the
        kernel re-admits them while their ledger capacity lasts
        (reservationmanager.go:28-85)."""
        T, O = snap.o_avail.shape
        _, V1 = snap.vocab.padded_shape()
        A = np.zeros((T, V1, V1), dtype=bool)
        rids: Dict[str, int] = {}
        caps: List[int] = []
        res_cells: List[Tuple[int, int, int, int]] = []  # (rid, t, z, c)
        for t, it in enumerate(snap.instance_types):
            for o, off in enumerate(it.offerings):
                if (
                    reserved_enabled
                    and off.capacity_type() == labels_mod.CAPACITY_TYPE_RESERVED
                ):
                    # the ledger tracks the least capacity seen per id over
                    # ALL offerings, available or not (reservation.py:15-23)
                    rid = off.reservation_id()
                    r = rids.setdefault(rid, len(rids))
                    if r == len(caps):
                        caps.append(off.reservation_capacity)
                    else:
                        caps[r] = min(caps[r], off.reservation_capacity)
                    if not snap.o_avail[t, o]:
                        continue
                    z, c = snap.o_zone[t, o], snap.o_ct[t, o]
                    if z >= 0 and c >= 0:
                        res_cells.append((r, t, z, c))
                    continue
                if not snap.o_avail[t, o]:
                    continue
                z, c = snap.o_zone[t, o], snap.o_ct[t, o]
                if z >= 0 and c >= 0:
                    A[t, z, c] = True
                elif z >= 0:
                    A[t, z, :] = True
                elif c >= 0:
                    A[t, :, c] = True
                else:
                    A[t, :, :] = True
        nres = len(caps)
        a_res = np.zeros((nres, T, V1, V1), dtype=bool)
        for r, t, z, c in res_cells:
            a_res[r, t, z, c] = True
        return A, np.asarray(caps, dtype=np.int32), a_res

    # -- decode -----------------------------------------------------------

    def _decode(
        self,
        snap: enc.EncodedSnapshot,
        c_pool: np.ndarray,  # [NMAX]
        c_tmask: np.ndarray,  # [NMAX, T]
        n_open: int,
        exist_fills: np.ndarray,  # [G, N]
        claim_fills: np.ndarray,  # [G, NMAX]
        unplaced: np.ndarray,  # [G]
        c_dzone: Optional[np.ndarray] = None,  # [NMAX] pinned zone value ids
        c_dct: Optional[np.ndarray] = None,  # [NMAX] pinned capacity-type ids
        c_resv: Optional[np.ndarray] = None,  # [NMAX] claim holds reservations
        group_pods: Optional[List[List[Pod]]] = None,
        existing_nodes: Optional[List] = None,
    ) -> Tuple[List[DecodedClaim], Dict[str, object]]:
        """``group_pods``/``existing_nodes`` override the decode targets for
        scenario fan-out: scenario s places only its ACTIVE subset of each
        group's pods (group members are equivalent, so any k of them decode
        a fill of k), and commits fills onto per-scenario node clones so
        scenarios never see each other's placements."""
        self._cursors = {}
        existing = (
            existing_nodes if existing_nodes is not None
            else self.oracle.existing_nodes
        )

        def pods_of(gi: int) -> List[Pod]:
            return (
                group_pods[gi] if group_pods is not None
                else snap.groups[gi].pods
            )

        # existing-node fills: commit pods + requests onto the oracle's
        # ExistingNode models so a subsequent oracle pass sees them.
        # Iterate sparse nonzeros only; group-major order so pod cursors
        # advance deterministically per group.
        for gi, ni in zip(*np.nonzero(exist_fills)):
            g = snap.groups[gi]
            en = existing[ni]
            k = int(exist_fills[gi, ni])
            pods = pods_of(gi)[self._g_cursor(gi) : self._g_cursor(gi) + k]
            self._advance(gi, k)
            en.pods.extend(pods)
            en.requests = res.merge(en.requests, *(p.spec.requests for p in pods))
            en.requirements.add(*g.requirements.values())
            # attach-slot ledger commit: mirror ExistingNode.add's
            # volume_usage.add so a subsequent oracle pass (and the next
            # encode's remaining-slot columns) see the attachments
            if en.volume_usage is not None:
                for p in pods:
                    rv = self._vol_resolved.get(p.uid)
                    if rv:
                        en.volume_usage.add(p, rv)

        claims: List[DecodedClaim] = []
        claim_by_slot: Dict[int, DecodedClaim] = {}
        type_ids_cache: Dict[bytes, List[cp.InstanceType]] = {}
        resv_ledger: Optional[Dict[str, int]] = None
        T = len(snap.instance_types)
        packed = c_tmask.dtype == np.uint8 and c_tmask.shape[1] != T
        for slot in range(n_open):
            nct = snap.templates[int(c_pool[slot])]
            row = c_tmask[slot]
            tkey = row.tobytes()
            options = type_ids_cache.get(tkey)
            if options is None:
                if packed:
                    row = np.unpackbits(row)[:T]
                options = [
                    snap.instance_types[t] for t in np.nonzero(row)[0]
                ]
                type_ids_cache[tkey] = options
            claim = DecodedClaim(
                nct, [], options, Requirements(*nct.requirements.values())
            )
            # domain-pinned claims (zonal spread / affinity bootstrap) carry
            # the selected domain as a concrete requirement so the created
            # node lands there (the oracle tightens the in-flight claim the
            # same way, topology.go:220-242)
            for pins, key in (
                (c_dzone, labels_mod.TOPOLOGY_ZONE),
                (c_dct, labels_mod.CAPACITY_TYPE_LABEL_KEY),
            ):
                if pins is None or pins[slot] < 0:
                    continue
                kid = snap.vocab.key_ids[key]
                claim.requirements.add(
                    Requirement(
                        key, Operator.IN, [snap.vocab.values[kid][int(pins[slot])]]
                    )
                )
            if c_resv is not None and c_resv[slot]:
                # mirror the oracle's InFlightNodeClaim surface by replaying
                # the ledger in slot order (claims open in scan order, so
                # this reproduces the kernel's debits): a claim holds only
                # the compatible reserved offerings that still had capacity
                # when it opened
                if resv_ledger is None:
                    resv_ledger = {}
                    for it in snap.instance_types:
                        for o in it.offerings:
                            if (
                                o.capacity_type()
                                == labels_mod.CAPACITY_TYPE_RESERVED
                            ):
                                rid = o.reservation_id()
                                resv_ledger[rid] = min(
                                    resv_ledger.get(rid, o.reservation_capacity),
                                    o.reservation_capacity,
                                )
                held = []
                for it in options:
                    for o in it.offerings:
                        if (
                            o.available
                            and o.capacity_type()
                            == labels_mod.CAPACITY_TYPE_RESERVED
                            and resv_ledger.get(o.reservation_id(), 0) > 0
                            and claim.requirements.is_compatible(
                                o.requirements, labels_mod.WELL_KNOWN_LABELS
                            )
                        ):
                            held.append(o)
                # one slot per reservation ID per claim (a rid may back
                # offerings on several instance types), matching the
                # kernel's res_rem[r] -= k
                # analysis: sanctioned[DET1101] per-rid decrements commute
                for rid in {o.reservation_id() for o in held}:
                    resv_ledger[rid] -= 1
                claim.reserved_offerings = held
            claim_by_slot[slot] = claim
            claims.append(claim)
        for gi, slot in zip(*np.nonzero(claim_fills)):
            g = snap.groups[gi]
            claim = claim_by_slot.get(int(slot))
            if claim is None:
                continue
            k = int(claim_fills[gi, slot])
            claim.pods.extend(
                pods_of(gi)[self._g_cursor(gi) : self._g_cursor(gi) + k]
            )
            self._advance(gi, k)
            claim.requirements.add(*g.requirements.values())

        errors: Dict[str, object] = {}
        for gi, g in enumerate(snap.groups):
            n_err = int(unplaced[gi])
            if n_err:
                for p in pods_of(gi)[
                    self._g_cursor(gi) : self._g_cursor(gi) + n_err
                ]:
                    errors[p.uid] = "no feasible instance type/template for pod group"
        return claims, errors

    def _g_cursor(self, gi: int) -> int:
        return self._cursors.get(gi, 0)

    def _advance(self, gi: int, k: int) -> None:
        self._cursors[gi] = self._cursors.get(gi, 0) + k
