"""TpuSolver: the batched solver behind the Scheduler seam.

Routes pods between the TPU fast path and the host oracle:

- *Tensorizable* pods (no pod-affinity/spread/host-port/minValues/Gt-Lt
  state — solver/encode.py:is_tensorizable) are grouped, encoded to dense
  arrays, and solved by the jitted feasibility + grouped-FFD kernels
  (ops/feasibility.py, ops/packing.py).
- Everything else falls through to the exact host oracle
  (scheduling/scheduler.py) in the same solve, sharing existing-node
  capacity with the TPU placements.

The oracle remains the semantic source of truth; parity tests assert the two
paths agree on node count and packing cost (tests/test_solver_parity.py).
"""

from __future__ import annotations


from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as labels_mod
from ..api import resources as res
from ..api.objects import NodePool, Pod
from ..api.requirements import Requirements
from ..cloudprovider import types as cp
from ..scheduling.scheduler import Results, Scheduler
from ..scheduling.template import NodeClaimTemplate
from ..scheduling.topology import Topology
from . import encode as enc


@dataclass
class SolverConfig:
    max_claims: Optional[int] = None  # NMAX override; default auto-estimated
    force_oracle: bool = False  # route everything host-side (debugging)
    # "tpu": jitted JAX kernel (ops/solve.py). "native": the C++ host core
    # (native/solve_core.cc) — same contract, no accelerator needed.
    backend: str = "tpu"


@dataclass
class DecodedClaim:
    """A claim produced by the TPU path; duck-types InFlightNodeClaim for
    Results consumers (pods, instance_type_options, requirements,
    template)."""

    template: NodeClaimTemplate
    pods: List[Pod]
    instance_type_options: List[cp.InstanceType]
    requirements: Requirements

    def finalize(self) -> None:  # parity with InFlightNodeClaim
        pass


class TpuSolver:
    """Drop-in Solve() accelerator at the Scheduler seam."""

    def __init__(
        self,
        node_pools: Sequence[NodePool],
        instance_types: Dict[str, List[cp.InstanceType]],
        topology: Topology,
        state_nodes: Sequence = (),
        daemonset_pods: Sequence[Pod] = (),
        config: Optional[SolverConfig] = None,
        **scheduler_kwargs,
    ):
        self.config = config or SolverConfig()
        # the oracle scheduler provides template prefiltering, daemon
        # overhead, existing-node models, and the fallback solve loop
        self.oracle = Scheduler(
            node_pools,
            instance_types,
            topology,
            state_nodes=state_nodes,
            daemonset_pods=daemonset_pods,
            **scheduler_kwargs,
        )
        self.pool_limits = {
            np_.name: dict(np_.spec.limits) for np_ in node_pools if np_.spec.limits
        }

    # -- solve ------------------------------------------------------------

    def solve(self, pods: Sequence[Pod]) -> Results:
        if self.config.force_oracle:
            return self.oracle.solve(pods)
        fast: List[Pod] = []
        rest: List[Pod] = []
        for p in pods:
            (fast if enc.is_tensorizable(p) else rest).append(p)

        tpu_claims: List[DecodedClaim] = []
        tpu_errors: Dict[str, object] = {}
        if fast:
            tpu_claims, tpu_errors = self._solve_fast(fast)

        results = self.oracle.solve(rest) if rest else Results(
            new_node_claims=[], existing_nodes=self.oracle.existing_nodes, pod_errors={}
        )
        results.new_node_claims = list(results.new_node_claims) + list(tpu_claims)
        results.pod_errors.update(tpu_errors)
        return results

    # -- fast path --------------------------------------------------------

    def _solve_fast(self, pods: List[Pod]) -> Tuple[List[DecodedClaim], Dict[str, object]]:
        groups = enc.build_groups(pods)
        templates = self.oracle.templates
        if not templates:
            return [], {p.uid: "no nodepool matched pod" for p in pods}
        its_by_pool = {
            nct.node_pool_name: nct.instance_type_options for nct in templates
        }
        snap = enc.encode(
            groups,
            templates,
            its_by_pool,
            existing_nodes=self.oracle.existing_nodes,
            daemon_overhead=self.oracle.daemon_overhead,
            pool_limits=self.pool_limits,
        )
        a_tzc = self._offering_availability(snap)
        nmax = self.config.max_claims or self._estimate_nmax(snap)
        statics = dict(zone_kid=snap.zone_kid, ct_kid=snap.ct_kid)
        args = snap.solve_args(a_tzc)

        if self.config.backend == "native":
            from .. import native

            def call(nmax):
                return native.solve_core_native(*args, nmax=nmax, **statics)

        elif self.config.backend == "tpu":
            # imported lazily so backend="native" serves accelerator-less
            # (and jax-less) hosts
            import jax

            from ..ops.solve import solve_all

            # one transfer, one dispatch, one readback (tunnel round-trips
            # dominate small solves — see ops/solve.py)
            device_args = jax.device_put(args)

            def call(nmax):
                out = solve_all(*device_args, nmax=nmax, **statics)
                return [np.asarray(x) for x in jax.device_get(out)]

        else:
            raise ValueError(
                f"unknown solver backend {self.config.backend!r}"
                " (expected 'tpu' or 'native')"
            )

        while True:
            (c_pool, c_tmask, n_open, overflow,
             exist_fills, claim_fills, unplaced) = call(nmax)
            if not overflow:
                break
            nmax *= 2
        return self._decode(
            snap, c_pool, c_tmask, int(n_open), exist_fills, claim_fills, unplaced
        )

    def _estimate_nmax(self, snap: enc.EncodedSnapshot) -> int:
        """Host-side claim-count bound: pods per node by the best
        unconstrained fit. Compatibility can only shrink the real fit, so
        this may undershoot; the overflow retry doubles NMAX in that case."""
        alloc = snap.t_alloc[None, :, :] - np.min(snap.p_daemon, axis=0)[None, None, :]
        req = snap.g_req[:, None, :]
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(req > 0, np.floor(alloc / np.maximum(req, 1e-9)), np.inf)
        n_fit = np.min(per, axis=-1)  # [G, T]
        n_fit = np.where(np.isfinite(n_fit), n_fit, 0)
        best = np.maximum(n_fit.max(axis=1), 1)
        return enc._next_pow2(
            int(np.ceil(snap.g_count / best).sum()) + len(snap.groups) + 8, floor=8
        )

    def _offering_availability(self, snap: enc.EncodedSnapshot) -> np.ndarray:
        """A[T, Vz, Vc]: type t has an available offering in (zone z, ct c)."""
        T, O = snap.o_avail.shape
        _, V1 = snap.vocab.padded_shape()
        A = np.zeros((T, V1, V1), dtype=bool)
        for t in range(T):
            for o in range(O):
                if not snap.o_avail[t, o]:
                    continue
                z, c = snap.o_zone[t, o], snap.o_ct[t, o]
                if z >= 0 and c >= 0:
                    A[t, z, c] = True
                elif z >= 0:
                    A[t, z, :] = True
                elif c >= 0:
                    A[t, :, c] = True
                else:
                    A[t, :, :] = True
        return A

    # -- decode -----------------------------------------------------------

    def _decode(
        self,
        snap: enc.EncodedSnapshot,
        c_pool: np.ndarray,  # [NMAX]
        c_tmask: np.ndarray,  # [NMAX, T]
        n_open: int,
        exist_fills: np.ndarray,  # [G, N]
        claim_fills: np.ndarray,  # [G, NMAX]
        unplaced: np.ndarray,  # [G]
    ) -> Tuple[List[DecodedClaim], Dict[str, object]]:
        self._cursors = {}

        # existing-node fills: commit pods + requests onto the oracle's
        # ExistingNode models so a subsequent oracle pass sees them.
        # Iterate sparse nonzeros only; group-major order so pod cursors
        # advance deterministically per group.
        for gi, ni in zip(*np.nonzero(exist_fills)):
            g = snap.groups[gi]
            en = self.oracle.existing_nodes[ni]
            k = int(exist_fills[gi, ni])
            pods = g.pods[self._g_cursor(gi) : self._g_cursor(gi) + k]
            self._advance(gi, k)
            en.pods.extend(pods)
            en.requests = res.merge(en.requests, *(p.spec.requests for p in pods))
            en.requirements.add(*g.requirements.values())

        claims: List[DecodedClaim] = []
        claim_by_slot: Dict[int, DecodedClaim] = {}
        type_ids_cache: Dict[bytes, List[cp.InstanceType]] = {}
        for slot in range(n_open):
            nct = snap.templates[int(c_pool[slot])]
            tkey = c_tmask[slot].tobytes()
            options = type_ids_cache.get(tkey)
            if options is None:
                options = [
                    snap.instance_types[t] for t in np.nonzero(c_tmask[slot])[0]
                ]
                type_ids_cache[tkey] = options
            claim = DecodedClaim(
                nct, [], options, Requirements(*nct.requirements.values())
            )
            claim_by_slot[slot] = claim
            claims.append(claim)
        for gi, slot in zip(*np.nonzero(claim_fills)):
            g = snap.groups[gi]
            claim = claim_by_slot.get(int(slot))
            if claim is None:
                continue
            k = int(claim_fills[gi, slot])
            claim.pods.extend(g.pods[self._g_cursor(gi) : self._g_cursor(gi) + k])
            self._advance(gi, k)
            claim.requirements.add(*g.requirements.values())

        errors: Dict[str, object] = {}
        for gi, g in enumerate(snap.groups):
            n_err = int(unplaced[gi])
            if n_err:
                for p in g.pods[self._g_cursor(gi) : self._g_cursor(gi) + n_err]:
                    errors[p.uid] = "no feasible instance type/template for pod group"
        return claims, errors

    def _g_cursor(self, gi: int) -> int:
        return self._cursors.get(gi, 0)

    def _advance(self, gi: int, k: int) -> None:
        self._cursors[gi] = self._cursors.get(gi, 0) + k
