"""Wire codec for the solver service boundary.

The reference has no RPC seam — its scheduler is in-process — but the
TPU-native design places the batch solver in a sidecar reached over the
datacenter network (SURVEY.md §5 "Distributed communication backend"): the
controller ships a pod/instance-type snapshot, the sidecar returns packed
NodeClaims. This module is the snapshot codec: a tagged, msgpack-encoded
tree over the API dataclasses, plus explicit codecs for the slotted
Requirement/Requirements set-algebra types.

Objects are serialized structurally ("__t" type tags), so the format is
self-describing and language-neutral (any peer that can emit the same tags
can drive the solver).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional

import msgpack

from ..api import objects as obj
from ..api import resources as res
from ..api.requirements import Requirement, Requirements
from ..cloudprovider import types as cp

# Every dataclass that may appear in a snapshot. Reconstruction looks the
# class up by tag and calls it with decoded fields.
_CLASSES = {
    cls.__name__: cls
    for cls in (
        obj.ObjectMeta,
        obj.Taint,
        obj.Toleration,
        obj.NodeSelectorRequirement,
        obj.PreferredSchedulingTerm,
        obj.NodeAffinity,
        obj.LabelSelector,
        obj.LabelSelectorRequirement,
        obj.PodAffinityTerm,
        obj.WeightedPodAffinityTerm,
        obj.TopologySpreadConstraint,
        obj.HostPort,
        obj.PersistentVolumeClaimRef,
        obj.PodSpec,
        obj.PodCondition,
        obj.PodStatus,
        obj.Pod,
        obj.NodeClassRef,
        obj.NodeClaimSpec,
        obj.NodeClaimTemplate,
        obj.Budget,
        obj.Disruption,
        obj.NodePoolSpec,
        obj.NodePoolStatus,
        obj.NodePool,
        obj.DaemonSet,
        cp.Offering,
        cp.InstanceTypeOverhead,
        cp.InstanceType,
    )
}


def to_wire(value: Any) -> Any:
    """Recursively convert an API object tree to msgpack-able primitives."""
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    if isinstance(value, enum.Enum):
        return value.value
    if isinstance(value, Requirement):
        return {
            "__t": "Requirement",
            "key": value.key,
            "complement": value.complement,
            "values": sorted(value.values),
            "greater_than": value.greater_than,
            "less_than": value.less_than,
            "min_values": value.min_values,
        }
    if isinstance(value, Requirements):
        return {"__t": "Requirements", "items": [to_wire(r) for r in value]}
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out: Dict[str, Any] = {"__t": type(value).__name__}
        for f in dataclasses.fields(value):
            if f.name.startswith("_"):
                continue  # memoized/private fields are rebuilt on the far side
            out[f.name] = to_wire(getattr(value, f.name))
        return out
    if isinstance(value, dict):
        return {k: to_wire(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [to_wire(v) for v in value]
    raise TypeError(f"cannot serialize {type(value).__name__} for the wire")


def from_wire(value: Any) -> Any:
    """Inverse of to_wire."""
    if isinstance(value, dict):
        tag = value.get("__t")
        if tag == "Requirement":
            return Requirement._raw(
                key=value["key"],
                complement=value["complement"],
                values=set(value["values"]),
                greater_than=value["greater_than"],
                less_than=value["less_than"],
                min_values=value["min_values"],
            )
        if tag == "Requirements":
            return Requirements(*(from_wire(r) for r in value["items"]))
        if tag is not None:
            cls = _CLASSES.get(tag)
            if cls is None:
                raise TypeError(f"unknown wire tag {tag!r}")
            fields = {
                k: from_wire(v) for k, v in value.items() if k != "__t"
            }
            return cls(**fields)
        return {k: from_wire(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_wire(v) for v in value]
    return value


# -- snapshot / result envelopes -------------------------------------------


def encode_solve_request(
    pods,
    node_pools,
    instance_types: Dict[str, List[cp.InstanceType]],
    daemonset_pods=(),
    solver_options: Optional[Dict[str, Any]] = None,
) -> bytes:
    """solver_options carries behavior knobs (feature gates) that must match
    between controller and sidecar — e.g. reserved_capacity_enabled."""
    return msgpack.packb(
        {
            "pods": [to_wire(p) for p in pods],
            "node_pools": [to_wire(np_) for np_ in node_pools],
            "instance_types": {
                pool: [to_wire(it) for it in its]
                for pool, its in instance_types.items()
            },
            "daemonset_pods": [to_wire(p) for p in daemonset_pods],
            "solver_options": dict(solver_options or {}),
        },
        use_bin_type=True,
    )


def decode_solve_request(data: bytes) -> Dict[str, Any]:
    raw = msgpack.unpackb(data, raw=False)
    return {
        "pods": [from_wire(p) for p in raw["pods"]],
        "node_pools": [from_wire(np_) for np_ in raw["node_pools"]],
        "instance_types": {
            pool: [from_wire(it) for it in its]
            for pool, its in raw["instance_types"].items()
        },
        "daemonset_pods": [from_wire(p) for p in raw.get("daemonset_pods", [])],
        "solver_options": raw.get("solver_options", {}),
    }


def encode_solve_response(results) -> bytes:
    """Results → wire. Claims reference instance types by name and pods by
    uid; the caller reassembles against its own objects."""
    claims = []
    for claim in results.new_node_claims:
        claims.append(
            {
                "pool": claim.template.node_pool_name,
                "instance_types": [it.name for it in claim.instance_type_options],
                "pod_uids": [p.uid for p in claim.pods],
                "requirements": to_wire(claim.requirements),
            }
        )
    return msgpack.packb(
        {
            "claims": claims,
            "pod_errors": {uid: str(err) for uid, err in results.pod_errors.items()},
        },
        use_bin_type=True,
    )


def decode_solve_response(data: bytes) -> Dict[str, Any]:
    raw = msgpack.unpackb(data, raw=False)
    for claim in raw["claims"]:
        claim["requirements"] = from_wire(claim["requirements"])
    return raw


__all__ = [
    "to_wire",
    "from_wire",
    "encode_solve_request",
    "decode_solve_request",
    "encode_solve_response",
    "decode_solve_response",
]
