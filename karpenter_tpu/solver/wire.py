"""Wire codec for the solver service boundary.

The reference has no RPC seam — its scheduler is in-process — but the
TPU-native design places the batch solver in a sidecar reached over the
datacenter network (SURVEY.md §5 "Distributed communication backend"): the
controller ships a pod/instance-type snapshot, the sidecar returns packed
NodeClaims. This module is the snapshot codec: a tagged, msgpack-encoded
tree over the API dataclasses, plus explicit codecs for the slotted
Requirement/Requirements set-algebra types.

Objects are serialized structurally ("__t" type tags), so the format is
self-describing and language-neutral (any peer that can emit the same tags
can drive the solver).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional

import msgpack

from .. import obs
from ..api import objects as obj
from ..api import resources as res
from ..api.requirements import Requirement, Requirements
from ..cloudprovider import types as cp

# Every dataclass that may appear in a snapshot. Reconstruction looks the
# class up by tag and calls it with decoded fields.
_CLASSES = {
    cls.__name__: cls
    for cls in (
        obj.ObjectMeta,
        obj.Taint,
        obj.Toleration,
        obj.NodeSelectorRequirement,
        obj.PreferredSchedulingTerm,
        obj.NodeAffinity,
        obj.LabelSelector,
        obj.LabelSelectorRequirement,
        obj.PodAffinityTerm,
        obj.WeightedPodAffinityTerm,
        obj.TopologySpreadConstraint,
        obj.HostPort,
        obj.PersistentVolumeClaimRef,
        obj.PodSpec,
        obj.PodCondition,
        obj.PodStatus,
        obj.Pod,
        obj.NodeClassRef,
        obj.NodeClaimSpec,
        obj.NodeClaimTemplate,
        obj.Budget,
        obj.Disruption,
        obj.NodePoolSpec,
        obj.NodePoolStatus,
        obj.NodePool,
        obj.DaemonSet,
        obj.Node,
        obj.NodeStatus,
        obj.NodeClaim,
        obj.NodeClaimStatus,
        obj.Condition,
        obj.PersistentVolumeClaim,
        obj.PersistentVolume,
        obj.StorageClass,
        cp.Offering,
        cp.InstanceTypeOverhead,
        cp.InstanceType,
    )
}


def to_wire(value: Any) -> Any:
    """Recursively convert an API object tree to msgpack-able primitives."""
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    if isinstance(value, enum.Enum):
        return value.value
    if isinstance(value, Requirement):
        return {
            "__t": "Requirement",
            "key": value.key,
            "complement": value.complement,
            "values": sorted(value.values),
            "greater_than": value.greater_than,
            "less_than": value.less_than,
            "min_values": value.min_values,
        }
    if isinstance(value, Requirements):
        return {"__t": "Requirements", "items": [to_wire(r) for r in value]}
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out: Dict[str, Any] = {"__t": type(value).__name__}
        for f in dataclasses.fields(value):
            if f.name.startswith("_"):
                continue  # memoized/private fields are rebuilt on the far side
            out[f.name] = to_wire(getattr(value, f.name))
        return out
    if isinstance(value, dict):
        return {k: to_wire(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [to_wire(v) for v in value]
    raise TypeError(f"cannot serialize {type(value).__name__} for the wire")


def from_wire(value: Any) -> Any:
    """Inverse of to_wire."""
    if isinstance(value, dict):
        tag = value.get("__t")
        if tag == "Requirement":
            return Requirement._raw(
                key=value["key"],
                complement=value["complement"],
                values=set(value["values"]),
                greater_than=value["greater_than"],
                less_than=value["less_than"],
                min_values=value["min_values"],
            )
        if tag == "Requirements":
            return Requirements(*(from_wire(r) for r in value["items"]))
        if tag is not None:
            cls = _CLASSES.get(tag)
            if cls is None:
                raise TypeError(f"unknown wire tag {tag!r}")
            fields = {
                k: from_wire(v) for k, v in value.items() if k != "__t"
            }
            return cls(**fields)
        return {k: from_wire(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_wire(v) for v in value]
    return value


# -- state-node snapshots ---------------------------------------------------


def encode_state_node(sn) -> Dict[str, Any]:
    """StateNode → wire: the merged Node/NodeClaim objects, the node's bound
    pods (with which of them are daemons), and the usage surfaces the
    scheduler's ExistingNode model reads. The sidecar reconstructs a
    StateNode that answers labels()/taints()/available()/hostport_usage
    identically, so existing-capacity packing matches the controller's
    in-process solve (scheduler.go:357-425 packs existing nodes FIRST)."""
    return {
        "node": to_wire(sn.node),
        "node_claim": to_wire(sn.node_claim),
        "pods": [to_wire(p) for p in sn.pods],
        "daemon_uids": sorted(sn.daemonset_requests),
        "volume_limits": dict(sn.volume_limits),
        "volume_usage": sn.volume_usage.snapshot(),
        "mark_for_deletion": bool(sn.mark_for_deletion),
        "nominated_until": float(sn.nominated_until),
    }


def decode_state_node(raw: Dict[str, Any]):
    from ..controllers.state import StateNode

    sn = StateNode(
        node=from_wire(raw["node"]), node_claim=from_wire(raw["node_claim"])
    )
    from ..scheduling.volumeusage import VolumeUsage

    sn.volume_limits = dict(raw.get("volume_limits") or {})
    sn.volume_usage = VolumeUsage.from_snapshot(raw.get("volume_usage"))
    sn.mark_for_deletion = raw.get("mark_for_deletion", False)
    sn.nominated_until = raw.get("nominated_until", 0.0)
    daemons = set(raw.get("daemon_uids", ()))
    for p in (from_wire(x) for x in raw.get("pods", [])):
        sn.update_pod(p, is_daemon=p.uid in daemons)
    return sn


# -- snapshot / result envelopes -------------------------------------------


def encode_solve_request(
    pods,
    node_pools,
    instance_types: Dict[str, List[cp.InstanceType]],
    daemonset_pods=(),
    solver_options: Optional[Dict[str, Any]] = None,
    state_nodes=(),
    volume_objects=(),
) -> bytes:
    """solver_options carries behavior knobs (feature gates) that must match
    between controller and sidecar — e.g. reserved_capacity_enabled.
    ``volume_objects`` are the PVC/PV/StorageClass objects pending pods
    reference, so the sidecar's VolumeResolver answers identically to the
    controller's (volumeusage.go resolveDriver/VolumeName)."""
    with obs.span("wire.encode_request", pods=len(pods)):
        return msgpack.packb(
            {
                "pods": [to_wire(p) for p in pods],
                "node_pools": [to_wire(np_) for np_ in node_pools],
                "instance_types": {
                    pool: [to_wire(it) for it in its]
                    for pool, its in instance_types.items()
                },
                "daemonset_pods": [to_wire(p) for p in daemonset_pods],
                "solver_options": dict(solver_options or {}),
                "state_nodes": [encode_state_node(sn) for sn in state_nodes],
                "volume_objects": [to_wire(o) for o in volume_objects],
            },
            use_bin_type=True,
        )


def decode_solve_request(data: bytes) -> Dict[str, Any]:
    with obs.span("wire.decode_request", bytes=len(data)):
        return _decode_solve_request(data)


def _decode_solve_request(data: bytes) -> Dict[str, Any]:
    raw = msgpack.unpackb(data, raw=False)
    return {
        "pods": [from_wire(p) for p in raw["pods"]],
        "node_pools": [from_wire(np_) for np_ in raw["node_pools"]],
        "instance_types": {
            pool: [from_wire(it) for it in its]
            for pool, its in raw["instance_types"].items()
        },
        "daemonset_pods": [from_wire(p) for p in raw.get("daemonset_pods", [])],
        "solver_options": raw.get("solver_options", {}),
        "state_nodes": [
            decode_state_node(sn) for sn in raw.get("state_nodes", [])
        ],
        # None (vs []) marks a client that predates the volume protocol;
        # the sidecar then skips PVC resolution rather than failing every
        # PVC-bearing pod with "not found" against its empty scratch store
        "volume_objects": (
            [from_wire(o) for o in raw["volume_objects"]]
            if "volume_objects" in raw
            else None
        ),
    }


def encode_solve_response(results, state_nodes_packed: int = 0) -> bytes:
    """Results → wire. Claims reference instance types by name and pods by
    uid; the caller reassembles against its own objects. Existing-node
    placements travel as (node name, newly placed pod uids);
    ``state_nodes_packed`` acknowledges how many shipped state nodes the
    solve actually packed against, so a client that sent state nodes can
    fail fast against a sidecar that silently dropped them."""
    claims = []
    for claim in results.new_node_claims:
        claims.append(
            {
                "pool": claim.template.node_pool_name,
                "instance_types": [it.name for it in claim.instance_type_options],
                "pod_uids": [p.uid for p in claim.pods],
                "requirements": to_wire(claim.requirements),
            }
        )
    existing = [
        {"name": en.name, "pod_uids": [p.uid for p in en.pods]}
        for en in results.existing_nodes
    ]
    return msgpack.packb(
        {
            "claims": claims,
            "existing": existing,
            "state_nodes_packed": int(state_nodes_packed),
            "pod_errors": {uid: str(err) for uid, err in results.pod_errors.items()},
        },
        use_bin_type=True,
    )


def decode_solve_response(data: bytes) -> Dict[str, Any]:
    raw = msgpack.unpackb(data, raw=False)
    for claim in raw["claims"]:
        claim["requirements"] = from_wire(claim["requirements"])
    return raw


__all__ = [
    "to_wire",
    "from_wire",
    "encode_solve_request",
    "decode_solve_request",
    "encode_solve_response",
    "decode_solve_response",
]
