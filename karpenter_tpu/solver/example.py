"""Self-contained example snapshot builders (no test fixtures needed).

Used by __graft_entry__ (compile checks, multi-chip dryrun) and bench.py.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..api import resources as res
from ..api.objects import NodePool, NodePoolSpec, ObjectMeta, Pod, PodSpec
from ..api.objects import NodeClaimTemplate as NodeClaimTemplateSpec
from ..cloudprovider import corpus
from ..kube import Client, TestClock
from ..scheduling.topology import Topology
from . import encode as enc
from .driver import TpuSolver


def example_pods(count: int, shapes: int = 1, zonal: int = 0) -> List[Pod]:
    """``zonal`` of the pods additionally carry a self-selecting zonal
    topology-spread constraint, exercising the kernel's domain-quota path."""
    from ..api import labels as labels_mod
    from ..api.objects import LabelSelector, TopologySpreadConstraint

    pods = []
    for i in range(count):
        s = i % shapes
        spread = []
        pod_labels = {}
        if i < zonal:
            # one uniform shape: the zonal pods must form a single
            # equivalence class (a shared spread constraint across groups
            # demotes them all to the host oracle)
            s = 0
            pod_labels = {"example": "zonal"}
            spread = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=labels_mod.TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels=dict(pod_labels)),
                )
            ]
        pods.append(
            Pod(
                metadata=ObjectMeta(name=f"pod-{i}", labels=pod_labels),
                spec=PodSpec(
                    requests={
                        res.CPU: (1 + s % 7) * res.MILLI,
                        res.MEMORY: (1 + s % 9) * 2**30 * res.MILLI,
                    },
                    topology_spread_constraints=spread,
                ),
            )
        )
    return pods


def example_nodepool(name: str = "default") -> NodePool:
    return NodePool(metadata=ObjectMeta(name=name), spec=NodePoolSpec())


def example_solver(
    n_pods: int, n_types: int, shapes: int = 1, zonal: int = 0
) -> Tuple[TpuSolver, List[Pod]]:
    pods = example_pods(n_pods, shapes, zonal=zonal)
    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(n_types)}
    topology = Topology(Client(TestClock()), [], pools, its, pods)
    return TpuSolver(pools, its, topology), pods


def example_snapshot_arrays(
    n_pods: int, n_types: int, shapes: int = 1, zonal: int = 0
):
    """Encoded snapshot + static kwargs for solve_core, ready to feed the
    kernels directly."""
    solver, pods = example_solver(n_pods, n_types, shapes, zonal=zonal)
    groups, rest = enc.partition_and_group(pods, topology=solver.oracle.topology)
    assert not rest
    templates = solver.oracle.templates
    snap = enc.encode(
        groups,
        templates,
        {t.node_pool_name: t.instance_type_options for t in templates},
        daemon_overhead=solver.oracle.daemon_overhead,
    )
    a_tzc, res_cap0, a_res = solver._offering_availability(snap)
    nmax = solver._estimate_nmax(snap, solver._fit_matrix(snap))
    statics = dict(
        nmax=nmax,
        zone_kid=snap.zone_kid,
        ct_kid=snap.ct_kid,
        has_domains=bool((snap.g_dmode > 0).any()),
        has_contrib=bool(snap.g_hcontrib.any() or snap.g_dcontrib.any()),
    )
    return snap.solve_args(a_tzc, res_cap0, a_res), statics
