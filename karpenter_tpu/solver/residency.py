"""Device-resident solve arguments + the two-slot async dispatch queue.

The delta-encode layer (solver/encode.py:ClusterEncoding) makes the HOST
side of a reconcile marginal-cost; this module does the same for the
host→device boundary so steady-state churn transfers the *delta*, not the
snapshot:

- ``DeviceResidentArgs`` keeps the encoded cluster tensors resident on
  device between solves. Buffers are keyed by the EncodeDelta's per-class
  version counters: an unchanged class reuses its device buffer with zero
  transfer; a class whose buffer is exactly one encode behind re-transfers
  only the changed rows and applies them on device
  (``ops/solve.py:delta_apply_rows``; donation is opt-in — see its module
  note); everything else is a full ``jax.device_put``.
- ``DispatchQueue`` is the explicit two-slot dispatch window: JAX dispatch
  is async, so a submitted kernel computes while the host encodes the next
  batch (or decodes the previous one). The queue makes the overlap an
  auditable object — depth instant events on the open span, a named fault
  site (faults.DISPATCH_QUEUE) for chaos coverage, and a hard two-slot
  bound so a runaway caller cannot pile uncollected work onto the device.

Neither object reads device values back: draining a slot returns the
device arrays, and the single blessed readback stays in solver/driver.py
(PARITY.md device-residency contract).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults, obs

# SOLVE_ARG_NAMES partitioned into device-buffer classes. Versions come
# from EncodeDelta (encode.py): a class's buffers are reusable iff its
# version counter is unchanged since they were staged.
NODE_ROW_ARGS = frozenset(
    {"n_avail", "n_base", "n_def", "n_mask", "n_dzone", "n_dct"}
)
CROSS_ARGS = frozenset({"n_tol", "n_hcnt", "nh_cnt0"})
GROUP_ARGS = frozenset(
    {
        "g_req", "g_def", "g_neg", "g_mask", "g_hcap", "g_haff",
        "g_dmode", "g_dkey", "g_dskew", "g_dmin0", "g_dprior", "g_dreg",
        "g_drank", "g_hstg", "g_hscap", "g_dtg", "g_hself", "g_hcontrib",
        "g_dcontrib", "dd0", "dtg_key", "p_tol",
        # the compacted segment index is a pure function of the group
        # requirement rows, so it versions with the group class; its
        # leading axis is the live-pair bucket L, not G (NO_ROW_DELTA)
        "gk_g", "gk_k", "gk_w", "goff_idx",
    }
)
# g_count is its own class: count-only churn (the steady-state reconcile
# shape) moves ONLY this [G] vector, so the heavy group masks keep their
# device buffers while the counts ride a tiny row delta
GCOUNT_ARGS = frozenset({"g_count"})
# group-class members whose leading axis is NOT the group axis (dd0 and
# dtg_key ride the shared-constraint slot axis, p_tol carries G on axis 1):
# they restage whole on a version bump, never row-by-row — a group-axis
# index applied to them would silently clamp
NO_ROW_DELTA = frozenset(
    {"dd0", "dtg_key", "p_tol", "gk_g", "gk_k", "gk_w", "goff_idx"}
)


class DeviceResidentArgs:
    """Version-keyed device buffers for one catalog's solve arguments.

    Owned by the long-lived EncodeCache (it must outlive TpuSolver
    instances, which the provisioner rebuilds per solve). ``stage``
    returns the argument list with every host array replaced by its
    device-resident buffer, transferring only what the EncodeDelta proves
    changed. ``last_incremental`` reports whether anything was reused or
    delta-applied (the driver's corrupt-delta fallback consults it), and
    ``last_delta_rows``/``last_full_puts`` feed the bench/audit columns.
    """

    def __init__(self, owner: str = ""):
        import threading

        # multi-tenant attribution (solver/tenancy.py): whose device
        # buffers these are — rides the ENCODE_DELTA mutate ctx so chaos
        # plans can corrupt exactly one tenant's deltas
        self.owner = owner
        # the resident-attribute naming convention (_dev*) is load-bearing:
        # the DTX9xx pass treats loads from it as device values, so any host
        # sink on a buffer between solves is a finding
        self._dev_buffers: Dict[str, object] = {}
        self._meta: Dict[str, Tuple[int, tuple, object]] = {}
        # concurrent sidecar solves serialize staging here: the host-side
        # encode already serializes on EncodeCache.lock, and the buffer
        # map + version bookkeeping need the same discipline. Buffer
        # updates default to the NON-donating jit (ops/solve.py) so an
        # in-flight queue token's reference to a replaced buffer stays
        # valid — donation (KTPU_DONATE_DELTA=1) is only safe when no
        # token can outlive a stage.
        self._lock = threading.Lock()
        # mesh signature of the resident buffers: staging against a
        # different mesh (or switching mesh <-> single-device) sheds every
        # buffer — a buffer committed to one device set cannot serve a
        # program compiled over another
        self._mesh_key: object = None
        self.last_incremental = False
        self.last_delta_rows = 0
        self.last_full_puts = 0

    def reset(self) -> None:
        """Drop every device buffer (catalog change, corrupt-delta
        fallback): the next stage() is a clean full transfer."""
        with self._lock:
            self._dev_buffers.clear()
            self._meta.clear()
            self.last_incremental = False

    @staticmethod
    def _class_of(name: str, delta) -> Tuple[int, Optional[np.ndarray]]:
        """(version, row-delta indices or None) for an arg name."""
        if name in NODE_ROW_ARGS:
            return delta.v_nodes, delta.node_rows
        if name in CROSS_ARGS:
            return delta.v_cross, delta.cross_rows
        if name in GCOUNT_ARGS:
            rows = (
                delta.count_rows
                if delta.count_rows is not None
                else delta.group_rows
            )
            return delta.v_gcount, rows
        if name in GROUP_ARGS:
            rows = None if name in NO_ROW_DELTA else delta.group_rows
            return delta.v_groups, rows
        return delta.v_static, None

    def stage(
        self,
        names: Sequence[str],
        host_args: Sequence,
        delta,
        skip: frozenset = frozenset(),
        shardings: Optional[Dict[str, object]] = None,
        mesh_key: object = None,
    ) -> List:
        """Device-resident argument list aligned with ``names``.

        ``skip`` names pass through untouched (the scenario axis overrides
        g_count/n_tol with per-scenario stacks that are staged by the
        caller). Emits one ``solve.delta_apply`` span covering the
        row-level updates (delta_rows/reused attrs ride it for the trace
        smoke and the churn bench).

        ``shardings``/``mesh_key`` make the warm path mesh-resident: full
        puts commit each buffer against its NamedSharding
        (parallel/mesh.py:arg_shardings — the mesh-padded host args the
        driver passes already divide the sharded axes), reuse and row
        deltas then behave exactly as on one device (delta_apply_rows is
        sharding-aware). A changed ``mesh_key`` sheds every buffer first.
        """
        import jax

        from ..ops.solve import delta_apply_rows

        with self._lock:
            if mesh_key != self._mesh_key:
                self._dev_buffers.clear()
                self._meta.clear()
                self._mesh_key = mesh_key
            return self._stage_locked(
                names, host_args, delta, skip, jax, delta_apply_rows,
                shardings or {},
            )

    def _stage_locked(
        self, names, host_args, delta, skip, jax, delta_apply_rows,
        shardings,
    ) -> List:
        out: List = []
        applies: List[Tuple[str, int, np.ndarray, np.ndarray]] = []
        reused = 0
        puts = 0
        for name, host in zip(names, host_args):
            if name in skip or not isinstance(host, np.ndarray):
                out.append(host)
                continue
            version, rows = self._class_of(name, delta)
            meta = self._meta.get(name)
            sig = (version, host.shape, host.dtype)
            if meta is not None and meta == sig:
                out.append(self._dev_buffers[name])
                reused += 1
                continue
            if (
                meta is not None
                and rows is not None
                and len(rows)
                and meta[0] == version - 1
                and meta[1] == host.shape
                and meta[2] == host.dtype
            ):
                # shape-stable row delta — valid ONLY when the resident
                # buffer is exactly one version step behind AND this
                # encode is the step: a class version bumps exactly when
                # its tags change, and the diff is nonempty exactly then,
                # so nonempty rows + version-1 proves the rows describe
                # the buffer's own transition. Encodes can pass without a
                # stage (a scenario batch declining after its encode, a
                # skipped per-scenario arg, the native backend); an EMPTY
                # diff with a version gap means the change happened at
                # one of those unstaged encodes — patching nothing and
                # stamping the buffer current would feed the kernel
                # content from two encodes ago, so it restages whole.
                applies.append((name, version, host, rows))
                out.append(None)  # patched below, order preserved
                continue
            sharding = shardings.get(name)
            buf = (
                jax.device_put(host, sharding)
                if sharding is not None
                else jax.device_put(host)
            )
            self._dev_buffers[name] = buf
            self._meta[name] = sig
            out.append(buf)
            puts += 1
        delta_rows = 0
        if applies:
            pos = {name: i for i, name in enumerate(names)}
            with obs.span(
                "solve.delta_apply",
                arrays=len(applies),
                delta_rows=int(sum(len(r) for *_x, r in applies)),
            ):
                for name, version, host, rows in applies:
                    vals = host[rows]
                    # chaos seam: a corrupt delta lands HERE, on the wire
                    # rows — the pre-decode invariant guard must catch the
                    # resulting solve and force a full re-encode
                    vals = faults.mutate(
                        faults.ENCODE_DELTA, vals, name=name,
                        rows=len(rows), owner=self.owner,
                    )
                    buf = delta_apply_rows(self._dev_buffers[name], rows, vals)
                    self._dev_buffers[name] = buf
                    self._meta[name] = (version, host.shape, host.dtype)
                    out[pos[name]] = buf
                    delta_rows += len(rows)
        self.last_incremental = bool(reused or applies)
        self.last_delta_rows = delta_rows
        self.last_full_puts = puts
        return out


class DispatchQueue:
    """Explicit two-slot window over async kernel dispatches.

    ``submit(label, fn)`` runs ``fn`` immediately — JAX async dispatch
    returns device futures, so the call does not block on XLA — and
    tracks the slot; a third in-flight submit evicts the oldest slot
    (its device work completes and is dropped; the two-slot bound keeps
    device memory and speculation bounded). ``drain(slot)`` hands back
    the submitted call's outputs and frees the slot. Both edges emit
    ``queue.depth`` instant events on the open span and consult the
    ``faults.DISPATCH_QUEUE`` site, so chaos plans can crash either edge
    and traces show the overlap window.
    """

    DEPTH = 2

    def __init__(self):
        self._slots: deque = deque()
        self._seq = 0

    def depth(self) -> int:
        return len(self._slots)

    def submit(self, label: str, fn):
        faults.hit(
            faults.DISPATCH_QUEUE, op="submit", label=label,
            depth=len(self._slots),
        )
        while len(self._slots) >= self.DEPTH:
            # evict the oldest uncollected slot: its device computation
            # finishes on its own; the caller that abandoned it never
            # drains (speculative prefetch that lost the race)
            stale = self._slots.popleft()
            obs.event("queue.evict", label=stale["label"])
        self._seq += 1
        slot = {"label": label, "seq": self._seq, "out": fn()}
        self._slots.append(slot)
        obs.event("queue.depth", depth=len(self._slots), op="submit",
                  label=label)
        return slot

    def drain(self, slot):
        faults.hit(
            faults.DISPATCH_QUEUE, op="drain", label=slot["label"],
            depth=len(self._slots),
        )
        try:
            self._slots.remove(slot)
        except ValueError:
            pass  # already evicted; its outputs are still valid futures
        obs.event("queue.depth", depth=len(self._slots), op="drain",
                  label=slot["label"])
        return slot["out"]


__all__ = [
    "DeviceResidentArgs", "DispatchQueue",
    "NODE_ROW_ARGS", "CROSS_ARGS", "GROUP_ARGS", "GCOUNT_ARGS",
    "NO_ROW_DELTA",
]
