"""Benchmark workload generators (BASELINE.json configs).

Deterministic (seeded) pod/cluster builders mirroring the reference's
benchmark harness (scheduling_benchmark_test.go:236-249 and its random
cpu/memory/label tables) plus the BASELINE-specific configs. Used by
bench.py and tests/test_perf_floor.py; kept in the package so the solver
sidecar can regenerate identical workloads.
"""

from __future__ import annotations

import random
from typing import Dict, List, Tuple

from ..api import labels as labels_mod
from ..api import resources as res
from ..api.objects import (
    Budget,
    LabelSelector,
    NodeSelectorRequirement,
    ObjectMeta,
    NodeAffinity,
    Pod,
    PodAffinityTerm,
    PodSpec,
    TopologySpreadConstraint,
)

# the reference's random tables (scheduling_benchmark_test.go:357-381)
_CPUS_M = (100, 250, 500, 1000, 1500)
_MEM_MI = (100, 256, 512, 1024, 2048, 4096)
_LABEL_VALUES = ("a", "b", "c", "d", "e", "f", "g")

_MI = 2**20 * res.MILLI


def _pod(name: str, cpu_m: int, mem_mi: int, labels: Dict[str, str] = None,
         gpu: int = 0, **spec_kwargs) -> Pod:
    requests = {res.CPU: cpu_m, res.MEMORY: mem_mi * _MI}
    if gpu:
        requests["nvidia.com/gpu"] = gpu * res.MILLI
    return Pod(
        metadata=ObjectMeta(name=name, labels=dict(labels or {})),
        spec=PodSpec(requests=requests, **spec_kwargs),
    )


def identical_pods(count: int) -> List[Pod]:
    """BASELINE config[0]: identical cpu/mem pods."""
    return [_pod(f"ident-{i}", 1000, 2048) for i in range(count)]


def mixed_pods(count: int, seed: int = 7, gpu_fraction: float = 0.05) -> List[Pod]:
    """BASELINE config[1]: mixed cpu/mem/gpu pods over the reference's
    random request tables."""
    rng = random.Random(seed)
    pods = []
    for i in range(count):
        gpu = 1 if rng.random() < gpu_fraction else 0
        pods.append(
            _pod(
                f"mixed-{i}",
                rng.choice(_CPUS_M),
                rng.choice(_MEM_MI),
                labels={"my-label": rng.choice(_LABEL_VALUES)},
                gpu=gpu,
            )
        )
    return pods


def _self_spread(key: str, labels: Dict[str, str], max_skew: int = 1):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=dict(labels)),
    )


def constrained_mix(count: int, seed: int = 11) -> List[Pod]:
    """BASELINE config[2]: nodeAffinity + topology spread (zone/hostname).

    Deployment-shaped: constraints are self-selecting per deployment (the
    realistic spread shape — a Deployment's constraint selects its own
    replicas), so the whole mix rides the TPU fast path. 40% generic,
    20% zonal node affinity, 20% zonal spread, 20% hostname spread.
    """
    rng = random.Random(seed)
    pods: List[Pod] = []
    n_generic = count * 4 // 10
    n_aff = count * 2 // 10
    n_zspread = count * 2 // 10
    n_hspread = count - n_generic - n_aff - n_zspread

    for i in range(n_generic):
        pods.append(
            _pod(f"gen-{i}", rng.choice(_CPUS_M), rng.choice(_MEM_MI))
        )
    zones = ["test-zone-a", "test-zone-b", "test-zone-c"]
    for i in range(n_aff):
        pick = sorted(rng.sample(zones, 2))
        pods.append(
            _pod(
                f"aff-{i}", rng.choice(_CPUS_M), rng.choice(_MEM_MI),
                node_affinity=NodeAffinity(
                    required=[
                        (
                            NodeSelectorRequirement(
                                labels_mod.TOPOLOGY_ZONE, "In", tuple(pick)
                            ),
                        )
                    ]
                ),
            )
        )
    # spread classes: deployments of ~500 replicas, one shape each so every
    # deployment is a single tensor group
    def deployments(n: int, key: str, prefix: str) -> None:
        size = 500
        d = 0
        while n > 0:
            k = min(size, n)
            lbl = {prefix: f"d{d}"}
            cpu, mem = rng.choice(_CPUS_M), rng.choice(_MEM_MI)
            for i in range(k):
                pods.append(
                    _pod(
                        f"{prefix}-{d}-{i}", cpu, mem, labels=lbl,
                        topology_spread_constraints=[_self_spread(key, lbl)],
                    )
                )
            n -= k
            d += 1

    deployments(n_zspread, labels_mod.TOPOLOGY_ZONE, "zs")
    deployments(n_hspread, labels_mod.HOSTNAME, "hs")
    return pods


def diverse_reference_mix(count: int, seed: int = 13) -> List[Pod]:
    """The reference's literal 5-class diverse mix
    (scheduling_benchmark_test.go:236-249): equal parts generic, zonal
    spread, hostname spread, zonal self-affinity, hostname anti-affinity —
    with the reference's independently-random spread selectors (which
    select across groups and therefore serialize via the host oracle)."""
    rng = random.Random(seed)
    per = count // 5
    pods: List[Pod] = []

    def rand_req():
        return rng.choice(_CPUS_M), rng.choice(_MEM_MI)

    for i in range(per + count - 5 * per):  # generic fills the remainder
        cpu, mem = rand_req()
        pods.append(
            _pod(f"dgen-{i}", cpu, mem,
                 labels={"my-label": rng.choice(_LABEL_VALUES)})
        )
    for key, prefix in (
        (labels_mod.TOPOLOGY_ZONE, "dzs"),
        (labels_mod.HOSTNAME, "dhs"),
    ):
        for i in range(per):
            cpu, mem = rand_req()
            pods.append(
                _pod(
                    f"{prefix}-{i}", cpu, mem,
                    labels={"my-label": rng.choice(_LABEL_VALUES)},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=key,
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={
                                    "my-label": rng.choice(_LABEL_VALUES)
                                }
                            ),
                        )
                    ],
                )
            )
    for i in range(per):  # zonal self-affinity
        cpu, mem = rand_req()
        lbl = {"my-affininity": rng.choice(_LABEL_VALUES)}
        pods.append(
            _pod(
                f"daff-{i}", cpu, mem, labels=lbl,
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=labels_mod.TOPOLOGY_ZONE,
                        label_selector=LabelSelector(match_labels=lbl),
                    )
                ],
            )
        )
    anti_lbl = {"app": "nginx"}
    for i in range(per):  # hostname anti-affinity (one node per pod)
        cpu, mem = rand_req()
        pods.append(
            _pod(
                f"danti-{i}", cpu, mem, labels=anti_lbl,
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=labels_mod.HOSTNAME,
                        label_selector=LabelSelector(match_labels=anti_lbl),
                    )
                ],
            )
        )
    return pods


def spot_od_pools():
    """BASELINE config[4]: weighted spot + on-demand pools with limits."""
    from ..api.objects import (
        NodeClaimSpec, NodePool, NodePoolSpec,
        NodeClaimTemplate as NodeClaimTemplateSpec,
    )

    def pool(name: str, ct: str, weight: int, cpu_limit: str):
        return NodePool(
            metadata=ObjectMeta(name=name),
            spec=NodePoolSpec(
                template=NodeClaimTemplateSpec(
                    spec=NodeClaimSpec(
                        requirements=[
                            NodeSelectorRequirement(
                                labels_mod.CAPACITY_TYPE_LABEL_KEY, "In", (ct,)
                            )
                        ]
                    )
                ),
                weight=weight,
                limits={res.CPU: res.parse_quantity(cpu_limit)},
            ),
        )

    return [
        pool("spot", labels_mod.CAPACITY_TYPE_SPOT, 80, "3000"),
        pool("on-demand", labels_mod.CAPACITY_TYPE_ON_DEMAND, 20, "100000"),
    ]


def build_single_consolidation_env(n_nodes: int) -> Tuple:
    """A single-node-consolidation variant of the consolidation env: same
    underutilized cluster, method = SingleNodeConsolidation (the
    per-candidate sweep the scenario batch evaluates in chunks). Returns
    (ctx, SingleNodeConsolidation, candidates, budgets)."""
    from ..controllers.disruption.methods import SingleNodeConsolidation

    ctx, _multi, candidates, budgets = build_consolidation_env(n_nodes)
    method = SingleNodeConsolidation(ctx)
    return ctx, method, candidates, budgets


def build_consolidation_env(n_nodes: int) -> Tuple:
    """BASELINE config[3]: an underutilized cluster of ``n_nodes`` ready for
    multi-node consolidation.

    State is fabricated directly (Initialized NodeClaims + Nodes + one
    half-utilizing bound pod each) — the watch-fed Cluster ingests it
    exactly as live informer events would — so the benchmark times the
    consolidation search itself, not cluster bring-up. Returns
    (ctx, MultiNodeConsolidation, candidates, budgets)."""
    from ..api.objects import (
        COND_CONSOLIDATABLE, COND_INITIALIZED, COND_LAUNCHED, COND_REGISTERED,
        Node, NodeClaim, NodeClaimSpec, NodePool, NodePoolSpec,
        NodeClaimTemplate as NodeClaimTemplateSpec,
    )
    from ..cloudprovider import corpus
    from ..cloudprovider.kwok import KwokCloudProvider
    from ..controllers.disruption.controller import DisruptionContext
    from ..controllers.disruption.helpers import (
        build_budget_mapping, get_candidates,
    )
    from ..controllers.disruption.methods import MultiNodeConsolidation
    from ..controllers.state import Cluster
    from ..events.recorder import Recorder
    from ..kube import Client, TestClock

    clock = TestClock()
    clock.step(3600.0)
    client = Client(clock)
    its = corpus.generate(50)
    provider = KwokCloudProvider(client, its)
    cluster = Cluster(client)

    pool = NodePool(
        metadata=ObjectMeta(name="default"),
        spec=NodePoolSpec(template=NodeClaimTemplateSpec(spec=NodeClaimSpec())),
    )
    pool.spec.disruption.consolidate_after = 10.0
    client.create(pool)

    # a deliberately oversized node type: the filler pod uses <40% of it,
    # so consolidation can re-pack fillers onto fewer, cheaper nodes
    def fits(it):
        return (
            it.capacity.get(res.CPU, 0) >= 4000
            and it.capacity.get(res.MEMORY, 0) >= 8 * 1024 * _MI
        )

    candidates_it = sorted(
        (it for it in its if fits(it)),
        key=lambda it: min(
            (o.price for o in it.offerings if o.available), default=1e9
        ),
    )
    it = candidates_it[len(candidates_it) // 2]  # mid-priced: room to go cheaper
    offering = min(
        (o for o in it.offerings if o.available), key=lambda o: o.price
    )

    for i in range(n_nodes):
        name = f"bench-{i}"
        pid = f"bench://{i}"
        node_labels = {
            labels_mod.HOSTNAME: name,
            labels_mod.INSTANCE_TYPE: it.name,
            labels_mod.TOPOLOGY_ZONE: offering.zone(),
            labels_mod.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type(),
            labels_mod.NODEPOOL_LABEL_KEY: pool.name,
        }
        claim = NodeClaim(
            metadata=ObjectMeta(name=name, labels=dict(node_labels)),
            spec=NodeClaimSpec(),
        )
        claim.status.provider_id = pid
        claim.status.capacity = dict(it.capacity)
        claim.status.allocatable = dict(it.allocatable())
        now = clock.now()
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED,
                     COND_CONSOLIDATABLE):
            claim.conds().set(cond, "True", now=now)
        node = Node(
            metadata=ObjectMeta(name=name, labels=node_labels),
            provider_id=pid,
        )
        node.status.capacity = dict(it.capacity)
        node.status.allocatable = dict(it.allocatable())
        node.status.ready = True
        filler = _pod(f"fill-{i}", 750, 1024)
        filler.spec.node_name = name
        filler.status.phase = "Running"
        client.create(claim)
        client.create(node)
        client.create(filler)

    ctx = DisruptionContext(
        client=client,
        cluster=cluster,
        cloud_provider=provider,
        clock=clock,
        recorder=Recorder(clock),
        spot_to_spot_enabled=True,
    )
    method = MultiNodeConsolidation(ctx)
    candidates = [
        c
        for c in get_candidates(
            ctx.client, ctx.cluster, ctx.cloud_provider, clock
        )
        if method.should_disrupt(c)
    ]
    budgets = build_budget_mapping(
        ctx.client, ctx.cluster, method.reason, clock.now()
    )
    return ctx, method, candidates, budgets
