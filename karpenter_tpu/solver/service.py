"""Solver sidecar: multi-tenant gRPC service over the wire codec.

The TPU-native deployment splits the control plane from the solver: each
controller process (Go-shaped, level-triggered) ships snapshots over DCN
to this sidecar, which runs the fused feasibility/packing kernels on its
local TPU slice and returns packed claims (SURVEY.md §5, BASELINE.json
north-star). In-process callers keep using TpuSolver directly;
RemoteSolver is the same seam behind a channel.

Many control planes share one sidecar (the multi-tenant service,
solver/tenancy.py): the tenant id rides the ``ktpu-tenant-id`` request
metadata, and every tenant gets its OWN warm state (``EncodeCache`` →
row banks + device buffers) and its OWN degradation ladder — isolation
machinery, admission control, and QoS tiers live in ``TenantRegistry``.
Error contract over the hop:

- RESOURCE_EXHAUSTED — admission rejected (rate limit, queue bound,
  tier shed, tenant capacity). The client must BACK OFF; solving the
  same view in-process would defeat the quota.
- DEADLINE_EXCEEDED — the solve ran but blew the tenant's latency
  budget. The client's retry/fallback ladder treats it like a slow
  sidecar and falls back in-process.
- INVALID_ARGUMENT / INTERNAL — malformed request / sidecar bug, as
  before.

The service is defined with grpc generic handlers over the msgpack codec
in wire.py — no generated stubs, one method:

    /karpenter_tpu.solver.v1.Solver/Solve   (unary-unary, bytes in/out)
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import logging
from concurrent import futures
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import grpc
import msgpack

from .. import faults, obs
from ..api.objects import NodePool, Pod
from ..cloudprovider import types as cp
from ..kube import Client, TestClock
from ..scheduling.scheduler import Results
from ..scheduling.topology import Topology
from . import wire
from .driver import (
    DecodedClaim,
    EncodeCache,
    Scenario,
    SolverConfig,
    TpuSolver,
)
from .tenancy import (
    DEFAULT_TENANT,
    AdmissionError,
    CrossTenantBatcher,
    DeadlineOverrunError,
    TenantRegistry,
    TenantState,
)

_LOG = logging.getLogger("karpenter_tpu.solver.service")

SERVICE_NAME = "karpenter_tpu.solver.v1.Solver"
SOLVE_METHOD = f"/{SERVICE_NAME}/Solve"

# request metadata key carrying the caller's tenant id (lowercase per
# gRPC metadata rules); absent → the "default" tenant. Tier assignment
# is SERVER configuration (TenantRegistry), never client metadata.
TENANT_ID_METADATA_KEY = "ktpu-tenant-id"

# gRPC status codes that mean "the sidecar may answer if asked again" —
# RemoteSolver retries these once, then degrades to an in-process solve
RETRIABLE_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED")
# admission backpressure: retry once after the bounded retry, then RAISE
# (SolverBackpressure) instead of falling back in-process — the service
# refused to spend quota on this view, the client must back off
BACKPRESSURE_CODES = ("RESOURCE_EXHAUSTED",)


class SolverBackpressure(RuntimeError):
    """The sidecar's admission control rejected the solve twice — the
    caller should requeue with backoff. Deliberately NOT an in-process
    fallback: the tenant is over quota, not the sidecar unreachable."""

    def __init__(self, tenant: str, detail: str):
        super().__init__(
            f"solver sidecar admission backpressure"
            + (f" for tenant {tenant!r}" if tenant else "")
            + f": {detail}"
        )
        self.tenant = tenant
        self.detail = detail


class InjectedRpcError(grpc.RpcError):
    """Fault-injection stand-in for a channel-level RPC failure, carrying
    a status code the way a real ``grpc.Call`` error does. Fault plans
    raise this at the ``faults.REMOTE_SOLVE`` site."""

    def __init__(self, code: "grpc.StatusCode"):
        super().__init__(f"injected rpc failure: {code}")
        self._code = code

    def code(self):
        return self._code


def _status_name(exc: "grpc.RpcError") -> str:
    """The status-code name of an RpcError ("UNAVAILABLE", ...), tolerant
    of both real channel errors and injected ones."""
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            code = code()
        except Exception:
            return ""
    return getattr(code, "name", str(code) if code is not None else "")


def build_solver(
    pods: Sequence[Pod],
    node_pools: Sequence[NodePool],
    instance_types,
    daemonset_pods: Sequence[Pod],
    state_nodes: Sequence,
    volume_objects,
    reserved_capacity_enabled: bool,
    config: Optional[SolverConfig] = None,
    encode_cache: Optional[EncodeCache] = None,
    copy_objects: bool = False,
) -> TpuSolver:
    """The one recipe for a solver over a shipped cluster view — used by
    the sidecar for every request and by RemoteSolver's in-process
    fallback, so the two can never pack differently.

    Rebuilds the controller's cluster view: state nodes pack FIRST
    (scheduler.go:357-425), their bound pods feed the topology priors and
    inverse anti-affinity gates, and PVC/PV/StorageClass objects let the
    VolumeResolver answer identically — so the scratch client holds them.
    ``copy_objects`` deep-copies objects into the scratch store (the
    fallback path feeds LIVE controller objects, and the scratch create
    must not bump their resource versions). The scratch store is plain
    memory, not an apiserver — store-chaos plans are exempted so an
    injected store outage can't crash the fallback built to survive it."""
    scratch = Client(TestClock(), fault_injection=False)

    def _add(obj):
        scratch.create(copy.deepcopy(obj) if copy_objects else obj)

    for sn in state_nodes:
        if sn.node is not None:
            _add(sn.node)
        for p in sn.pods:
            _add(p)
    for vo in volume_objects or ():
        _add(vo)
    topology = Topology(scratch, state_nodes, node_pools, instance_types, pods)
    from ..scheduling.volumeusage import VolumeResolver

    # clients predating the volume protocol (volume_objects is None, not
    # []) never ship PVC/PV objects; resolving against the empty scratch
    # store would fail every PVC-bearing pod, so keep the old no-resolver
    # behavior for them
    resolver = VolumeResolver(scratch) if volume_objects is not None else None
    return TpuSolver(
        node_pools,
        instance_types,
        topology,
        state_nodes=state_nodes,
        daemonset_pods=daemonset_pods,
        volume_resolver=resolver,
        config=config,
        # catalog encode amortizes across requests; the cache's lock
        # serializes the host-side encode under the gRPC thread pool
        encode_cache=encode_cache,
        reserved_capacity_enabled=reserved_capacity_enabled,
    )


def _solve_snapshot(data: bytes, config: Optional[SolverConfig]) -> bytes:
    return _solve_decoded(wire.decode_solve_request(data), config)


def _solve_objects(
    snap: dict,
    config: Optional[SolverConfig],
    encode_cache: Optional[EncodeCache] = None,
):
    """One decoded snapshot solved end to end; returns ``(results,
    solver)`` so the tenant layer can read the solver's telemetry
    (fallback_solves) without re-plumbing it through the wire."""
    pods: List[Pod] = snap["pods"]
    solver = build_solver(
        pods,
        snap["node_pools"],
        snap["instance_types"],
        snap["daemonset_pods"],
        snap["state_nodes"],
        snap["volume_objects"],
        # behavior knobs travel in the snapshot so controller and sidecar
        # can never disagree on gate-dependent packing
        bool(snap["solver_options"].get("reserved_capacity_enabled", False)),
        config=config,
        encode_cache=encode_cache,
    )
    return solver.solve(pods), solver


def _solve_decoded(
    snap: dict,
    config: Optional[SolverConfig],
    encode_cache: Optional[EncodeCache] = None,
) -> bytes:
    results, _solver = _solve_objects(snap, config, encode_cache)
    return wire.encode_solve_response(
        results, state_nodes_packed=len(snap["state_nodes"])
    )


def _batch_key(snap: dict) -> Optional[str]:
    """Content key under which a snapshot may join a cross-tenant
    microbatch, or None when its shape must solo-solve.

    Only the shapes whose scenario-batched answer is PROVABLY the solo
    answer batch: identical catalog sections (hashed below — tenants
    with different catalogs land in different batches, never a wrong
    one), no volume objects (the VolumeResolver's scratch store is
    per-request), no pool limits (a shared kernel ledger would meter
    the union, not each tenant), and no topology-spread/affinity pods
    (union topology priors would leak one tenant's bound pods into
    another's spread counting). Everything else declines to the solo
    path — a lost batching opportunity, never a lost decision."""
    if snap["volume_objects"]:
        return None
    for np_ in snap["node_pools"]:
        if getattr(np_.spec, "limits", None):
            return None
    for p in snap["pods"]:
        spec = p.spec
        if getattr(spec, "topology_spread_constraints", None) or getattr(
            spec, "affinity", None
        ):
            return None
    for sn in snap["state_nodes"]:
        # scenario exclusion masks key on provider ids: a node without
        # one cannot be masked out of the other tenants' scenarios
        if not getattr(sn, "provider_id", ""):
            return None
    payload = msgpack.packb(
        wire.to_wire(
            [
                snap["node_pools"],
                snap["instance_types"],
                snap["daemonset_pods"],
                snap["solver_options"],
                snap["volume_objects"] is None,  # old-protocol marker
            ]
        ),
        use_bin_type=True,
    )
    return hashlib.blake2b(payload, digest_size=12).hexdigest()


class TenantService:
    """Multi-tenant solve orchestration behind the gRPC surface.

    One instance per sidecar process: holds the ``TenantRegistry`` (per-
    tenant warm state + admission control), the cross-tenant batcher,
    and the shared-batch-lane ``EncodeCache`` (its OWN isolation domain:
    a corrupt delta in the batch lane sheds the batch lane, never a
    tenant's private cache). Also the in-process facade the concurrency
    storm, the chaos suite, and ``bench.py --tenants`` drive — the gRPC
    handler is a thin codec shell around ``solve_encoded``."""

    def __init__(
        self,
        registry: Optional[TenantRegistry] = None,
        config: Optional[SolverConfig] = None,
        batch_window: float = 0.0,
        batch_max: int = 8,
    ):
        self.registry = registry if registry is not None else TenantRegistry()
        self._base_config = config
        self.batcher = CrossTenantBatcher(
            window=batch_window, max_batch=batch_max
        )
        self._batch_cache = EncodeCache(owner="__batch__")

    def solve_for(self, tenant_id: str, snap: dict) -> Results:
        """Admission → (batched | solo) solve → deadline check, with the
        tenant's ambient fault scope around everything that runs on its
        behalf. Raises ``AdmissionError`` before any work and
        ``DeadlineOverrunError`` after a budget-blowing solve."""
        lease = self.registry.admit(tenant_id)  # AdmissionError propagates
        tenant = lease.tenant
        try:
            t0 = self.registry.clock.now()
            with faults.ambient(tenant=tenant_id):
                # chaos seam: per-tenant solve crashes and latency (the
                # injected-clock sleep is how deadline-overrun plans fire
                # deterministically)
                faults.hit(faults.TENANT_SOLVE, tenant=tenant_id)
                with obs.span(
                    "tenant.solve", tenant=tenant_id, tier=tenant.qos.tier
                ):
                    results, fallbacks = self._solve_admitted(tenant, snap)
            elapsed = self.registry.clock.now() - t0
            if elapsed > tenant.qos.solve_deadline:
                tenant.note_deadline_overrun()
                raise DeadlineOverrunError(
                    tenant_id, elapsed, tenant.qos.solve_deadline
                )
            tenant.note_solve(fallbacks)
            return results
        finally:
            lease.release()

    def solve_encoded(self, tenant_id: str, snap: dict) -> bytes:
        results = self.solve_for(tenant_id, snap)
        return wire.encode_solve_response(
            results, state_nodes_packed=len(snap["state_nodes"])
        )

    def _solve_admitted(self, tenant: TenantState, snap: dict):
        key = None
        if self.batcher.window > 0 and tenant.health.level() == 0:
            # a degraded tenant drops out of the shared batch lane: its
            # rung rides its own ladder, not the batch's
            key = _batch_key(snap)
        if key is None:
            return self._solve_solo(tenant, snap)
        return self.batcher.solve(
            key,
            (tenant, snap),
            solo=lambda: self._solve_solo(tenant, snap),
            grouped=self._solve_union,
        )

    def _solve_solo(self, tenant: TenantState, snap: dict):
        cfg = (
            self._base_config
            if self._base_config is not None
            else SolverConfig()
        )
        cfg = dataclasses.replace(
            cfg, health=tenant.health, tenant=tenant.tenant_id
        )
        results, solver = _solve_objects(snap, cfg, tenant.encode_cache)
        return results, solver.fallback_solves

    def _solve_union(self, requests):
        """One scenario-batched dispatch over every participant's solve:
        union workload + union node set, one ``Scenario`` per tenant
        activating its pods and masking the other tenants' nodes. Returns
        per-request ``(results, fallbacks)`` aligned with ``requests``,
        or None to decline (participants solo-solve). The ``__batch__``
        ambient scope keeps tenant-pinned fault plans out of the shared
        lane — isolation is a property of the per-tenant lanes, and a
        faulted batch lane declines to them."""
        union_pods: List[Pod] = []
        seen_uids = set()
        union_sns: list = []
        seen_nodes = set()
        pids_by_req: List[set] = []
        for _tenant, snap in requests:
            for p in snap["pods"]:
                if p.uid in seen_uids:
                    return None
                seen_uids.add(p.uid)
                union_pods.append(p)
            pids = set()
            for sn in snap["state_nodes"]:
                pid = getattr(sn, "provider_id", "") or ""
                name = sn.node.name if sn.node is not None else pid
                if not pid or pid in seen_nodes or name in seen_nodes:
                    return None
                seen_nodes.add(pid)
                seen_nodes.add(name)
                pids.add(pid)
                union_sns.append(sn)
            pids_by_req.append(pids)
        all_pids: set = set()
        for pids in pids_by_req:
            all_pids |= pids
        first = requests[0][1]
        cfg = (
            self._base_config
            if self._base_config is not None
            else SolverConfig()
        )
        cfg = dataclasses.replace(cfg, health=None, tenant="__batch__")
        solver = build_solver(
            union_pods,
            first["node_pools"],
            first["instance_types"],
            first["daemonset_pods"],
            union_sns,
            first["volume_objects"],  # keyed: all-None or all-empty
            bool(
                first["solver_options"].get(
                    "reserved_capacity_enabled", False
                )
            ),
            config=cfg,
            encode_cache=self._batch_cache,
        )
        scenarios = [
            Scenario(
                pods=list(snap["pods"]),
                excluded_provider_ids=frozenset(all_pids - pids_by_req[i]),
            )
            for i, (_tenant, snap) in enumerate(requests)
        ]
        with faults.ambient(tenant="__batch__"):
            outs = solver.solve_scenarios(scenarios)
        if outs is None:
            return None
        per_request = []
        for (_tenant, snap), res in zip(requests, outs):
            own = {
                sn.node.name
                for sn in snap["state_nodes"]
                if sn.node is not None
            }
            existing = [en for en in res.existing_nodes if en.name in own]
            per_request.append(
                (
                    Results(
                        new_node_claims=res.new_node_claims,
                        existing_nodes=existing,
                        pod_errors=res.pod_errors,
                    ),
                    0,
                )
            )
        return per_request


class SolverService(grpc.GenericRpcHandler):
    """Generic unary handler for the Solve method.

    Exceptions map to proper gRPC status codes instead of crashing the
    stream through the generic handler: a request the codec cannot decode
    is the CLIENT's bug (INVALID_ARGUMENT — retrying it can never help);
    admission rejection is RESOURCE_EXHAUSTED (back off); a per-tenant
    deadline overrun is DEADLINE_EXCEEDED (client falls back in-process);
    a solve that raises is the sidecar's bug (INTERNAL, retriable by
    policy). RemoteSolver keys its retry/fallback ladder off these."""

    def __init__(
        self,
        config: Optional[SolverConfig] = None,
        tenants: Optional[TenantService] = None,
    ):
        self.config = config
        self.tenants = (
            tenants if tenants is not None else TenantService(config=config)
        )

    def _handle(self, request, context):
        # trace context rides the gRPC metadata (obs/trace.py): when the
        # caller sent one, the sidecar's spans adopt the caller's trace id
        # and parent on the caller's span — so the stitched trace shows
        # the RemoteSolver hop as one tree across both processes
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        tenant_id = md.get(TENANT_ID_METADATA_KEY) or DEFAULT_TENANT
        with obs.span(
            "sidecar.solve",
            trace_id=md.get(obs.TRACE_ID_METADATA_KEY),
            parent_id=md.get(obs.PARENT_ID_METADATA_KEY),
            tenant=tenant_id,
        ):
            try:
                snap = wire.decode_solve_request(request)
            except Exception as exc:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"malformed solve request: {type(exc).__name__}: {exc}",
                )
            try:
                return self.tenants.solve_encoded(tenant_id, snap)
            except AdmissionError as exc:
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"tenant {tenant_id!r} admission rejected "
                    f"({exc.reason}): back off and retry",
                )
            except DeadlineOverrunError as exc:
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
            except Exception as exc:
                _LOG.exception("solve failed")
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"solve failed: {type(exc).__name__}: {exc}",
                )

    def service(self, handler_call_details):
        if handler_call_details.method != SOLVE_METHOD:
            return None
        return grpc.unary_unary_rpc_method_handler(
            self._handle,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )


def serve(
    address: str = "127.0.0.1:0",
    config: Optional[SolverConfig] = None,
    max_workers: int = 4,
    registry: Optional[TenantRegistry] = None,
    batch_window: float = 0.0,
) -> "grpc.Server":
    """Start a solver sidecar; returns the started server. The bound port is
    available via server._bound_port (set here) when address ends in :0.
    ``registry`` carries the tenant/QoS configuration (default: a fresh
    registry with standard-tier defaults — unidentified traffic lands on
    the "default" tenant); ``batch_window`` > 0 opts into cross-tenant
    microbatching with that many seconds of batch formation delay."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    tenants = TenantService(
        registry=registry, config=config, batch_window=batch_window
    )
    server.add_generic_rpc_handlers((SolverService(config, tenants=tenants),))
    server._tenant_service = tenants
    port = server.add_insecure_port(address)
    server._bound_port = port
    server.start()
    return server


@dataclass
class RemoteExistingNode:
    """Existing-node placement reassembled from the sidecar's response.
    Duck-types the surface Results consumers read (provisioning.py:262:
    .name for nomination, .pods for events)."""

    name: str
    pods: List[Pod]


class RemoteSolver:
    """Client-side seam: same solve(pods) contract as TpuSolver, but the
    packing runs in the sidecar. Claims come back as instance-type names and
    pod uids and are reassembled against the local objects. Pass the
    cluster's StateNodes (``state_nodes``) so the sidecar packs existing
    capacity first exactly like the in-process solve — without them a
    non-empty cluster over-provisions every batch. Pass the PVC/PV/
    StorageClass objects pending pods reference (``volume_objects``) so
    CSI attach-limit checks match too.

    Every dispatch carries a deadline (``SolverConfig.solve_deadline``
    when a config is given, else ``timeout``). UNAVAILABLE and
    DEADLINE_EXCEEDED get exactly one retry; if the sidecar still doesn't
    answer, the solve degrades to an IN-PROCESS run over the same shipped
    cluster view (``build_solver`` — the sidecar's own recipe), so a gRPC
    outage slows a reconcile instead of failing it. RESOURCE_EXHAUSTED is
    the one status that gets a retry but NEVER the in-process fallback:
    it means the sidecar's admission control rejected this tenant, and
    solving locally would turn the backpressure signal into exactly the
    overload it exists to prevent — ``SolverBackpressure`` propagates so
    the caller re-queues the reconcile instead. Any other status (catalog
    skew, malformed request) propagates: retrying those lies."""

    def __init__(
        self,
        target: str,
        node_pools: Sequence[NodePool],
        instance_types: Dict[str, List[cp.InstanceType]],
        daemonset_pods: Sequence[Pod] = (),
        channel: Optional["grpc.Channel"] = None,
        timeout: float = 30.0,
        reserved_capacity_enabled: bool = False,
        state_nodes: Sequence = (),
        volume_objects: Sequence = (),
        config: Optional[SolverConfig] = None,
        encode_cache: Optional[EncodeCache] = None,
        tenant: str = "",
    ):
        self._channel = channel or grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(SOLVE_METHOD)
        self.config = config
        # identifies this control plane to the sidecar's TenantRegistry;
        # "" sends no metadata and lands on the "default" tenant
        self.tenant = tenant
        self.timeout = (
            config.solve_deadline if config is not None else timeout
        )
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.node_pools = list(node_pools)
        self.instance_types = instance_types
        self.daemonset_pods = list(daemonset_pods)
        self.state_nodes = list(state_nodes)
        self.volume_objects = list(volume_objects)
        self.fallback_solves = 0  # telemetry: in-process degradations
        # a sidecar outage makes EVERY reconcile fall back — amortize the
        # host-side catalog encode across those solves. Callers that build
        # a RemoteSolver per cycle (the Provisioner does) must pass their
        # long-lived cache; the per-instance default still de-dups repeat
        # solves on one instance
        self._fallback_cache = encode_cache or EncodeCache()
        self._pools_by_name = {np_.name: np_ for np_ in self.node_pools}
        self._types_by_pool = {
            pool: {it.name: it for it in its}
            for pool, its in instance_types.items()
        }

    def _dispatch(self, request: bytes) -> Optional[bytes]:
        """The raw RPC with one bounded retry on retriable status codes;
        None when the sidecar is out (callers degrade in-process)."""
        # propagate trace context so the sidecar's spans stitch into the
        # caller's trace (obs/trace.py; SolverService._handle reads these),
        # and the tenant id so the sidecar routes to the right control plane
        pairs = []
        cur = obs.current_span()
        if cur is not None:
            pairs.append((obs.TRACE_ID_METADATA_KEY, cur.trace_id))
            pairs.append((obs.PARENT_ID_METADATA_KEY, cur.span_id))
        if self.tenant:
            pairs.append((TENANT_ID_METADATA_KEY, self.tenant))
        metadata = tuple(pairs) or None
        last_backpressure: Optional[grpc.RpcError] = None
        for attempt in range(2):
            try:
                # chaos seam: plans raise InjectedRpcError here to model
                # channel outages, deadline blowouts, and admission rejects
                faults.hit(faults.REMOTE_SOLVE, attempt=attempt)
                with obs.span("remote.dispatch", attempt=attempt):
                    return self._solve(
                        request, timeout=self.timeout, metadata=metadata
                    )
            except grpc.RpcError as exc:
                code = _status_name(exc)
                if code in BACKPRESSURE_CODES:
                    # admission rejection: retriable once (the bucket
                    # refills), but NEVER the in-process fallback
                    last_backpressure = exc
                    _LOG.warning(
                        "solver sidecar rejected tenant %r (attempt %d)",
                        self.tenant or DEFAULT_TENANT, attempt + 1,
                    )
                    continue
                last_backpressure = None
                if code not in RETRIABLE_CODES:
                    raise
                _LOG.warning(
                    "solver sidecar dispatch failed with %s (attempt %d)",
                    code, attempt + 1,
                )
        if last_backpressure is not None:
            raise SolverBackpressure(
                self.tenant or DEFAULT_TENANT, str(last_backpressure)
            ) from last_backpressure
        return None

    def _solve_in_process(self, pods: Sequence[Pod]) -> Results:
        """Degraded rung: the sidecar is unreachable, so run the identical
        solve locally from the parts the request was built from."""
        self.fallback_solves += 1
        with obs.span("remote.fallback", pods=len(pods)):
            return self._build_and_solve(pods)

    def _build_and_solve(self, pods: Sequence[Pod]) -> Results:
        solver = build_solver(
            pods,
            self.node_pools,
            self.instance_types,
            self.daemonset_pods,
            self.state_nodes,
            self.volume_objects,
            self.reserved_capacity_enabled,
            config=self.config,
            encode_cache=self._fallback_cache,
            # live controller objects: never bump their resource versions
            copy_objects=True,
        )
        return solver.solve(pods)

    def solve(self, pods: Sequence[Pod]) -> Results:
        with obs.span("remote.solve", pods=len(pods)):
            return self._solve_remote(pods)

    def _solve_remote(self, pods: Sequence[Pod]) -> Results:
        from ..scheduling.template import NodeClaimTemplate

        request = wire.encode_solve_request(
            pods,
            self.node_pools,
            self.instance_types,
            self.daemonset_pods,
            solver_options={
                "reserved_capacity_enabled": self.reserved_capacity_enabled
            },
            state_nodes=self.state_nodes,
            volume_objects=self.volume_objects,
        )
        raw = self._dispatch(request)
        if raw is None:
            return self._solve_in_process(pods)
        response = wire.decode_solve_response(raw)
        if self.state_nodes and response.get("state_nodes_packed") != len(
            self.state_nodes
        ):
            # a sidecar speaking an older protocol drops unknown request
            # keys: solving against an empty cluster view would silently
            # over-provision — fail as loudly as catalog skew does below
            raise RuntimeError(
                f"sent {len(self.state_nodes)} state nodes but the solver "
                f"acknowledged {response.get('state_nodes_packed', 0)} — "
                "controller/sidecar wire protocol versions are out of sync"
            )
        pods_by_uid = {p.uid: p for p in pods}
        claims: List[DecodedClaim] = []
        for c in response["claims"]:
            pool = self._pools_by_name.get(c["pool"])
            if pool is None:
                raise RuntimeError(
                    f"solver returned a claim for unknown nodepool "
                    f"{c['pool']!r} — controller/sidecar nodepool catalogs "
                    "are out of sync"
                )
            by_name = self._types_by_pool.get(c["pool"], {})
            missing = [n for n in c["instance_types"] if n not in by_name]
            if missing:
                # catalog skew between controller and sidecar must be loud:
                # a claim without options would persist unlaunchable
                raise RuntimeError(
                    f"solver returned unknown instance types for pool "
                    f"{c['pool']!r}: {missing[:5]} — controller/sidecar "
                    "instance-type catalogs are out of sync"
                )
            claims.append(
                DecodedClaim(
                    template=NodeClaimTemplate(pool),
                    pods=[pods_by_uid[uid] for uid in c["pod_uids"]],
                    instance_type_options=[by_name[n] for n in c["instance_types"]],
                    requirements=c["requirements"],
                )
            )
        existing = [
            RemoteExistingNode(
                name=e["name"],
                pods=[pods_by_uid[u] for u in e["pod_uids"]],
            )
            for e in response.get("existing", [])
        ]
        return Results(
            new_node_claims=claims,
            existing_nodes=existing,
            pod_errors=dict(response["pod_errors"]),
        )

    def close(self) -> None:
        self._channel.close()


__all__ = [
    "SOLVE_METHOD", "SolverService", "TenantService", "serve",
    "RemoteSolver", "RemoteExistingNode", "InjectedRpcError",
    "SolverBackpressure", "build_solver",
    "RETRIABLE_CODES", "BACKPRESSURE_CODES", "TENANT_ID_METADATA_KEY",
]


def main(argv: Optional[Sequence[str]] = None) -> None:
    """Standalone sidecar binary: `python -m karpenter_tpu.solver.service`
    — the deployable form of the controller/solver process split
    (deploy/docker-compose.yml runs it next to the controller the way the
    reference splits controller and cloud-provider concerns)."""
    import argparse
    import signal
    import threading

    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument(
        "--listen", default="0.0.0.0:50099",
        help="host:port for the gRPC solve endpoint",
    )
    parser.add_argument("--max-workers", type=int, default=4)
    parser.add_argument(
        "--max-tenants", type=int, default=16,
        help="admission-control bound on distinct tenant ids",
    )
    parser.add_argument(
        "--batch-window", type=float, default=0.0,
        help="cross-tenant microbatch formation window in seconds "
        "(0 disables batching)",
    )
    args = parser.parse_args(argv)
    server = serve(
        address=args.listen,
        max_workers=args.max_workers,
        registry=TenantRegistry(max_tenants=args.max_tenants),
        batch_window=args.batch_window,
    )
    print(f"solver sidecar listening on {args.listen}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=5).wait()


if __name__ == "__main__":
    main()
