"""Solver sidecar: gRPC service exposing batch Solve over the wire codec.

The TPU-native deployment splits the control plane from the solver: the
controller process (Go-shaped, level-triggered) ships snapshots over DCN to
this sidecar, which runs the fused feasibility/packing kernels on its local
TPU slice and returns packed claims (SURVEY.md §5, BASELINE.json
north-star). In-process callers keep using TpuSolver directly; RemoteSolver
is the same seam behind a channel.

The service is defined with grpc generic handlers over the msgpack codec in
wire.py — no generated stubs, one method:

    /karpenter_tpu.solver.v1.Solver/Solve   (unary-unary, bytes in/out)
"""

from __future__ import annotations

from concurrent import futures
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import grpc

from ..api.objects import NodePool, Pod
from ..cloudprovider import types as cp
from ..kube import Client, TestClock
from ..scheduling.scheduler import Results
from ..scheduling.topology import Topology
from . import wire
from .driver import DecodedClaim, EncodeCache, SolverConfig, TpuSolver

# one process-wide cache: the sidecar serves many solves of one catalog
_SIDECAR_ENCODE_CACHE = EncodeCache()

SERVICE_NAME = "karpenter_tpu.solver.v1.Solver"
SOLVE_METHOD = f"/{SERVICE_NAME}/Solve"


def _solve_snapshot(data: bytes, config: Optional[SolverConfig]) -> bytes:
    snap = wire.decode_solve_request(data)
    pods: List[Pod] = snap["pods"]
    node_pools: List[NodePool] = snap["node_pools"]
    instance_types = snap["instance_types"]
    daemonset_pods = snap["daemonset_pods"]
    state_nodes = snap["state_nodes"]
    # rebuild the controller's cluster view: state nodes pack FIRST
    # (scheduler.go:357-425), their bound pods feed the topology priors and
    # inverse anti-affinity gates, and PVC/PV/StorageClass objects let the
    # VolumeResolver answer identically — so the scratch client holds them
    scratch = Client(TestClock())
    for sn in state_nodes:
        if sn.node is not None:
            scratch.create(sn.node)
        for p in sn.pods:
            scratch.create(p)
    for vo in snap["volume_objects"] or ():
        scratch.create(vo)
    topology = Topology(scratch, state_nodes, node_pools, instance_types, pods)
    from ..scheduling.volumeusage import VolumeResolver

    # clients predating the volume protocol (volume_objects is None, not
    # []) never ship PVC/PV objects; resolving against the empty scratch
    # store would fail every PVC-bearing pod, so keep the old no-resolver
    # behavior for them
    resolver = (
        VolumeResolver(scratch) if snap["volume_objects"] is not None else None
    )
    solver = TpuSolver(
        node_pools,
        instance_types,
        topology,
        state_nodes=state_nodes,
        daemonset_pods=daemonset_pods,
        volume_resolver=resolver,
        config=config,
        # catalog encode amortizes across requests; the cache's lock
        # serializes the host-side encode under the gRPC thread pool
        encode_cache=_SIDECAR_ENCODE_CACHE,
        # behavior knobs travel in the snapshot so controller and sidecar
        # can never disagree on gate-dependent packing
        reserved_capacity_enabled=bool(
            snap["solver_options"].get("reserved_capacity_enabled", False)
        ),
    )
    results = solver.solve(pods)
    return wire.encode_solve_response(
        results, state_nodes_packed=len(state_nodes)
    )


class SolverService(grpc.GenericRpcHandler):
    """Generic unary handler for the Solve method."""

    def __init__(self, config: Optional[SolverConfig] = None):
        self.config = config

    def service(self, handler_call_details):
        if handler_call_details.method != SOLVE_METHOD:
            return None
        return grpc.unary_unary_rpc_method_handler(
            lambda request, context: _solve_snapshot(request, self.config),
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )


def serve(
    address: str = "127.0.0.1:0",
    config: Optional[SolverConfig] = None,
    max_workers: int = 4,
) -> "grpc.Server":
    """Start a solver sidecar; returns the started server. The bound port is
    available via server._bound_port (set here) when address ends in :0."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((SolverService(config),))
    port = server.add_insecure_port(address)
    server._bound_port = port
    server.start()
    return server


@dataclass
class RemoteExistingNode:
    """Existing-node placement reassembled from the sidecar's response.
    Duck-types the surface Results consumers read (provisioning.py:262:
    .name for nomination, .pods for events)."""

    name: str
    pods: List[Pod]


class RemoteSolver:
    """Client-side seam: same solve(pods) contract as TpuSolver, but the
    packing runs in the sidecar. Claims come back as instance-type names and
    pod uids and are reassembled against the local objects. Pass the
    cluster's StateNodes (``state_nodes``) so the sidecar packs existing
    capacity first exactly like the in-process solve — without them a
    non-empty cluster over-provisions every batch. Pass the PVC/PV/
    StorageClass objects pending pods reference (``volume_objects``) so
    CSI attach-limit checks match too."""

    def __init__(
        self,
        target: str,
        node_pools: Sequence[NodePool],
        instance_types: Dict[str, List[cp.InstanceType]],
        daemonset_pods: Sequence[Pod] = (),
        channel: Optional["grpc.Channel"] = None,
        timeout: float = 30.0,
        reserved_capacity_enabled: bool = False,
        state_nodes: Sequence = (),
        volume_objects: Sequence = (),
    ):
        self._channel = channel or grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(SOLVE_METHOD)
        self.timeout = timeout
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.node_pools = list(node_pools)
        self.instance_types = instance_types
        self.daemonset_pods = list(daemonset_pods)
        self.state_nodes = list(state_nodes)
        self.volume_objects = list(volume_objects)
        self._pools_by_name = {np_.name: np_ for np_ in self.node_pools}
        self._types_by_pool = {
            pool: {it.name: it for it in its}
            for pool, its in instance_types.items()
        }

    def solve(self, pods: Sequence[Pod]) -> Results:
        from ..scheduling.template import NodeClaimTemplate

        request = wire.encode_solve_request(
            pods,
            self.node_pools,
            self.instance_types,
            self.daemonset_pods,
            solver_options={
                "reserved_capacity_enabled": self.reserved_capacity_enabled
            },
            state_nodes=self.state_nodes,
            volume_objects=self.volume_objects,
        )
        response = wire.decode_solve_response(
            self._solve(request, timeout=self.timeout)
        )
        if self.state_nodes and response.get("state_nodes_packed") != len(
            self.state_nodes
        ):
            # a sidecar speaking an older protocol drops unknown request
            # keys: solving against an empty cluster view would silently
            # over-provision — fail as loudly as catalog skew does below
            raise RuntimeError(
                f"sent {len(self.state_nodes)} state nodes but the solver "
                f"acknowledged {response.get('state_nodes_packed', 0)} — "
                "controller/sidecar wire protocol versions are out of sync"
            )
        pods_by_uid = {p.uid: p for p in pods}
        claims: List[DecodedClaim] = []
        for c in response["claims"]:
            pool = self._pools_by_name.get(c["pool"])
            if pool is None:
                raise RuntimeError(
                    f"solver returned a claim for unknown nodepool "
                    f"{c['pool']!r} — controller/sidecar nodepool catalogs "
                    "are out of sync"
                )
            by_name = self._types_by_pool.get(c["pool"], {})
            missing = [n for n in c["instance_types"] if n not in by_name]
            if missing:
                # catalog skew between controller and sidecar must be loud:
                # a claim without options would persist unlaunchable
                raise RuntimeError(
                    f"solver returned unknown instance types for pool "
                    f"{c['pool']!r}: {missing[:5]} — controller/sidecar "
                    "instance-type catalogs are out of sync"
                )
            claims.append(
                DecodedClaim(
                    template=NodeClaimTemplate(pool),
                    pods=[pods_by_uid[uid] for uid in c["pod_uids"]],
                    instance_type_options=[by_name[n] for n in c["instance_types"]],
                    requirements=c["requirements"],
                )
            )
        existing = [
            RemoteExistingNode(
                name=e["name"],
                pods=[pods_by_uid[u] for u in e["pod_uids"]],
            )
            for e in response.get("existing", [])
        ]
        return Results(
            new_node_claims=claims,
            existing_nodes=existing,
            pod_errors=dict(response["pod_errors"]),
        )

    def close(self) -> None:
        self._channel.close()


__all__ = [
    "SOLVE_METHOD", "SolverService", "serve", "RemoteSolver",
    "RemoteExistingNode",
]


def main(argv: Optional[Sequence[str]] = None) -> None:
    """Standalone sidecar binary: `python -m karpenter_tpu.solver.service`
    — the deployable form of the controller/solver process split
    (deploy/docker-compose.yml runs it next to the controller the way the
    reference splits controller and cloud-provider concerns)."""
    import argparse
    import signal
    import threading

    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument(
        "--listen", default="0.0.0.0:50099",
        help="host:port for the gRPC solve endpoint",
    )
    parser.add_argument("--max-workers", type=int, default=4)
    args = parser.parse_args(argv)
    server = serve(address=args.listen, max_workers=args.max_workers)
    print(f"solver sidecar listening on {args.listen}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=5).wait()


if __name__ == "__main__":
    main()
