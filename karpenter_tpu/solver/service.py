"""Solver sidecar: gRPC service exposing batch Solve over the wire codec.

The TPU-native deployment splits the control plane from the solver: the
controller process (Go-shaped, level-triggered) ships snapshots over DCN to
this sidecar, which runs the fused feasibility/packing kernels on its local
TPU slice and returns packed claims (SURVEY.md §5, BASELINE.json
north-star). In-process callers keep using TpuSolver directly; RemoteSolver
is the same seam behind a channel.

The service is defined with grpc generic handlers over the msgpack codec in
wire.py — no generated stubs, one method:

    /karpenter_tpu.solver.v1.Solver/Solve   (unary-unary, bytes in/out)
"""

from __future__ import annotations

import copy
import logging
from concurrent import futures
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import grpc

from .. import faults, obs
from ..api.objects import NodePool, Pod
from ..cloudprovider import types as cp
from ..kube import Client, TestClock
from ..scheduling.scheduler import Results
from ..scheduling.topology import Topology
from . import wire
from .driver import DecodedClaim, EncodeCache, SolverConfig, TpuSolver

_LOG = logging.getLogger("karpenter_tpu.solver.service")

# one process-wide cache: the sidecar serves many solves of one catalog
_SIDECAR_ENCODE_CACHE = EncodeCache()

SERVICE_NAME = "karpenter_tpu.solver.v1.Solver"
SOLVE_METHOD = f"/{SERVICE_NAME}/Solve"

# gRPC status codes that mean "the sidecar may answer if asked again" —
# RemoteSolver retries these once, then degrades to an in-process solve
RETRIABLE_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED")


class InjectedRpcError(grpc.RpcError):
    """Fault-injection stand-in for a channel-level RPC failure, carrying
    a status code the way a real ``grpc.Call`` error does. Fault plans
    raise this at the ``faults.REMOTE_SOLVE`` site."""

    def __init__(self, code: "grpc.StatusCode"):
        super().__init__(f"injected rpc failure: {code}")
        self._code = code

    def code(self):
        return self._code


def _status_name(exc: "grpc.RpcError") -> str:
    """The status-code name of an RpcError ("UNAVAILABLE", ...), tolerant
    of both real channel errors and injected ones."""
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            code = code()
        except Exception:
            return ""
    return getattr(code, "name", str(code) if code is not None else "")


def build_solver(
    pods: Sequence[Pod],
    node_pools: Sequence[NodePool],
    instance_types,
    daemonset_pods: Sequence[Pod],
    state_nodes: Sequence,
    volume_objects,
    reserved_capacity_enabled: bool,
    config: Optional[SolverConfig] = None,
    encode_cache: Optional[EncodeCache] = None,
    copy_objects: bool = False,
) -> TpuSolver:
    """The one recipe for a solver over a shipped cluster view — used by
    the sidecar for every request and by RemoteSolver's in-process
    fallback, so the two can never pack differently.

    Rebuilds the controller's cluster view: state nodes pack FIRST
    (scheduler.go:357-425), their bound pods feed the topology priors and
    inverse anti-affinity gates, and PVC/PV/StorageClass objects let the
    VolumeResolver answer identically — so the scratch client holds them.
    ``copy_objects`` deep-copies objects into the scratch store (the
    fallback path feeds LIVE controller objects, and the scratch create
    must not bump their resource versions). The scratch store is plain
    memory, not an apiserver — store-chaos plans are exempted so an
    injected store outage can't crash the fallback built to survive it."""
    scratch = Client(TestClock(), fault_injection=False)

    def _add(obj):
        scratch.create(copy.deepcopy(obj) if copy_objects else obj)

    for sn in state_nodes:
        if sn.node is not None:
            _add(sn.node)
        for p in sn.pods:
            _add(p)
    for vo in volume_objects or ():
        _add(vo)
    topology = Topology(scratch, state_nodes, node_pools, instance_types, pods)
    from ..scheduling.volumeusage import VolumeResolver

    # clients predating the volume protocol (volume_objects is None, not
    # []) never ship PVC/PV objects; resolving against the empty scratch
    # store would fail every PVC-bearing pod, so keep the old no-resolver
    # behavior for them
    resolver = VolumeResolver(scratch) if volume_objects is not None else None
    return TpuSolver(
        node_pools,
        instance_types,
        topology,
        state_nodes=state_nodes,
        daemonset_pods=daemonset_pods,
        volume_resolver=resolver,
        config=config,
        # catalog encode amortizes across requests; the cache's lock
        # serializes the host-side encode under the gRPC thread pool
        encode_cache=encode_cache,
        reserved_capacity_enabled=reserved_capacity_enabled,
    )


def _solve_snapshot(data: bytes, config: Optional[SolverConfig]) -> bytes:
    return _solve_decoded(wire.decode_solve_request(data), config)


def _solve_decoded(snap: dict, config: Optional[SolverConfig]) -> bytes:
    pods: List[Pod] = snap["pods"]
    state_nodes = snap["state_nodes"]
    solver = build_solver(
        pods,
        snap["node_pools"],
        snap["instance_types"],
        snap["daemonset_pods"],
        state_nodes,
        snap["volume_objects"],
        # behavior knobs travel in the snapshot so controller and sidecar
        # can never disagree on gate-dependent packing
        bool(snap["solver_options"].get("reserved_capacity_enabled", False)),
        config=config,
        encode_cache=_SIDECAR_ENCODE_CACHE,
    )
    results = solver.solve(pods)
    return wire.encode_solve_response(
        results, state_nodes_packed=len(state_nodes)
    )


class SolverService(grpc.GenericRpcHandler):
    """Generic unary handler for the Solve method.

    Exceptions map to proper gRPC status codes instead of crashing the
    stream through the generic handler: a request the codec cannot decode
    is the CLIENT's bug (INVALID_ARGUMENT — retrying it can never help),
    while a solve that raises is the sidecar's (INTERNAL, retriable by
    policy). RemoteSolver keys its retry/fallback ladder off these."""

    def __init__(self, config: Optional[SolverConfig] = None):
        self.config = config

    def _handle(self, request, context):
        # trace context rides the gRPC metadata (obs/trace.py): when the
        # caller sent one, the sidecar's spans adopt the caller's trace id
        # and parent on the caller's span — so the stitched trace shows
        # the RemoteSolver hop as one tree across both processes
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        with obs.span(
            "sidecar.solve",
            trace_id=md.get(obs.TRACE_ID_METADATA_KEY),
            parent_id=md.get(obs.PARENT_ID_METADATA_KEY),
        ):
            try:
                snap = wire.decode_solve_request(request)
            except Exception as exc:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"malformed solve request: {type(exc).__name__}: {exc}",
                )
            try:
                return _solve_decoded(snap, self.config)
            except Exception as exc:
                _LOG.exception("solve failed")
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"solve failed: {type(exc).__name__}: {exc}",
                )

    def service(self, handler_call_details):
        if handler_call_details.method != SOLVE_METHOD:
            return None
        return grpc.unary_unary_rpc_method_handler(
            self._handle,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )


def serve(
    address: str = "127.0.0.1:0",
    config: Optional[SolverConfig] = None,
    max_workers: int = 4,
) -> "grpc.Server":
    """Start a solver sidecar; returns the started server. The bound port is
    available via server._bound_port (set here) when address ends in :0."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((SolverService(config),))
    port = server.add_insecure_port(address)
    server._bound_port = port
    server.start()
    return server


@dataclass
class RemoteExistingNode:
    """Existing-node placement reassembled from the sidecar's response.
    Duck-types the surface Results consumers read (provisioning.py:262:
    .name for nomination, .pods for events)."""

    name: str
    pods: List[Pod]


class RemoteSolver:
    """Client-side seam: same solve(pods) contract as TpuSolver, but the
    packing runs in the sidecar. Claims come back as instance-type names and
    pod uids and are reassembled against the local objects. Pass the
    cluster's StateNodes (``state_nodes``) so the sidecar packs existing
    capacity first exactly like the in-process solve — without them a
    non-empty cluster over-provisions every batch. Pass the PVC/PV/
    StorageClass objects pending pods reference (``volume_objects``) so
    CSI attach-limit checks match too.

    Every dispatch carries a deadline (``SolverConfig.solve_deadline``
    when a config is given, else ``timeout``). UNAVAILABLE and
    DEADLINE_EXCEEDED get exactly one retry; if the sidecar still doesn't
    answer, the solve degrades to an IN-PROCESS run over the same shipped
    cluster view (``build_solver`` — the sidecar's own recipe), so a gRPC
    outage slows a reconcile instead of failing it. Any other status
    (catalog skew, malformed request) propagates: retrying those lies."""

    def __init__(
        self,
        target: str,
        node_pools: Sequence[NodePool],
        instance_types: Dict[str, List[cp.InstanceType]],
        daemonset_pods: Sequence[Pod] = (),
        channel: Optional["grpc.Channel"] = None,
        timeout: float = 30.0,
        reserved_capacity_enabled: bool = False,
        state_nodes: Sequence = (),
        volume_objects: Sequence = (),
        config: Optional[SolverConfig] = None,
        encode_cache: Optional[EncodeCache] = None,
    ):
        self._channel = channel or grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(SOLVE_METHOD)
        self.config = config
        self.timeout = (
            config.solve_deadline if config is not None else timeout
        )
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.node_pools = list(node_pools)
        self.instance_types = instance_types
        self.daemonset_pods = list(daemonset_pods)
        self.state_nodes = list(state_nodes)
        self.volume_objects = list(volume_objects)
        self.fallback_solves = 0  # telemetry: in-process degradations
        # a sidecar outage makes EVERY reconcile fall back — amortize the
        # host-side catalog encode across those solves. Callers that build
        # a RemoteSolver per cycle (the Provisioner does) must pass their
        # long-lived cache; the per-instance default still de-dups repeat
        # solves on one instance
        self._fallback_cache = encode_cache or EncodeCache()
        self._pools_by_name = {np_.name: np_ for np_ in self.node_pools}
        self._types_by_pool = {
            pool: {it.name: it for it in its}
            for pool, its in instance_types.items()
        }

    def _dispatch(self, request: bytes) -> Optional[bytes]:
        """The raw RPC with one bounded retry on retriable status codes;
        None when the sidecar is out (callers degrade in-process)."""
        # propagate trace context so the sidecar's spans stitch into the
        # caller's trace (obs/trace.py; SolverService._handle reads these)
        metadata = None
        cur = obs.current_span()
        if cur is not None:
            metadata = (
                (obs.TRACE_ID_METADATA_KEY, cur.trace_id),
                (obs.PARENT_ID_METADATA_KEY, cur.span_id),
            )
        for attempt in range(2):
            try:
                # chaos seam: plans raise InjectedRpcError here to model
                # channel outages and deadline blowouts
                faults.hit(faults.REMOTE_SOLVE, attempt=attempt)
                with obs.span("remote.dispatch", attempt=attempt):
                    return self._solve(
                        request, timeout=self.timeout, metadata=metadata
                    )
            except grpc.RpcError as exc:
                code = _status_name(exc)
                if code not in RETRIABLE_CODES:
                    raise
                _LOG.warning(
                    "solver sidecar dispatch failed with %s (attempt %d)",
                    code, attempt + 1,
                )
        return None

    def _solve_in_process(self, pods: Sequence[Pod]) -> Results:
        """Degraded rung: the sidecar is unreachable, so run the identical
        solve locally from the parts the request was built from."""
        self.fallback_solves += 1
        with obs.span("remote.fallback", pods=len(pods)):
            return self._build_and_solve(pods)

    def _build_and_solve(self, pods: Sequence[Pod]) -> Results:
        solver = build_solver(
            pods,
            self.node_pools,
            self.instance_types,
            self.daemonset_pods,
            self.state_nodes,
            self.volume_objects,
            self.reserved_capacity_enabled,
            config=self.config,
            encode_cache=self._fallback_cache,
            # live controller objects: never bump their resource versions
            copy_objects=True,
        )
        return solver.solve(pods)

    def solve(self, pods: Sequence[Pod]) -> Results:
        with obs.span("remote.solve", pods=len(pods)):
            return self._solve_remote(pods)

    def _solve_remote(self, pods: Sequence[Pod]) -> Results:
        from ..scheduling.template import NodeClaimTemplate

        request = wire.encode_solve_request(
            pods,
            self.node_pools,
            self.instance_types,
            self.daemonset_pods,
            solver_options={
                "reserved_capacity_enabled": self.reserved_capacity_enabled
            },
            state_nodes=self.state_nodes,
            volume_objects=self.volume_objects,
        )
        raw = self._dispatch(request)
        if raw is None:
            return self._solve_in_process(pods)
        response = wire.decode_solve_response(raw)
        if self.state_nodes and response.get("state_nodes_packed") != len(
            self.state_nodes
        ):
            # a sidecar speaking an older protocol drops unknown request
            # keys: solving against an empty cluster view would silently
            # over-provision — fail as loudly as catalog skew does below
            raise RuntimeError(
                f"sent {len(self.state_nodes)} state nodes but the solver "
                f"acknowledged {response.get('state_nodes_packed', 0)} — "
                "controller/sidecar wire protocol versions are out of sync"
            )
        pods_by_uid = {p.uid: p for p in pods}
        claims: List[DecodedClaim] = []
        for c in response["claims"]:
            pool = self._pools_by_name.get(c["pool"])
            if pool is None:
                raise RuntimeError(
                    f"solver returned a claim for unknown nodepool "
                    f"{c['pool']!r} — controller/sidecar nodepool catalogs "
                    "are out of sync"
                )
            by_name = self._types_by_pool.get(c["pool"], {})
            missing = [n for n in c["instance_types"] if n not in by_name]
            if missing:
                # catalog skew between controller and sidecar must be loud:
                # a claim without options would persist unlaunchable
                raise RuntimeError(
                    f"solver returned unknown instance types for pool "
                    f"{c['pool']!r}: {missing[:5]} — controller/sidecar "
                    "instance-type catalogs are out of sync"
                )
            claims.append(
                DecodedClaim(
                    template=NodeClaimTemplate(pool),
                    pods=[pods_by_uid[uid] for uid in c["pod_uids"]],
                    instance_type_options=[by_name[n] for n in c["instance_types"]],
                    requirements=c["requirements"],
                )
            )
        existing = [
            RemoteExistingNode(
                name=e["name"],
                pods=[pods_by_uid[u] for u in e["pod_uids"]],
            )
            for e in response.get("existing", [])
        ]
        return Results(
            new_node_claims=claims,
            existing_nodes=existing,
            pod_errors=dict(response["pod_errors"]),
        )

    def close(self) -> None:
        self._channel.close()


__all__ = [
    "SOLVE_METHOD", "SolverService", "serve", "RemoteSolver",
    "RemoteExistingNode", "InjectedRpcError", "build_solver",
    "RETRIABLE_CODES",
]


def main(argv: Optional[Sequence[str]] = None) -> None:
    """Standalone sidecar binary: `python -m karpenter_tpu.solver.service`
    — the deployable form of the controller/solver process split
    (deploy/docker-compose.yml runs it next to the controller the way the
    reference splits controller and cloud-provider concerns)."""
    import argparse
    import signal
    import threading

    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument(
        "--listen", default="0.0.0.0:50099",
        help="host:port for the gRPC solve endpoint",
    )
    parser.add_argument("--max-workers", type=int, default=4)
    args = parser.parse_args(argv)
    server = serve(address=args.listen, max_workers=args.max_workers)
    print(f"solver sidecar listening on {args.listen}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=5).wait()


if __name__ == "__main__":
    main()
