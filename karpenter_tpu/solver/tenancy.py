"""Multi-tenant control planes over one resident solver kernel.

The north star serves many clusters from one accelerator: each control
plane (tenant) ships snapshots to the same sidecar, but NONE of the
warm state that makes solving fast — the catalog-fingerprinted
``EncodeCache``, its ``ClusterEncoding`` row banks, the device-resident
argument buffers — may be shared between tenants. Sharing it would make
one tenant's corrupt delta another tenant's full re-encode, and one
tenant's quarantine everyone's oracle fallback. This module holds the
isolation machinery:

- ``TenantState``: one tenant's warm state (its own ``EncodeCache`` →
  ``ClusterEncoding`` + ``DeviceResidentArgs``), its OWN
  ``SolverHealth`` degradation ladder (faults/breaker.py) publishing
  per-tenant-labeled metrics, a token-bucket rate limiter and a bounded
  in-flight queue.
- ``TenantRegistry``: the tenant table plus global admission control —
  token buckets per tenant, a priority-tiered share of the global
  in-flight pool (premium may fill it, standard three quarters, batch
  half — "Priority Matters"-style tiering, lowest tier shed first
  under contention), and a hard ``max_tenants`` bound that is ALSO the
  cardinality bound for every ``tenant``-labeled metric series.
- ``CrossTenantBatcher``: leader/follower microbatching of same-shape
  solves from different tenants onto the existing scenario axis (one
  vmapped dispatch behind the one blessed drain); a declined batch
  falls back to per-tenant solo solves, never to a wrong answer.

Typed errors map to the sidecar's gRPC contract: ``AdmissionError`` →
RESOURCE_EXHAUSTED ("back off and retry"), ``DeadlineOverrunError`` →
DEADLINE_EXCEEDED ("fall back in-process") — solver/service.py wires
both, and RemoteSolver distinguishes them on the client side.

Lock discipline (PARITY.md "Tenant isolation contract"): ``TenantState``
and ``TenantRegistry`` each own one ``threading.Lock`` guarding all of
their mutable attributes; the two are never held at once (registry
methods complete their critical section before calling into a tenant),
so no new cross-module lock order is introduced and the GRD13xx/ATM14xx
sanctioned-site inventory is unchanged. ``CrossTenantBatcher``
serializes on a single ``threading.Condition``. Clock reads ride
injected clocks only (``obs.PerfClock`` by default) — never raw
``time.*`` (CLK10xx).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from .. import faults, obs
from ..faults.breaker import SolverHealth
from ..metrics import Counter, Gauge

DEFAULT_TENANT = "default"

# -- QoS tiers ---------------------------------------------------------------

TIER_PREMIUM = "premium"
TIER_STANDARD = "standard"
TIER_BATCH = "batch"

# fraction of the global in-flight pool a tier may fill: under
# contention the batch tier is shed first, then standard — premium is
# rejected only when the pool itself is full
_TIER_HEADROOM = {
    TIER_PREMIUM: 1.0,
    TIER_STANDARD: 0.75,
    TIER_BATCH: 0.5,
}


@dataclass(frozen=True)
class TenantQoS:
    """Per-tenant admission and latency budget.

    ``rate``/``burst`` parameterize the token bucket (solves per second,
    bucket depth); ``max_queue`` bounds the tenant's in-flight solves
    (the "bounded per-tenant queue" — anything beyond it is rejected,
    not queued, so one tenant's backlog cannot occupy the gRPC thread
    pool); ``solve_deadline`` is the per-tenant latency budget measured
    on the registry clock — an overrun maps to DEADLINE_EXCEEDED so the
    client falls back in-process instead of backing off."""

    tier: str = TIER_STANDARD
    rate: float = 100.0
    burst: float = 128.0
    max_queue: int = 32
    solve_deadline: float = 600.0


TIER_DEFAULTS: Dict[str, TenantQoS] = {
    TIER_PREMIUM: TenantQoS(
        tier=TIER_PREMIUM, rate=200.0, burst=256.0, max_queue=64
    ),
    TIER_STANDARD: TenantQoS(tier=TIER_STANDARD),
    TIER_BATCH: TenantQoS(
        tier=TIER_BATCH, rate=20.0, burst=32.0, max_queue=8
    ),
}


# -- typed error contract ----------------------------------------------------


class AdmissionError(RuntimeError):
    """Admission control rejected the solve BEFORE any work ran — the
    caller should back off and retry (gRPC RESOURCE_EXHAUSTED; the
    client must NOT fall back in-process, the cluster view it would
    solve is the same one the service just refused to spend quota on).
    ``reason`` is one of "rate-limited" | "queue-full" | "tier-shed" |
    "tenant-capacity"."""

    def __init__(self, reason: str, tenant: str):
        super().__init__(f"tenant {tenant!r} admission rejected: {reason}")
        self.reason = reason
        self.tenant = tenant


class DeadlineOverrunError(RuntimeError):
    """The solve ran but blew the tenant's latency budget — the answer
    is stale by contract (gRPC DEADLINE_EXCEEDED; the client's retry/
    fallback ladder treats it like a slow sidecar and solves
    in-process)."""

    def __init__(self, tenant: str, elapsed: float, deadline: float):
        super().__init__(
            f"tenant {tenant!r} solve took {elapsed:.3f}s "
            f"(deadline {deadline:.3f}s)"
        )
        self.tenant = tenant
        self.elapsed = elapsed
        self.deadline = deadline


# -- per-tenant metrics ------------------------------------------------------
# Cardinality contract: every ``tenant`` label below is bounded by
# TenantRegistry.max_tenants (default 16) — the registry refuses to mint
# an N+1st tenant, so the label can never carry unbounded identity.
# Capacity rejections happen BEFORE a tenant exists and use the fixed
# label "(capacity)" so a rogue client spraying fresh tenant ids cannot
# blow up the series map. Pinned by tests/test_tenants.py.

TENANT_SOLVES = Counter(
    "solver_tenant_solves_total",
    "Committed solves per tenant through the multi-tenant service",
)
TENANT_REJECTIONS = Counter(
    "solver_tenant_rejections_total",
    "Admission rejections per tenant and reason "
    "(rate-limited | queue-full | tier-shed; tenant-capacity rejections "
    "carry the fixed tenant label '(capacity)')",
)
TENANT_DEADLINE_OVERRUNS = Counter(
    "solver_tenant_deadline_overruns_total",
    "Solves that ran but blew the tenant's latency budget",
)
TENANT_INFLIGHT = Gauge(
    "solver_tenant_inflight",
    "In-flight solves per tenant (bounded by its QoS max_queue)",
)
TENANT_BATCHES = Counter(
    "solver_tenant_batches_total",
    "Cross-tenant microbatch outcomes (outcome=batched|declined)",
)


class TenantState:
    """One tenant's isolation domain: warm state, ladder, quota.

    The ``EncodeCache`` (and through it the ``ClusterEncoding`` row
    banks and ``DeviceResidentArgs`` buffers) is constructed here, owned
    here, and never handed to another tenant — a corrupt-delta shed or a
    catalog reset stays inside this object. The ``SolverHealth`` ladder
    is equally private: this tenant quarantining its kernel rung cannot
    gate anyone else's batched path.

    All mutable admission state (``_tokens``, ``_inflight``, the stat
    counters) is guarded by ``self._lock``; the metric emissions happen
    after release so the per-metric locks never nest inside it."""

    def __init__(
        self,
        tenant_id: str,
        qos: TenantQoS,
        clock,
        recorder=None,
    ):
        from .driver import EncodeCache

        self.tenant_id = tenant_id
        self.qos = qos
        self.clock = clock
        # per-tenant warm state: the whole PR-8 object graph, one copy
        self.encode_cache = EncodeCache(owner=tenant_id)
        # per-tenant degradation ladder with per-tenant metric series
        self.health = SolverHealth(
            clock,
            recorder=recorder,
            metric_labels={"tenant": tenant_id},
        )
        self._lock = threading.Lock()
        self._tokens = float(qos.burst)
        self._last_refill = clock.now()
        self._inflight = 0
        self._admitted = 0
        self._rejected = 0
        self._solves = 0
        self._fallback_solves = 0
        self._deadline_overruns = 0

    def try_admit(self) -> Optional[str]:
        """Refill the bucket on the injected clock and take one token +
        one queue slot atomically; the rejection reason when either is
        exhausted (None = admitted)."""
        with self._lock:
            now = self.clock.now()
            self._tokens = min(
                float(self.qos.burst),
                self._tokens + (now - self._last_refill) * self.qos.rate,
            )
            self._last_refill = now
            if self._tokens < 1.0:
                self._rejected += 1
                return "rate-limited"
            if self._inflight >= self.qos.max_queue:
                self._rejected += 1
                return "queue-full"
            self._tokens -= 1.0
            self._inflight += 1
            self._admitted += 1
            inflight = self._inflight
        TENANT_INFLIGHT.set(
            float(inflight), labels={"tenant": self.tenant_id}
        )
        return None

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1
            inflight = self._inflight
        TENANT_INFLIGHT.set(
            float(inflight), labels={"tenant": self.tenant_id}
        )

    def note_solve(self, fallback_delta: int = 0) -> None:
        with self._lock:
            self._solves += 1
            self._fallback_solves += fallback_delta
        TENANT_SOLVES.inc(labels={"tenant": self.tenant_id})

    def note_deadline_overrun(self) -> None:
        with self._lock:
            self._deadline_overruns += 1
        TENANT_DEADLINE_OVERRUNS.inc(labels={"tenant": self.tenant_id})

    @property
    def fallback_solves(self) -> int:
        with self._lock:
            return self._fallback_solves

    def stats(self) -> Dict[str, object]:
        """A copied snapshot (never the guarded dicts themselves)."""
        with self._lock:
            return {
                "tenant": self.tenant_id,
                "tier": self.qos.tier,
                "admitted": self._admitted,
                "rejected": self._rejected,
                "solves": self._solves,
                "fallback_solves": self._fallback_solves,
                "deadline_overruns": self._deadline_overruns,
                "inflight": self._inflight,
                "tokens": self._tokens,
            }


class TenantRegistry:
    """The tenant table + global admission control.

    ``max_tenants`` is a hard bound (an N+1st tenant is rejected with
    reason "tenant-capacity") and doubles as the metric-cardinality
    bound for every ``tenant`` label. ``max_inflight`` is the global
    solve pool the tiers share fractionally (see ``_TIER_HEADROOM``).
    ``tiers`` maps tenant id → tier name — tier assignment is SERVICE
    configuration, never client metadata, so a tenant cannot promote
    itself across the trust boundary."""

    def __init__(
        self,
        clock=None,
        max_tenants: int = 16,
        max_inflight: int = 32,
        tiers: Optional[Dict[str, str]] = None,
        default_tier: str = TIER_STANDARD,
        qos: Optional[Dict[str, TenantQoS]] = None,
        recorder=None,
    ):
        self.clock = clock if clock is not None else obs.PerfClock()
        self.max_tenants = max_tenants
        self.max_inflight = max_inflight
        self.default_tier = default_tier
        self.recorder = recorder
        self._tier_of = dict(tiers or {})
        self._qos = dict(TIER_DEFAULTS)
        self._qos.update(qos or {})
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantState] = {}
        self._inflight_total = 0

    def qos_for(self, tenant_id: str) -> TenantQoS:
        tier = self._tier_of.get(tenant_id, self.default_tier)
        return self._qos[tier]

    def get_or_create(self, tenant_id: str) -> TenantState:
        """The tenant's state object, minted on first sight — or
        ``AdmissionError("tenant-capacity")`` at the ``max_tenants``
        bound (which is what keeps every tenant-labeled metric series
        bounded)."""
        with self._lock:
            tenant = self._tenants.get(tenant_id)
            if tenant is None:
                if len(self._tenants) >= self.max_tenants:
                    raise AdmissionError("tenant-capacity", tenant_id)
                tenant = TenantState(
                    tenant_id,
                    self.qos_for(tenant_id),
                    self.clock,
                    recorder=self.recorder,
                )
                self._tenants[tenant_id] = tenant
            return tenant

    def get(self, tenant_id: str) -> Optional[TenantState]:
        with self._lock:
            return self._tenants.get(tenant_id)

    def tenant_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def _admit_global(self, tier: str) -> Optional[str]:
        """Take one global in-flight slot within the tier's headroom
        fraction; the rejection reason when the tier's share is full."""
        headroom = _TIER_HEADROOM.get(tier, _TIER_HEADROOM[TIER_STANDARD])
        with self._lock:
            limit = max(1, int(self.max_inflight * headroom))
            if self._inflight_total >= limit:
                return "tier-shed"
            self._inflight_total += 1
            return None

    def _release_global(self) -> None:
        with self._lock:
            self._inflight_total -= 1

    def admit(self, tenant_id: str) -> "AdmissionLease":
        """Full admission: tenant-capacity → tier headroom → token
        bucket + queue bound, each atomic under its own lock, with the
        global slot compensated when the per-tenant step rejects.
        Raises ``AdmissionError``; on success returns a lease the caller
        MUST release (try/finally) when the solve completes."""
        faults.hit(faults.TENANT_ADMIT, tenant=tenant_id)
        try:
            tenant = self.get_or_create(tenant_id)
        except AdmissionError:
            # fixed label: capacity rejections precede tenant existence,
            # so the tenant id here is unbounded attacker-controlled input
            TENANT_REJECTIONS.inc(
                labels={"tenant": "(capacity)", "reason": "tenant-capacity"}
            )
            raise
        reason = self._admit_global(tenant.qos.tier)
        if reason is not None:
            TENANT_REJECTIONS.inc(
                labels={"tenant": tenant_id, "reason": reason}
            )
            raise AdmissionError(reason, tenant_id)
        reason = tenant.try_admit()
        if reason is not None:
            self._release_global()
            TENANT_REJECTIONS.inc(
                labels={"tenant": tenant_id, "reason": reason}
            )
            raise AdmissionError(reason, tenant_id)
        return AdmissionLease(self, tenant)

    def stats(self) -> List[Dict[str, object]]:
        with self._lock:
            tenants = sorted(self._tenants.values(), key=lambda t: t.tenant_id)
        return [t.stats() for t in tenants]


class AdmissionLease:
    """One admitted solve's slot pair (global + tenant), released once.
    Single-owner by contract (the admitting thread), so the released
    flag needs no lock."""

    def __init__(self, registry: TenantRegistry, tenant: TenantState):
        self.registry = registry
        self.tenant = tenant
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self.tenant.release()
        self.registry._release_global()


# -- cross-tenant microbatching ---------------------------------------------


class _BatchSlot:
    """One tenant's seat in a forming batch (leader-owned after close)."""

    def __init__(self, item):
        self.item = item
        self.result = None
        self.declined = False


class _Batch:
    def __init__(self, key):
        self.key = key
        self.slots: List[_BatchSlot] = []
        self.done = False


class CrossTenantBatcher:
    """Leader/follower microbatching of same-shape solves.

    The first arrival under a batch key becomes the leader: it waits up
    to ``window`` seconds (on the injected duration clock) for followers
    with the same key, then runs ``grouped(items)`` ONCE — one scenario-
    batched kernel dispatch for every participant. ``grouped`` returns
    per-item results aligned with its input, or None to decline, in
    which case every participant falls back to its own ``solo()`` (the
    correct answer, just without the shared dispatch). ``window <= 0``
    disables batching entirely (the default — batching is opt-in).

    All shared state is serialized on one ``threading.Condition``; the
    leader closes its batch (removes it from ``_pending``) before
    releasing the lock to solve, so late arrivals start a fresh batch
    rather than racing a solve in progress."""

    def __init__(self, window: float = 0.0, max_batch: int = 8):
        self.window = window
        self.max_batch = max_batch
        self._cond = threading.Condition()
        self._pending: Dict[object, _Batch] = {}
        self._batched = 0
        self._declined = 0

    def counts(self) -> Dict[str, int]:
        with self._cond:
            return {"batched": self._batched, "declined": self._declined}

    def solve(
        self,
        key,
        item,
        solo: Callable[[], object],
        grouped: Callable[[Sequence[object]], Optional[List[object]]],
    ):
        if self.window <= 0 or key is None:
            return solo()
        with self._cond:
            batch = self._pending.get(key)
            if batch is None:
                batch = _Batch(key)
                self._pending[key] = batch
                slot = _BatchSlot(item)
                batch.slots.append(slot)
                leader = True
            else:
                slot = _BatchSlot(item)
                batch.slots.append(slot)
                leader = False
                if len(batch.slots) >= self.max_batch:
                    self._cond.notify_all()  # wake the leader early
        if leader:
            return self._lead(batch, slot, grouped, solo)
        return self._follow(batch, slot, solo)

    def _lead(self, batch, slot, grouped, solo):
        dclk = obs.duration_clock()
        deadline = dclk.now() + self.window
        with self._cond:
            while (
                len(batch.slots) < self.max_batch
                and dclk.now() < deadline
            ):
                self._cond.wait(max(0.001, deadline - dclk.now()))
            # close the batch: late same-key arrivals form a new one
            self._pending.pop(batch.key, None)
            slots = list(batch.slots)
        results = None
        try:
            results = grouped([s.item for s in slots])
        except Exception:
            # a failed union solve must never take the participants down
            # with it — everyone gets the solo answer instead
            results = None
        with self._cond:
            if results is None:
                self._declined += 1
                for s in slots:
                    s.declined = True
            else:
                self._batched += 1
                for s, r in zip(slots, results):
                    s.result = r
            batch.done = True
            self._cond.notify_all()
        TENANT_BATCHES.inc(
            labels={
                "outcome": "declined" if results is None else "batched"
            }
        )
        if slot.declined:
            return solo()
        return slot.result

    def _follow(self, batch, slot, solo):
        # the leader always completes (grouped() exceptions are caught),
        # but the wait is still bounded so a killed leader thread cannot
        # park followers forever
        with self._cond:
            for _ in range(2400):
                if batch.done:
                    break
                self._cond.wait(0.25)
            done = batch.done
        if not done:
            raise RuntimeError(
                "cross-tenant batch leader never completed "
                f"(key={batch.key!r})"
            )
        if slot.declined:
            return solo()
        return slot.result


__all__ = [
    "DEFAULT_TENANT",
    "TIER_PREMIUM", "TIER_STANDARD", "TIER_BATCH", "TIER_DEFAULTS",
    "TenantQoS", "TenantState", "TenantRegistry", "AdmissionLease",
    "AdmissionError", "DeadlineOverrunError", "CrossTenantBatcher",
    "TENANT_SOLVES", "TENANT_REJECTIONS", "TENANT_DEADLINE_OVERRUNS",
    "TENANT_INFLIGHT", "TENANT_BATCHES",
]
