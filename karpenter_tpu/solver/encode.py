"""Snapshot encoding: scheduling problem -> dense device arrays.

The TPU formulation departs from the reference's pod-by-pod loop in two ways:

1. **Pod grouping.** Pods with identical requests + requirements +
   tolerations are one *group* with a count. A 50k-pod deployment becomes a
   single group; the FFD scan runs over groups, not pods, and places whole
   groups by water-filling (ops/packing.py).
2. **Mask algebra.** Requirements become boolean masks over an interned
   vocabulary (solver/vocab.py) so compatibility is a batched AND/ANY
   reduction instead of per-key set walks (the vectorization of
   filterInstanceTypesByRequirements, reference nodeclaim.go:363-426).

Resource quantities are quantized to per-resource integer units that fit
float32 exactly (cpu: milli, memory-like: MiB ceil-for-requests /
floor-for-capacity, counts: whole): conservative, never over-packs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as labels_mod
from ..api import resources as res
from ..api import taints as taints_mod
from ..api.objects import Pod
from ..api.requirements import Operator, Requirement, Requirements, pod_requirements
from ..cloudprovider import types as cp
from ..scheduling.template import NodeClaimTemplate
from ..scheduling.topology import MAX_SKEW_UNBOUNDED, TopologyType
from .vocab import Vocab, _next_pow2

_MEMORY_LIKE = ("memory", "storage", "hugepages")

HCAP_NONE = 2**30  # sentinel: no per-entity topology cap

# domain-constraint modes for the kernel's quota machinery (ops/packing.py)
DMODE_NONE = 0
DMODE_SPREAD = 1
DMODE_AFFINITY = 2
# gate modes: the group is constrained by the counts but does not move them
# (the owner pod is NOT selected by its own constraint; the reference checks
# skew/options against counts other pods' placements evolve,
# topologygroup.go:205-251 / :277-290 with selects(pod)=false). Admissible
# domains are re-derived each step from the shared carry.
DMODE_GATE_SPREAD = 3
DMODE_GATE_AFF = 4

# topology keys whose domains are interned in the offering vocabulary and
# therefore ride the TPU as a dense domain axis (solver/vocab.py)
DOMAIN_KEYS = (labels_mod.TOPOLOGY_ZONE, labels_mod.CAPACITY_TYPE_LABEL_KEY)
_DRANK_NONE = 2**28

# synthetic per-CSI-driver resource columns: pods with volumes consume
# attach slots as an ordinary resource the pack phase ledgers (requests
# ceil per pod, node capacity = remaining CSINode attach limit). Fresh
# claims have no CSINode yet, so their columns carry the no-limit
# sentinel — exactly the oracle's "limits only apply to existing nodes"
# (scheduling/volumeusage.py).
VOL_RES_PREFIX = "ktpu.io/vol-"
VOL_UNLIMITED = float(2**24)  # float32-exact; far above any attach limit
# per-pod memoized routing verdict sentinel; a STRING so it survives
# copy.deepcopy of a pod (an object() sentinel would deep-copy to a new
# identity and masquerade as a group key)
_NOT_TENSORIZABLE = "__not_tensorizable__"

# EncodedSnapshot array fields with a G or N axis (padded by .padded()) and
# those provably without one; .padded() refuses unclassified fields so a
# new axis-carrying field cannot silently ship unpadded
_PADDED_FIELDS = frozenset({
    "g_count", "g_req", "g_def", "g_neg", "g_mask", "g_hcap", "g_haff",
    "g_dmode", "g_dkey", "g_dskew", "g_dmin0", "g_dprior", "g_dreg",
    "g_drank", "g_hstg", "g_hscap", "g_dtg",
    "g_hself", "g_hcontrib", "g_dcontrib",
    "p_tol", "n_tol", "n_hcnt",
    "n_avail", "n_base", "n_def", "n_mask", "n_dzone", "n_dct", "nh_cnt0",
})
_GN_FREE_FIELDS = frozenset({
    "t_alloc", "t_cap", "t_def", "t_mask", "t_price",
    "o_avail", "o_zone", "o_ct", "o_price",
    "p_def", "p_neg", "p_mask", "p_daemon", "p_limit", "p_has_limit",
    "p_titype_ok",
    "p_mvmin", "t_mvoh",
    "dd0", "dtg_key", "well_known",
    # the compacted segment index rides its own live-pair axis (L / LZ,
    # power-of-two bucketed); entries name REAL group rows, which padding
    # never moves, so the arrays are valid for any padded G
    "gk_g", "gk_k", "gk_w", "goff_idx",
})


def _unit_divisor(resource_name: str) -> int:
    if resource_name == res.CPU:
        return 1  # milli-cpu
    if resource_name.startswith(VOL_RES_PREFIX):
        # attach-slot columns are whole-unit counts regardless of the CSI
        # driver's NAME — "pd.csi.storage.gke.io" must not quantize as
        # memory-like or the kernel over-packs past the attach limit
        return res.MILLI
    if any(tag in resource_name for tag in _MEMORY_LIKE):
        return 2**20 * res.MILLI  # MiB
    return res.MILLI  # whole units (pods, gpus, ...)


def quantize_requests(rl: res.ResourceList, names: Sequence[str]) -> np.ndarray:
    """Ceil to units (requests must never be under-counted)."""
    out = np.zeros(len(names), dtype=np.float32)
    for i, name in enumerate(names):
        q = rl.get(name, 0)
        d = _unit_divisor(name)
        out[i] = -((-q) // d)
    return out

def quantize_capacity(rl: res.ResourceList, names: Sequence[str]) -> np.ndarray:
    """Floor to units (capacity must never be over-counted)."""
    out = np.zeros(len(names), dtype=np.float32)
    for i, name in enumerate(names):
        out[i] = rl.get(name, 0) // _unit_divisor(name)
    return out


def _node_single_value(en, key: str) -> Optional[str]:
    """The node's concrete value for a label key, if single-valued."""
    if not en.requirements.has(key):
        return None
    r = en.requirements.get(key)
    if r.complement or len(r.values) != 1:
        return None
    return next(iter(r.values))


def _observe_node_domains(vocab: "Vocab", en) -> None:
    for key in DOMAIN_KEYS:
        v = _node_single_value(en, key)
        if v is not None:
            vocab.value_id(key, v)


def _node_domain_id(vocab: "Vocab", en, key: str) -> int:
    v = _node_single_value(en, key)
    return vocab.value_id(key, v) if v is not None else -1


@dataclass
class SharedHostTG:
    """A hostname-keyed constraint shared by several pod groups (e.g. one
    Deployment's anti-affinity across request shapes). Counts live in the
    kernel carry, indexed by the slot encode() assigns; ``counts`` are the
    cluster priors per hostname. ``tg`` back-references the oracle
    TopologyGroup this descriptor distilled from (host-side only — never
    encoded; the scenario axis uses it to re-derive per-scenario priors)."""

    cap: int
    counts: Dict[str, int] = field(default_factory=dict)
    tg: object = None

    def content(self) -> tuple:
        return (self.cap, tuple(sorted(self.counts.items())))


@dataclass
class SharedDomainTG:
    """A zone/capacity-type-keyed constraint shared by several pod groups.
    Descriptor fields mirror TopoSpec's d* fields; the evolving counts ride
    the kernel's domain carry. ``tg`` is the host-side oracle back-ref."""

    key: str
    mode: int
    skew: int = 0
    min0: bool = False
    prior: Dict[str, int] = field(default_factory=dict)
    reg: frozenset = frozenset()
    tg: object = None

    def content(self) -> tuple:
        return (
            self.key, self.mode, self.skew, self.min0,
            tuple(sorted(self.prior.items())), tuple(sorted(self.reg)),
        )


@dataclass
class TopoSpec:
    """Tensorized topology state for one pod group.

    Host-side distillation of the oracle's TopologyGroups
    (scheduling/topology.py) into the forms the kernel consumes:

    - hostname-keyed constraints collapse to a per-entity cap: hostname
      domains have a global min of 0 (reference topologygroup.go:253-274),
      so "count+1-min <= maxSkew" is just "<= maxSkew pods of this group per
      node/claim"; self anti-affinity is the maxSkew=1 case of the same rule
      (empty-domain selection, topologygroup.go:340-366).
    - domain-keyed (zone / capacity-type) constraints become a per-group
      descriptor over the interned value slots: self-selecting spread
      (DMODE_SPREAD) carries maxSkew + priors + the registered universe for
      the kernel's quota water-fill (topologygroup.go:205-251); affinity
      with no compatible placed pods (DMODE_AFFINITY) triggers the
      bootstrap single-domain rule (topologygroup.go:277-324).
      Non-self-selecting gates and affinity-with-priors need no kernel
      state at all — they intersect the group's requirement mask in
      _resolve_topology.
    - prior counts come from cluster pods already selected by the
      constraint (topology.go:322-420), keyed by node name / domain value.
    """

    host_cap: Optional[int] = None  # per-entity cap; None = unconstrained
    host_counts: Dict[str, int] = field(default_factory=dict)  # node -> prior
    # hostname-keyed POD_AFFINITY: the whole group co-locates on ONE entity
    # (topologygroup.go:277-324 hostname case). With priors, candidates are
    # exactly the prior-holding nodes; without, the bootstrap pins the
    # first fitting entity and the rest follow (overflow = pod errors).
    haff: bool = False
    haff_prior: Dict[str, int] = field(default_factory=dict)  # node -> count
    dmode: int = DMODE_NONE
    dkey: Optional[str] = None  # TOPOLOGY_ZONE or CAPACITY_TYPE_LABEL_KEY
    dskew: int = 0
    dmin0: bool = False  # minDomains unsatisfied: global min pinned to 0
    dprior: Dict[str, int] = field(default_factory=dict)  # domain -> count
    dreg: frozenset = frozenset()  # registered ∧ pod-admissible domains
    # constraints shared across groups: same descriptor object on every
    # sharing group's spec; encode() assigns carry slots by object identity
    shared_h: Optional[SharedHostTG] = None
    shared_d: Optional[SharedDomainTG] = None
    # shared-hostname role: True = self-selecting owner (per-entity cap of
    # h_capval, counts itself), False = gated-only owner (entities whose
    # carry count exceeds h_capval are blocked; placements never counted)
    h_self: bool = True
    h_capval: Optional[int] = None  # overrides shared_h.cap when set
    # shared constraints this group's placements COUNT toward without being
    # gated by them (the group's pods match the selector but don't own the
    # constraint — the oracle counts them at record(), topology.py:491-498)
    contrib_h: List[SharedHostTG] = field(default_factory=list)
    contrib_d: List[SharedDomainTG] = field(default_factory=list)
    # host-side oracle back-refs (never encoded): the TopologyGroups the
    # dynamic state above distilled from — ``src_h`` the self-selecting
    # hostname constraints folded into host_cap/host_counts, ``src_d`` the
    # private domain-dynamic constraint. The scenario-batched axis walks
    # these to re-derive per-scenario priors when candidate nodes' bound
    # pods count toward a constraint.
    src_h: List[object] = field(default_factory=list)
    src_d: object = None
    # total hostname constraints folded into host_cap/host_counts (self +
    # gate): the scenario corrections are additive only for a single-source
    # fold, so the count gates representability
    host_nsrc: int = 0

    def content_sig(self) -> tuple:
        """Canonical content signature for the delta-encode contract: two
        groups whose sig (plus slot structure, added by the caller) match
        encode to identical g_* topology rows."""
        return (
            self.host_cap,
            tuple(sorted(self.host_counts.items())),
            self.haff,
            tuple(sorted(self.haff_prior.items())),
            self.dmode, self.dkey, self.dskew, self.dmin0,
            tuple(sorted(self.dprior.items())),
            tuple(sorted(self.dreg)),
            self.h_self, self.h_capval,
        )


@dataclass
class PodGroup:
    """An equivalence class of schedulable pods."""

    pods: List[Pod]
    requirements: Requirements
    requests: res.ResourceList
    topo: Optional[TopoSpec] = None

    @property
    def count(self) -> int:
        return len(self.pods)


def _tsc_key(t) -> tuple:
    """Memoized identity tuple for a TopologySpreadConstraint (hot in the
    50k-pod grouping loop; constraint objects are immutable in practice)."""
    k = getattr(t, "_key_cache", None)
    if k is None:
        k = (
            t.max_skew, t.topology_key, t.when_unsatisfiable,
            t.label_selector.key() if t.label_selector else None,
            t.min_domains, t.node_affinity_policy, t.node_taints_policy,
        )
        object.__setattr__(t, "_key_cache", k)
    return k


def _term_key(t) -> tuple:
    """Memoized identity tuple for a PodAffinityTerm."""
    k = getattr(t, "_key_cache", None)
    if k is None:
        k = (
            t.topology_key,
            t.label_selector.key() if t.label_selector else None,
            t.namespaces,
        )
        object.__setattr__(t, "_key_cache", k)
    return k


def group_key(pod: Pod) -> tuple:
    """Equivalence key from raw spec primitives — no Requirements objects
    are built per pod (hot for 50k-pod snapshots); the group's Requirements
    are constructed once in build_groups. Frozensets, not sorted tuples:
    only equality/hash matter here and set construction is ~2x faster.

    Pods carrying topology constraints additionally key on namespace +
    labels + the constraint signatures: their placement depends on selector
    matching, so only pods that count identically may share a group."""
    spec = pod.spec
    affinity_key = ()
    if spec.node_affinity is not None and spec.node_affinity.required:
        affinity_key = tuple(
            (t.key, t.operator, tuple(t.values), t.min_values)
            for t in spec.node_affinity.required[0]
        )
    base = (
        frozenset(spec.requests.items()),
        frozenset(spec.node_selector.items()) if spec.node_selector else (),
        affinity_key,
        frozenset(
            (t.key, t.operator, t.value, t.effect) for t in spec.tolerations
        ) if spec.tolerations else (),
    )
    if not (spec.topology_spread_constraints or spec.pod_anti_affinity or spec.pod_affinity):
        return base
    topo = (
        pod.metadata.namespace,
        frozenset(pod.metadata.labels.items()),
        tuple(_tsc_key(t) for t in spec.topology_spread_constraints),
        tuple(_term_key(t) for t in spec.pod_affinity),
        tuple(_term_key(t) for t in spec.pod_anti_affinity),
    )
    return base + topo


_EMPTY_FS = frozenset()


def _sel_signature(pod: Pod, sel_keys: frozenset) -> tuple:
    """(namespace, selector-relevant labels) appended to the group key of
    pods whose own key carries no labels: selector matching for the
    shared-constraint contributor role must be uniform per group."""
    lbl = pod.metadata.labels
    return (
        pod.metadata.namespace,
        frozenset((k, v) for k, v in lbl.items() if k in sel_keys)
        if lbl
        else _EMPTY_FS,
    )


def is_tensorizable(
    pod: Pod, allow_topology: bool = False, allow_volumes: bool = False
) -> bool:
    """Pods the TPU fast path handles; the rest route to the host oracle.

    ``allow_topology`` admits the topology shapes the kernel models —
    hostname-keyed spread / anti-affinity (per-entity caps) and zone- or
    capacity-type-keyed spread / pod-affinity (domain quotas / mask gates)
    — subject to the global cross-group checks in partition_and_group (a
    Topology context is required for those). ``allow_volumes`` admits pods
    whose volumes the driver has resolved into attach-slot requests
    (driver.prepare_volume_routing: fresh non-shared volumes become
    synthetic resource columns the pack-phase ledger consumes; zonal
    constraints were already injected as node affinity upstream).
    Everything else with sequential state (host ports, preference
    relaxation, Gt/Lt) stays host-side."""
    spec = pod.spec
    if not allow_topology and (
        spec.topology_spread_constraints or spec.pod_anti_affinity or spec.pod_affinity
    ):
        return False
    if allow_topology:
        for tsc in spec.topology_spread_constraints:
            if tsc.topology_key != labels_mod.HOSTNAME and (
                tsc.topology_key not in DOMAIN_KEYS
            ):
                return False  # custom topology keys stay host-side
            if tsc.when_unsatisfiable != "DoNotSchedule":
                return False  # ScheduleAnyway relaxes host-side
            if tsc.node_taints_policy == "Honor":
                return False  # taint-gated counting stays host-side
        for term in spec.pod_anti_affinity:
            # zonal anti-affinity serializes host-side: the oracle records
            # EVERY domain of a multi-domain claim as occupied
            # (topology.go:205-214), which the quota form cannot express
            if term.topology_key != labels_mod.HOSTNAME:
                return False
        if len(spec.pod_anti_affinity) > 1:
            return False
        for term in spec.pod_affinity:
            # zone/ct affinity rides the domain machinery; hostname
            # affinity (co-locate on one node) rides the single-entity
            # pin (_resolve_topology admits only the self-selecting,
            # group-private shape)
            if (
                term.topology_key not in DOMAIN_KEYS
                and term.topology_key != labels_mod.HOSTNAME
            ):
                return False
        if len(spec.pod_affinity) > 1:
            return False
    if spec.preferred_pod_affinity or spec.preferred_pod_anti_affinity:
        return False
    if spec.host_ports or (spec.volumes and not allow_volumes):
        return False
    if spec.node_affinity is not None:
        if spec.node_affinity.preferred or len(spec.node_affinity.required) > 1:
            return False  # relaxation loop is host-side
        for term in spec.node_affinity.required[:1]:
            for r in term:
                if r.min_values is not None:
                    return False
                # Gt/Lt carry operator identity the mask algebra can't retain
                # through intersections (the double-negation exemption
                # distinguishes NotIn from Gt); rare enough to stay host-side
                if r.operator in ("Gt", "Lt"):
                    return False
    return True


@dataclass
class EncodedSnapshot:
    """Device-ready arrays for one solve. Shapes:
    G groups, T types, P templates(pools), N existing nodes, R resources,
    K keys, V1 value slots (last = overflow), O offerings per type.
    """

    vocab: Vocab
    resource_names: List[str]
    groups: List[PodGroup]
    templates: List[NodeClaimTemplate]
    instance_types: List[cp.InstanceType]
    existing_names: List[str]

    # groups
    g_count: np.ndarray  # [G] int32
    g_req: np.ndarray  # [G, R] f32
    g_def: np.ndarray  # [G, K] bool
    g_neg: np.ndarray  # [G, K] bool
    g_mask: np.ndarray  # [G, K, V1] bool
    g_hcap: np.ndarray  # [G] int32 per-entity cap (hostname topology; HCAP_NONE=free)
    n_hcnt: np.ndarray  # [N, G] int32 prior selected-pod counts per existing
    # node — per-entity-cap priors for capped groups; for g_haff groups the
    # SAME rows hold the hostname-affinity matching-pod priors (the two
    # never combine: _resolve_topology demotes the combo)
    g_haff: np.ndarray  # [G] bool hostname-affinity single-entity pin
    # domain-keyed (zone / capacity-type) constraint descriptors
    g_dmode: np.ndarray  # [G] int32 DMODE_*
    g_dkey: np.ndarray  # [G] int32 0=zone 1=capacity-type
    g_dskew: np.ndarray  # [G] int32 maxSkew
    g_dmin0: np.ndarray  # [G] bool minDomains pins global min to 0
    g_dprior: np.ndarray  # [G, V1] int32 prior counts per domain slot
    g_dreg: np.ndarray  # [G, V1] bool registered ∧ pod-admissible domains
    g_drank: np.ndarray  # [G, V1] int32 sorted-domain rank (bootstrap order)
    n_dzone: np.ndarray  # [N] int32 node zone value id (-1 = none)
    n_dct: np.ndarray  # [N] int32 node capacity-type value id (-1 = none)
    # shared-constraint carries (cross-group counting)
    g_hstg: np.ndarray  # [G] int32 shared hostname-constraint slot (-1 none)
    g_hscap: np.ndarray  # [G] int32 per-entity cap (self) / gate threshold
    g_dtg: np.ndarray  # [G] int32 shared domain-constraint slot (-1 none)
    g_hself: np.ndarray  # [G] bool shared-hostname role (True = counts itself)
    g_hcontrib: np.ndarray  # [G, JH] bool slots this group counts toward
    g_dcontrib: np.ndarray  # [G, JD] bool slots this group counts toward
    nh_cnt0: np.ndarray  # [N, JH] int32 shared-constraint node priors
    dd0: np.ndarray  # [JD, V1] int32 shared domain-count carry init (zeros)
    dtg_key: np.ndarray  # [JD] int32 shared domain-constraint axis (0=zone)

    # instance types
    t_alloc: np.ndarray  # [T, R] f32
    t_cap: np.ndarray  # [T, R] f32 (capacity, for limits accounting)
    t_def: np.ndarray  # [T, K] bool
    t_mask: np.ndarray  # [T, K, V1] bool
    t_price: np.ndarray  # [T] f32 cheapest available offering (unconstrained)

    # offerings
    o_avail: np.ndarray  # [T, O] bool
    o_zone: np.ndarray  # [T, O] int32 (value id in zone vocab; -1 pad)
    o_ct: np.ndarray  # [T, O] int32
    o_price: np.ndarray  # [T, O] f32

    # templates (nodepools, weight-desc order)
    p_def: np.ndarray  # [P, K] bool
    p_neg: np.ndarray  # [P, K] bool
    p_mask: np.ndarray  # [P, K, V1] bool
    p_daemon: np.ndarray  # [P, R] f32
    p_limit: np.ndarray  # [P, R] f32 (inf when unlimited)
    p_has_limit: np.ndarray  # [P] bool
    p_titype_ok: np.ndarray  # [P, T] bool  template prefilter
    p_tol: np.ndarray  # [P, G] bool  group tolerates template taints
    # dense minValues: MV = distinct requirement keys carrying min_values
    # across templates, W = padded distinct-value bound over the catalog.
    # p_mvmin[p, j] is template p's floor for key slot j (0 = none);
    # t_mvoh[t, j, w] marks instance type t offering catalog value w of key
    # slot j (the raw per-type value union satisfies_min_values counts,
    # cloudprovider/types.go:155-233). MV == 0 traces the whole minValues
    # machinery out of the kernels.
    p_mvmin: np.ndarray  # [P, MV] int32
    t_mvoh: np.ndarray  # [T, MV, W] bool

    # existing nodes (priority order: initialized first, then name)
    n_avail: np.ndarray  # [N, R] f32 (available to new pods)
    n_base: np.ndarray  # [N, R] f32 (already-committed daemon remainder)
    n_def: np.ndarray  # [N, K] bool
    n_mask: np.ndarray  # [N, K, V1] bool
    n_tol: np.ndarray  # [N, G] bool

    zone_kid: int
    ct_kid: int
    well_known: np.ndarray  # [K] bool

    # compacted nonzero-mask segment index over the group requirement axis
    # (build_segment_index): the (group, key) pairs whose requirement row
    # differs from the neutral all-true row. The sparse feasibility kernels
    # (ops/feasibility.py:*_sparse) contract over these L live pairs with
    # segment_sum instead of materializing the dense [P, G, T, K, V1] join,
    # so feasibility cost scales with live pairs, not G x K. Both axes are
    # power-of-two bucketed so group churn shares compiled programs.
    gk_g: np.ndarray  # [L] int32 group id per live pair (0 on padding)
    gk_k: np.ndarray  # [L] int32 key id per live pair (0 on padding)
    gk_w: np.ndarray  # [L] int32 1 live / 0 padding
    goff_idx: np.ndarray  # [LZ] int32 groups whose zone/ct row is non-neutral

    def padded(self, g_target: int, n_target: int) -> "EncodedSnapshot":
        """A copy with the group and existing-node axes padded to bucket
        sizes, so repeat solves of nearby shapes (e.g. consolidation's
        binary-search probes, each with a slightly different candidate set)
        share one compiled program instead of recompiling per probe.

        Padded groups have count 0 and place nothing; padded nodes have no
        capacity and no tolerance, so they never receive fills. Decode
        reads ``groups``/``existing_names`` (unpadded) and only walks
        nonzero fills, so outputs stay correct.
        """
        import dataclasses

        G = len(self.g_count)
        N = self.n_avail.shape[0]
        gp = max(g_target - G, 0)
        np_pad = max(n_target - N, 0)
        if not gp and not np_pad:
            return self
        # exhaustiveness guard: every array field must either be padded
        # below or be known G/N-free — a new G/N-axis field silently
        # shipping unpadded would clamp-index real groups inside jit
        known = _PADDED_FIELDS | _GN_FREE_FIELDS
        for f in dataclasses.fields(self):
            if isinstance(getattr(self, f.name), np.ndarray) and f.name not in known:
                raise AssertionError(
                    f"EncodedSnapshot.{f.name} is not classified for padded();"
                    " add it to _PADDED_FIELDS or _GN_FREE_FIELDS"
                )

        def pad(arr, axis, width, fill=0):
            if not width:
                return arr
            widths = [(0, 0)] * arr.ndim
            widths[axis] = (0, width)
            return np.pad(arr, widths, constant_values=fill)

        return dataclasses.replace(
            self,
            g_count=pad(self.g_count, 0, gp),
            g_req=pad(self.g_req, 0, gp),
            g_def=pad(self.g_def, 0, gp),
            g_neg=pad(self.g_neg, 0, gp),
            g_mask=pad(self.g_mask, 0, gp, fill=1),
            g_hcap=pad(self.g_hcap, 0, gp, fill=HCAP_NONE),
            g_haff=pad(self.g_haff, 0, gp),
            g_dmode=pad(self.g_dmode, 0, gp),
            g_dkey=pad(self.g_dkey, 0, gp),
            g_dskew=pad(self.g_dskew, 0, gp),
            g_dmin0=pad(self.g_dmin0, 0, gp),
            g_dprior=pad(self.g_dprior, 0, gp),
            g_dreg=pad(self.g_dreg, 0, gp),
            g_drank=pad(self.g_drank, 0, gp, fill=_DRANK_NONE),
            g_hstg=pad(self.g_hstg, 0, gp, fill=-1),
            g_hscap=pad(self.g_hscap, 0, gp, fill=HCAP_NONE),
            g_dtg=pad(self.g_dtg, 0, gp, fill=-1),
            g_hself=pad(self.g_hself, 0, gp, fill=1),
            g_hcontrib=pad(self.g_hcontrib, 0, gp),
            g_dcontrib=pad(self.g_dcontrib, 0, gp),
            p_tol=pad(self.p_tol, 1, gp),
            n_tol=pad(pad(self.n_tol, 1, gp), 0, np_pad),
            n_hcnt=pad(pad(self.n_hcnt, 1, gp), 0, np_pad),
            n_avail=pad(self.n_avail, 0, np_pad),
            n_base=pad(self.n_base, 0, np_pad),
            n_def=pad(self.n_def, 0, np_pad),
            n_mask=pad(self.n_mask, 0, np_pad, fill=1),
            n_dzone=pad(self.n_dzone, 0, np_pad, fill=-1),
            n_dct=pad(self.n_dct, 0, np_pad, fill=-1),
            nh_cnt0=pad(self.nh_cnt0, 0, np_pad),
        )

    def solve_args(
        self,
        a_tzc: np.ndarray,
        res_cap0: Optional[np.ndarray] = None,
        a_res: Optional[np.ndarray] = None,
    ) -> tuple:
        """The positional argument tuple for ops/solve.py:solve_core — the
        single authority on that ordering (driver, examples, the multi-chip
        padding, and the scenario axis all build from this; SOLVE_ARG_NAMES
        below names each position for axis selection)."""
        if res_cap0 is None:
            res_cap0 = np.zeros((0,), np.int32)
        if a_res is None:
            a_res = np.zeros((0,) + a_tzc.shape, bool)
        return (
            self.g_count, self.g_req, self.g_def, self.g_neg, self.g_mask,
            self.g_hcap, self.g_haff,
            self.g_dmode, self.g_dkey, self.g_dskew, self.g_dmin0,
            self.g_dprior, self.g_dreg, self.g_drank,
            self.g_hstg, self.g_hscap, self.g_dtg,
            self.g_hself, self.g_hcontrib, self.g_dcontrib,
            self.p_def, self.p_neg, self.p_mask, self.p_daemon,
            self.p_limit, self.p_has_limit, self.p_tol, self.p_titype_ok,
            self.t_def, self.t_mask, self.t_alloc, self.t_cap,
            self.o_avail, self.o_zone, self.o_ct,
            a_tzc, res_cap0, a_res,
            self.n_def, self.n_mask, self.n_avail, self.n_base, self.n_tol,
            self.n_hcnt,
            self.n_dzone, self.n_dct,
            self.nh_cnt0, self.dd0, self.dtg_key,
            self.well_known,
            self.p_mvmin, self.t_mvoh,
            self.gk_g, self.gk_k, self.gk_w, self.goff_idx,
        )


# Position names for EncodedSnapshot.solve_args' tuple, in order. The
# scenario-batched dispatch (ops/solve.py:solve_all_scenarios_packed) maps
# batched axes by name through this tuple, so it must track solve_args
# exactly (tests/test_scenario_batch.py pins the correspondence).
SOLVE_ARG_NAMES = (
    "g_count", "g_req", "g_def", "g_neg", "g_mask",
    "g_hcap", "g_haff",
    "g_dmode", "g_dkey", "g_dskew", "g_dmin0",
    "g_dprior", "g_dreg", "g_drank",
    "g_hstg", "g_hscap", "g_dtg",
    "g_hself", "g_hcontrib", "g_dcontrib",
    "p_def", "p_neg", "p_mask", "p_daemon",
    "p_limit", "p_has_limit", "p_tol", "p_titype_ok",
    "t_def", "t_mask", "t_alloc", "t_cap",
    "o_avail", "o_zone", "o_ct",
    "a_tzc", "res_cap0", "a_res",
    "n_def", "n_mask", "n_avail", "n_base", "n_tol",
    "n_hcnt",
    "n_dzone", "n_dct",
    "nh_cnt0", "dd0", "dtg_key",
    "well_known",
    "p_mvmin", "t_mvoh",
    "gk_g", "gk_k", "gk_w", "goff_idx",
)


def build_segment_index(
    g_def: np.ndarray,
    g_neg: np.ndarray,
    g_mask: np.ndarray,
    zone_kid: int,
    ct_kid: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Compacted nonzero-mask index over the group requirement axis.

    A (group, key) pair is *live* when its requirement row differs from the
    neutral row (undefined, non-negated, all-true mask) — the only rows
    that can change a feasibility term away from the group-independent
    base. Returns (gk_g, gk_k, gk_w, goff_idx):

    - ``gk_g``/``gk_k``/``gk_w`` [L]: group id, key id, and 0/1 live weight
      per pair. L is power-of-two bucketed (floor 8) so group churn of
      nearby live-pair counts shares one compiled program; padding rows
      carry weight 0 and never contribute to a segment sum.
    - ``goff_idx`` [LZ]: ids of groups whose zone or capacity-type row is
      non-neutral — the only groups whose merged offering row differs from
      the template's. Padding repeats group 0: the sparse kernel scatters
      each listed group's *recomputed true row*, so duplicate writes are
      idempotent by construction.
    """
    neutral = (~g_def) & (~g_neg) & g_mask.all(axis=2)
    live = ~neutral  # [G, K]
    gg, kk = np.nonzero(live)
    L = _next_pow2(max(len(gg), 1), floor=8)
    gk_g = np.zeros((L,), np.int32)
    gk_k = np.zeros((L,), np.int32)
    gk_w = np.zeros((L,), np.int32)
    gk_g[: len(gg)] = gg
    gk_k[: len(gg)] = kk
    gk_w[: len(gg)] = 1
    offl = np.flatnonzero(live[:, zone_kid] | live[:, ct_kid])
    LZ = _next_pow2(max(len(offl), 1), floor=8)
    goff_idx = np.zeros((LZ,), np.int32)
    goff_idx[: len(offl)] = offl
    return gk_g, gk_k, gk_w, goff_idx


# -- incremental (delta) encoding -------------------------------------------


def shared_slot_ids(
    groups: Sequence["PodGroup"],
) -> Tuple[Dict[int, int], Dict[int, int]]:
    """(hostname-slot, domain-slot) maps keyed by id(descriptor), assigned
    by first appearance over the group walk — EXACTLY _encode_groups'
    assignment, so callers (the scenario-batched prior corrections, the
    delta content tags) address the same carry columns the kernel reads."""
    h_slots: Dict[int, int] = {}
    d_slots: Dict[int, int] = {}
    for g in groups:
        t = g.topo
        if t is None:
            continue
        if t.shared_h is not None:
            h_slots.setdefault(id(t.shared_h), len(h_slots))
        if t.shared_d is not None:
            d_slots.setdefault(id(t.shared_d), len(d_slots))
        for d in t.contrib_h:
            h_slots.setdefault(id(d), len(h_slots))
        for d in t.contrib_d:
            d_slots.setdefault(id(d), len(d_slots))
    return h_slots, d_slots


def topo_content_sigs(groups: Sequence["PodGroup"]) -> tuple:
    """Per-group topology content signatures for the delta-encode
    contract: ``None`` for topology-free groups, else the TopoSpec content
    plus the shared-carry SLOT STRUCTURE (slot index + descriptor content
    per shared/contributed constraint). Slot indices are assigned by
    first-appearance order over the group walk — exactly
    ``_encode_groups``'s assignment — so equal sig tuples guarantee
    byte-identical g_* topology arrays AND carry layouts (dd0/dtg_key
    shapes, g_hcontrib/g_dcontrib columns)."""
    h_slots: Dict[int, int] = {}
    d_slots: Dict[int, int] = {}

    def _h(desc) -> int:
        return h_slots.setdefault(id(desc), len(h_slots))

    def _d(desc) -> int:
        return d_slots.setdefault(id(desc), len(d_slots))

    sigs = []
    for g in groups:
        t = g.topo
        if t is None:
            sigs.append(None)
            continue
        shared_h = (
            (_h(t.shared_h), t.shared_h.content())
            if t.shared_h is not None
            else None
        )
        shared_d = (
            (_d(t.shared_d), t.shared_d.content())
            if t.shared_d is not None
            else None
        )
        contrib_h = tuple((_h(d), d.content()) for d in t.contrib_h)
        contrib_d = tuple((_d(d), d.content()) for d in t.contrib_d)
        sigs.append(
            t.content_sig() + (shared_h, shared_d, contrib_h, contrib_d)
        )
    return tuple(sigs)


def _req_content_key(reqs) -> tuple:
    """Content identity of a Requirements object for cross-solve row
    reuse: everything vocab.encode reads, order-normalized."""
    return tuple(
        sorted(
            (
                r.key, r.complement, tuple(sorted(r.values)),
                r.greater_than, r.less_than,
            )
            for r in reqs
        )
    )


@dataclass
class EncodeDelta:
    """What changed between this encode and the previous one, in the
    shape the device-residency layer (solver/residency.py) consumes.

    ``reused`` means the prior EncodedSnapshot's arrays were returned
    verbatim (content-hash fast path: nothing relevant changed).
    ``full`` means no delta information is available (first encode, vocab
    growth, catalog change, topology in the batch) and every device
    buffer must be restaged. Otherwise the ``*_rows`` arrays name the
    ordered axis positions whose rows changed — ``node_rows`` for the
    pure node-content arrays (n_avail/n_base/n_def/n_mask/n_dzone/n_dct),
    ``group_rows`` for the g_* group-axis arrays, ``cross_rows`` for the
    node x group arrays (n_tol/n_hcnt/nh_cnt0). ``v_*`` are monotonic
    version counters per device-argument class; the residency store
    reuses a device buffer iff its class version is unchanged."""

    reused: bool = False
    full: bool = True
    delta_rows: int = 0
    node_rows: Optional[np.ndarray] = None
    group_rows: Optional[np.ndarray] = None
    count_rows: Optional[np.ndarray] = None
    cross_rows: Optional[np.ndarray] = None
    v_static: int = 0
    v_groups: int = 0
    v_gcount: int = 0
    v_nodes: int = 0
    v_cross: int = 0
    groups_unchanged: bool = False
    # group SHAPES (requests/requirements/tolerations/topology-freedom)
    # unchanged, only per-group counts moved — the steady-state churn
    # shape: every G-side array except g_count is reusable verbatim
    groups_shape_unchanged: bool = False


class ClusterEncoding:
    """Persistent incremental encoding of one cluster across solves.

    Owned by EncodeCache (one per control plane / sidecar), consulted by
    ``encode()`` when passed as ``cluster=``. Three layers, fastest first:

    1. **Content-hash fast path** — a fingerprint over (vocab generation,
       padded shape, resource axis, per-group content tags, per-node
       content tags, pool limits / daemon overhead) matches the previous
       encode's: the prior EncodedSnapshot's arrays are returned verbatim
       (fresh ``groups``/``existing_names`` metadata so decode binds the
       NEW pod/node objects), and the delta reports ``reused``.
    2. **Row banks** — content-keyed caches of the expensive per-row
       work (vocab.encode masks per requirement content, tolerance rows
       per taint content, quantized node rows per node content) so churn
       re-encodes only the changed rows; the assembled arrays are
       byte-identical to a from-scratch encode because the banks cache
       exactly what the from-scratch loops compute
       (tests/test_delta_encode.py pins this over random churn scripts).
    3. **Full re-encode** — vocab growth (a genuinely new label value),
       catalog change, or a topology-carrying batch drops the fast paths
       for that encode; the banks re-warm on the next pass.

    Delta tracking: each encode compares its ordered content-tag lists
    against the previous encode's and reports the changed axis positions
    plus per-class version counters (EncodeDelta above) — the device-
    residency layer transfers only those rows. Banks are periodically
    compacted: entries unused for ``2 * compact_every`` encodes are
    evicted every ``compact_every`` encodes, so one-off shapes don't
    accumulate across days of reconciles.

    Not thread-safe on its own: callers serialize on EncodeCache.lock
    (the same discipline encode's shared vocab already requires).
    """

    def __init__(self, compact_every: int = 64, owner: str = ""):
        self.compact_every = compact_every
        # multi-tenant attribution (solver/tenancy.py): whose warm banks
        # these are. Rides the ENCODE_DELTA fault ctx so tenant-scoped
        # chaos plans can match a specific tenant's encode leases.
        self.owner = owner
        self._epoch = None
        self._tol_epoch = None
        # content-keyed row banks; values are (last_used_tick, payload)
        # where the tick is the bank's OWN use clock — advanced only on
        # encodes that actually consult that bank, so a quiet cluster
        # (consecutive content-hash reuses, or count-only churn that
        # skips the group loop) cannot age live entries to eviction
        self.node_bank: Dict[tuple, tuple] = {}
        self.group_bank: Dict[tuple, tuple] = {}
        self.tol_bank: Dict[tuple, np.ndarray] = {}
        self._encodes = 0
        self._nuses = 0
        self._guses = 0
        # previous encode's state
        self._prior_snap: Optional[EncodedSnapshot] = None
        self._prior_gtags: Optional[tuple] = None
        self._prior_ntags: Optional[tuple] = None
        self._prior_tkeys: Optional[tuple] = None
        # per-class device-buffer versions (monotonic)
        self.v_static = 0
        self.v_groups = 0
        self.v_gcount = 0
        self.v_nodes = 0
        self.v_cross = 0
        self.last_delta = EncodeDelta(
            v_static=0, v_groups=0, v_nodes=0, v_cross=0
        )
        # scratch state between begin() and finish()
        self._gkeys: List[Optional[tuple]] = []
        self._gtags: Optional[tuple] = None
        self._ntags: Optional[tuple] = None
        self._tkeys: Optional[tuple] = None
        self._banks_on = False

    # -- lifecycle --------------------------------------------------------

    def invalidate(self, reason: str = "") -> None:
        """Drop every bank and the prior snapshot; the next encode is
        full. Called on catalog changes (EncodeCache.lease reset) and by
        the driver's corrupt-delta fallback half-step."""
        self._epoch = None
        self._tol_epoch = None
        self.node_bank.clear()
        self.group_bank.clear()
        self.tol_bank.clear()
        self._prior_snap = None
        self._prior_gtags = None
        self._prior_ntags = None
        self._prior_tkeys = None
        self.v_static += 1
        self.v_groups += 1
        self.v_gcount += 1
        self.v_nodes += 1
        self.v_cross += 1

    def _vocab_gen(self, vocab: Vocab) -> tuple:
        # serial pins the instance; the value total pins growth (complement
        # masks cached at one growth state would be stale after an intern)
        return (
            vocab.serial,
            len(vocab.keys),
            sum(len(v) for v in vocab.values),
        )

    def begin(
        self,
        vocab: Vocab,
        K: int,
        V1: int,
        resource_names: Sequence[str],
        groups: Sequence[PodGroup],
        existing_nodes: Sequence,
        daemon_overhead,
        pool_limits,
        hn_interned: bool,
    ) -> EncodeDelta:
        """Compute content tags + decide reuse. Called by encode() after
        vocab observation; scratch tags feed the bank lookups in the
        assembly loops and finish()'s delta computation."""
        self._encodes += 1
        epoch = (
            self._vocab_gen(vocab), K, V1, tuple(resource_names),
            hn_interned,
            tuple(
                sorted(
                    (getattr(nct, "node_pool_name", ""), tuple(sorted(rl.items())))
                    for nct, rl in (daemon_overhead or {}).items()
                )
            ),
            tuple(
                sorted(
                    (pool, tuple(sorted(rl.items())))
                    for pool, rl in (pool_limits or {}).items()
                )
            ),
        )
        if epoch != self._epoch:
            self.node_bank.clear()
            self.group_bank.clear()
            self._tol_epoch = None
            self._prior_snap = None
            self._prior_gtags = None
            self._prior_ntags = None
            self._prior_tkeys = None
            self._epoch = epoch
            self.v_static += 1
        self._banks_on = not hn_interned
        # per-group content tags; topology-carrying groups tag the FULL
        # TopoSpec content + shared-carry slot structure (topo_content_sigs)
        # so topology batches participate in the content-hash/delta fast
        # paths instead of forcing FULL re-encodes — the ISSUE-10 extension
        # of the PR-8 contract. Equal sigs guarantee identical g_* topology
        # arrays and carry layouts; any prior/universe/role change breaks
        # the tag and restages.
        topo_sigs = topo_content_sigs(groups)
        gkeys: List[Optional[tuple]] = []
        gtags = []
        for g, tsig in zip(groups, topo_sigs):
            gk = _req_content_key(g.requirements)
            gkeys.append(gk)
            tolk = (
                tuple(
                    (t.key, t.operator, t.value, t.effect)
                    for t in g.pods[0].spec.tolerations
                )
                if g.pods[0].spec.tolerations
                else ()
            )
            gtags.append(
                (
                    g.count,
                    frozenset(g.requests.items()),
                    gk,
                    tolk,
                    tsig,
                )
            )
        # node identity extensions: hostname joins the tag whenever any
        # group carries topology (n_hcnt/nh_cnt0 rows are keyed by the
        # node's hostname — a positional node swap must break the fast
        # path); volume-ledger state joins when the resource axis carries
        # attach-slot columns (n_avail vol columns derive from it)
        has_topo = any(s is not None for s in topo_sigs)
        vol_cols = any(n.startswith(VOL_RES_PREFIX) for n in resource_names)
        ntags = []
        tkeys = []
        for en in existing_nodes:
            # the bank-sharing key excludes the hostname VALUE (it encodes
            # to the overflow slot identically across nodes) — but only
            # while no hostname value is interned. With one interned (a
            # pod node-selector naming a node), two nodes differing only
            # by hostname encode DIFFERENT mask rows, so the identity tag
            # must carry the full requirement content or a positional
            # node swap would pass the fast path undetected.
            ck = tuple(
                sorted(
                    (
                        r.key, r.complement, tuple(sorted(r.values)),
                        r.greater_than, r.less_than,
                    )
                    for r in en.requirements
                    if hn_interned or r.key != labels_mod.HOSTNAME
                )
            ) + (en.requirements.has(labels_mod.HOSTNAME),)
            ext: tuple = ()
            if has_topo:
                ext += (
                    en.state_node.hostname()
                    if hasattr(en, "state_node")
                    else en.name,
                )
            if vol_cols:
                vu = getattr(en, "volume_usage", None)
                ext += (
                    tuple(sorted((getattr(en, "volume_limits", None) or {}).items())),
                    tuple(sorted(vu.attached_counts().items()))
                    if vu is not None
                    else (),
                )
            ntags.append(
                (
                    ck,
                    tuple(sorted(en.cached_available.items())),
                    tuple(sorted(en.requests.items())),
                )
                + ext
            )
            tkeys.append(
                tuple((t.key, t.value, t.effect) for t in en.cached_taints)
            )
        self._gkeys = gkeys
        self._gtags = tuple(gtags)
        self._ntags = tuple(ntags)
        self._tkeys = tuple(tkeys)
        # tolerance-row bank epoch: rows are [G]-wide and keyed by group
        # toleration content in order, so any group change re-derives them
        tol_epoch = (epoch, tuple(t[3] for t in gtags), len(gtags))
        if tol_epoch != self._tol_epoch:
            self.tol_bank.clear()
            self._tol_epoch = tol_epoch
        groups_unchanged = (
            self._prior_snap is not None and self._gtags == self._prior_gtags
        )
        # count-only churn: same shapes in the same order, different
        # per-group counts — the common steady-state reconcile shape
        groups_shape_unchanged = groups_unchanged or (
            self._prior_snap is not None
            and tuple(t[1:] for t in self._gtags)
            == tuple(t[1:] for t in self._prior_gtags)
        )
        reused = (
            groups_unchanged
            and self._ntags == self._prior_ntags
            and self._tkeys == self._prior_tkeys
        )
        # advance each bank's use clock only when this encode will
        # consult it (eviction horizons count uses, not encodes)
        if not reused and self._banks_on:
            self._nuses += 1
            if not groups_shape_unchanged:
                self._guses += 1
        delta = EncodeDelta(
            reused=reused,
            full=not reused,
            groups_unchanged=groups_unchanged,
            groups_shape_unchanged=groups_shape_unchanged,
            v_static=self.v_static,
            v_groups=self.v_groups,
            v_gcount=self.v_gcount,
            v_nodes=self.v_nodes,
            v_cross=self.v_cross,
        )
        self.last_delta = delta
        return delta

    def reused_snapshot(
        self, groups, templates, instance_types, existing_nodes
    ) -> EncodedSnapshot:
        """The content-hash fast path: prior arrays verbatim, fresh
        metadata so decode binds this solve's pod/node objects (group i
        has the same count and content as last time — begin() proved it —
        so fills map positionally)."""
        import dataclasses

        from .. import faults

        faults.hit(faults.ENCODE_DELTA, reused=True, rows=0, owner=self.owner)
        self._maybe_compact()
        return dataclasses.replace(
            self._prior_snap,
            groups=list(groups),
            templates=list(templates),
            instance_types=list(instance_types),
            existing_names=[en.name for en in existing_nodes],
        )

    # -- bank accessors (called from encode()'s assembly loops) ----------

    def group_rows(self, i: int, vocab: Vocab, reqs, K: int, V1: int):
        """(g_def, g_neg, g_mask) rows for group i, bank-cached by
        requirement content."""
        if not self._banks_on:
            return vocab.encode(reqs, K, V1)
        gk = self._gkeys[i]
        hit = self.group_bank.get(gk)
        if hit is not None:
            self.group_bank[gk] = (self._guses, hit[1])
            return hit[1]
        rows = vocab.encode(reqs, K, V1)
        self.group_bank[gk] = (self._guses, rows)
        return rows

    def node_mask_rows(self, i: int, compute):
        """(n_def, n_mask, n_dzone, n_dct) for ordered node i, bank-cached
        by the node's non-hostname requirement content (the same sharing
        key the per-call row_cache uses); ``compute`` is the from-scratch
        fallback. The quantized capacity rows are NOT banked — they are a
        cheap per-node quantize and their content feeds the node tag, so
        staleness is impossible either way."""
        ck = self._ntags[i][0]
        hit = self.node_bank.get(ck)
        if hit is not None:
            self.node_bank[ck] = (self._nuses, hit[1])
            return hit[1]
        rows = compute()
        self.node_bank[ck] = (self._nuses, rows)
        return rows

    def tol_row(self, i: int, compute) -> np.ndarray:
        """The [G] tolerance row for ordered node i, keyed by taint
        content under the current group-toleration epoch."""
        tkey = self._tkeys[i]
        row = self.tol_bank.get(tkey)
        if row is None:
            row = compute()
            self.tol_bank[tkey] = row
        return row

    # -- delta bookkeeping ------------------------------------------------

    @staticmethod
    def _diff_positions(prev: Optional[tuple], cur: tuple) -> Optional[np.ndarray]:
        if prev is None:
            return None
        m = min(len(prev), len(cur))
        changed = [i for i in range(m) if prev[i] != cur[i]]
        changed.extend(range(m, max(len(prev), len(cur))))
        return np.asarray(changed, dtype=np.int32)

    def finish(self, snap: EncodedSnapshot) -> EncodeDelta:
        """Record this encode's snapshot as the new prior and derive the
        delta report (changed axis positions + class versions)."""
        from .. import faults

        delta = self.last_delta
        node_rows = self._diff_positions(self._prior_ntags, self._ntags)
        group_rows = self._diff_positions(self._prior_gtags, self._gtags)
        count_rows = (
            self._diff_positions(
                tuple(t[0] for t in self._prior_gtags),
                tuple(t[0] for t in self._gtags),
            )
            if delta.groups_shape_unchanged and self._prior_gtags is not None
            else None
        )
        tol_rows = self._diff_positions(self._prior_tkeys, self._tkeys)
        if self._gtags != self._prior_gtags:
            self.v_gcount += 1
            if not delta.groups_shape_unchanged:
                # shapes moved too: every G-side array restages
                self.v_groups += 1
        if self._ntags != self._prior_ntags:
            self.v_nodes += 1
        prior_tolsig = (
            tuple(t[3] for t in self._prior_gtags)
            if self._prior_gtags is not None
            else None
        )
        tolsig = tuple(t[3] for t in self._gtags)
        # topology batches ride the delta contract through their content
        # tags (topo_content_sigs): n_hcnt/nh_cnt0/g_dprior derive from
        # TopoSpec priors that the GROUP sigs now model fully, and the node
        # tags carry the hostname whenever topology is present — so the
        # cross arrays restage only when either side's content moved
        toposig = tuple(t[4] for t in self._gtags)
        prior_toposig = (
            tuple(t[4] for t in self._prior_gtags)
            if self._prior_gtags is not None
            else None
        )
        cross_changed = (
            toposig != prior_toposig
            or self._tkeys != self._prior_tkeys
            or self._ntags != self._prior_ntags
            or tolsig != prior_tolsig
            or (
                self._prior_gtags is not None
                and len(self._gtags) != len(self._prior_gtags)
            )
        )
        if cross_changed or self._prior_gtags is None:
            self.v_cross += 1
        # cross-row delta only when the group axis kept its shape,
        # toleration signature, AND topology signature: then a node x group
        # row changes only via its node's taints or node-content position
        cross_rows = None
        if (
            toposig == prior_toposig
            and tolsig == prior_tolsig
            and node_rows is not None
            and tol_rows is not None
        ):
            cross_rows = np.union1d(node_rows, tol_rows).astype(np.int32)
        had_prior = self._prior_snap is not None
        delta.full = not had_prior
        delta.node_rows = node_rows if had_prior else None
        delta.group_rows = group_rows if had_prior else None
        delta.count_rows = count_rows if had_prior else None
        delta.cross_rows = cross_rows if had_prior else None
        delta.delta_rows = int(
            (len(node_rows) if delta.node_rows is not None else 0)
            + (
                len(count_rows)
                if delta.count_rows is not None
                else (len(group_rows) if delta.group_rows is not None else 0)
            )
            + (len(cross_rows) if delta.cross_rows is not None else 0)
        )
        delta.v_static = self.v_static
        delta.v_groups = self.v_groups
        delta.v_gcount = self.v_gcount
        delta.v_nodes = self.v_nodes
        delta.v_cross = self.v_cross
        self._prior_snap = snap
        self._prior_gtags = self._gtags
        self._prior_ntags = self._ntags
        self._prior_tkeys = self._tkeys
        faults.hit(
            faults.ENCODE_DELTA, reused=False, rows=delta.delta_rows,
            owner=self.owner,
        )
        self._maybe_compact()
        return delta

    def _maybe_compact(self) -> None:
        """Periodic compaction: drop bank entries unused for two
        compaction windows of that bank's OWN use clock, so churn's
        one-off shapes don't accumulate — and a quiet cluster (whose
        encodes never consult a bank) can't age live entries out."""
        for bank, uses in (
            (self.node_bank, self._nuses),
            (self.group_bank, self._guses),
        ):
            if not uses or uses % self.compact_every:
                continue
            horizon = uses - 2 * self.compact_every
            stale = [k for k, (used, _) in bank.items() if used < horizon]
            for k in stale:
                del bank[k]


def _encode_groups(
    groups: List[PodGroup],
    vocab: Vocab,
    cluster: Optional[ClusterEncoding],
    resource_names: Sequence[str],
    K: int,
    V1: int,
    R: int,
    G: int,
):
    """The G-side arrays of one encode (split out of encode() so the
    delta path can skip it whole when the group tags are unchanged).
    ``cluster`` provides the cross-solve requirement-mask bank."""
    g_count = np.array([g.count for g in groups], dtype=np.int32)
    g_req = np.stack(
        [quantize_requests(g.requests, resource_names) for g in groups]
    ) if G else np.zeros((0, R), np.float32)
    g_def = np.zeros((G, K), bool)
    g_neg = np.zeros((G, K), bool)
    g_mask = np.ones((G, K, V1), bool)
    g_hcap = np.full((G,), HCAP_NONE, np.int32)
    g_haff = np.zeros((G,), bool)
    g_dmode = np.zeros((G,), np.int32)
    g_dkey = np.zeros((G,), np.int32)
    g_dskew = np.zeros((G,), np.int32)
    g_dmin0 = np.zeros((G,), bool)
    g_dprior = np.zeros((G, V1), np.int32)
    g_dreg = np.zeros((G, V1), bool)
    g_drank = np.full((G, V1), _DRANK_NONE, np.int32)
    # shared-constraint carry slots, assigned by descriptor identity
    g_hstg = np.full((G,), -1, np.int32)
    g_hscap = np.full((G,), HCAP_NONE, np.int32)
    g_dtg = np.full((G,), -1, np.int32)
    g_hself = np.ones((G,), bool)
    shared_h_descs: List[SharedHostTG] = []
    shared_d_descs: List[SharedDomainTG] = []
    _h_slots: Dict[int, int] = {}
    _d_slots: Dict[int, int] = {}

    def _h_slot(desc: SharedHostTG) -> int:
        j = _h_slots.setdefault(id(desc), len(_h_slots))
        if j == len(shared_h_descs):
            shared_h_descs.append(desc)
        return j

    def _d_slot(desc: SharedDomainTG) -> int:
        j = _d_slots.setdefault(id(desc), len(_d_slots))
        if j == len(shared_d_descs):
            shared_d_descs.append(desc)
        return j

    for i, g in enumerate(groups):
        t = g.topo
        if t is None:
            continue
        if t.shared_h is not None:
            g_hstg[i] = _h_slot(t.shared_h)
            g_hscap[i] = t.h_capval if t.h_capval is not None else t.shared_h.cap
            g_hself[i] = t.h_self
        if t.shared_d is not None:
            g_dtg[i] = _d_slot(t.shared_d)
        for desc in t.contrib_h:
            _h_slot(desc)
        for desc in t.contrib_d:
            _d_slot(desc)
    JH = max(len(shared_h_descs), 1)
    JD = max(len(_d_slots), 1)
    dd0 = np.zeros((JD, V1), np.int32)
    dtg_key = np.zeros((JD,), np.int32)
    for j, desc in enumerate(shared_d_descs):
        dtg_key[j] = 0 if desc.key == labels_mod.TOPOLOGY_ZONE else 1
    # contribution rows: slots this group's placements count toward (the
    # oracle's record() rule, scheduling/topology.py:491-498)
    g_hcontrib = np.zeros((G, JH), bool)
    g_dcontrib = np.zeros((G, JD), bool)
    for i, g in enumerate(groups):
        t = g.topo
        if t is None:
            continue
        for desc in t.contrib_h:
            g_hcontrib[i, _h_slots[id(desc)]] = True
        for desc in t.contrib_d:
            g_dcontrib[i, _d_slots[id(desc)]] = True
    for i, g in enumerate(groups):
        if cluster is not None:
            g_def[i], g_neg[i], g_mask[i] = cluster.group_rows(
                i, vocab, g.requirements, K, V1
            )
        else:
            g_def[i], g_neg[i], g_mask[i] = vocab.encode(g.requirements, K, V1)
        if g.topo is not None:
            if g.topo.host_cap is not None:
                g_hcap[i] = g.topo.host_cap
            g_haff[i] = g.topo.haff
            if g.topo.dmode != DMODE_NONE:
                t = g.topo
                g_dmode[i] = t.dmode
                g_dkey[i] = 0 if t.dkey == labels_mod.TOPOLOGY_ZONE else 1
                g_dskew[i] = min(t.dskew, HCAP_NONE)
                g_dmin0[i] = t.dmin0
                # rank = sorted-domain order, the oracle's tie-break and
                # bootstrap preference (topologygroup.go:291-324)
                for rank, d in enumerate(sorted(t.dreg)):
                    vid = vocab.value_id(t.dkey, d)
                    g_dreg[i, vid] = True
                    g_drank[i, vid] = rank
                    g_dprior[i, vid] = t.dprior.get(d, 0)
    return (
        g_count, g_req, g_def, g_neg, g_mask, g_hcap, g_haff,
        g_dmode, g_dkey, g_dskew, g_dmin0, g_dprior, g_dreg, g_drank,
        g_hstg, g_hscap, g_dtg, g_hself, g_hcontrib, g_dcontrib,
        dd0, dtg_key, shared_h_descs, JH,
    )


def encode(
    groups: List[PodGroup],
    templates: List[NodeClaimTemplate],
    instance_types_by_pool: Dict[str, List[cp.InstanceType]],
    existing_nodes: Sequence = (),
    daemon_overhead: Optional[Dict] = None,
    pool_limits: Optional[Dict[str, res.ResourceList]] = None,
    vocab: Optional[Vocab] = None,
    cache: Optional[dict] = None,
    cluster: Optional[ClusterEncoding] = None,
) -> EncodedSnapshot:
    """Encode a snapshot. ``vocab``/``cache`` (both owned by one TpuSolver)
    let repeat solves skip the instance-type/template side: those arrays
    only depend on the vocab padding (K, V1) and the resource axis, both of
    which are part of the cache key — value ids assigned to NEW group values
    land inside the existing padding, where cached IN-masks are already
    False (non-matching) and complement masks already True (matching), so
    reuse is exact."""
    cache = cache if cache is not None else {}
    if vocab is None:
        vocab = Vocab()
    # pin the topology keys so ids are stable
    zone_kid = vocab.key_id(labels_mod.TOPOLOGY_ZONE)
    ct_kid = vocab.key_id(labels_mod.CAPACITY_TYPE_LABEL_KEY)

    # union of all instance types, stable order, deduped by name
    instance_types = cache.get("instance_types")
    if instance_types is None:
        seen = {}
        for its in instance_types_by_pool.values():
            for it in its:
                seen.setdefault(it.name, it)
        instance_types = cache["instance_types"] = list(seen.values())

    # Constraint-side entities register values; provider-side entities only
    # register keys and fall back to the overflow slot (see Vocab.observe) —
    # this keeps the value axis independent of the instance-type count.
    for g in groups:
        vocab.observe(g.requirements)
        if g.topo is not None and g.topo.dmode != DMODE_NONE:
            # domain-constraint universes must be interned before the
            # padded shape is fixed; sorted so value-id assignment (the
            # water-fill's deficit tie-break) is deterministic across
            # processes and matches the oracle's sorted-domain order
            for d in sorted(g.topo.dreg | set(g.topo.dprior)):
                vocab.value_id(g.topo.dkey, d)
    if not cache.get("static_observed"):
        for nct in templates:
            vocab.observe(nct.requirements)
        for it in instance_types:
            vocab.observe_keys(it.requirements)
            for o in it.offerings:
                # zone/capacity-type values are indexed by the offering tables
                z = o.requirements.get(labels_mod.TOPOLOGY_ZONE)
                c = o.requirements.get(labels_mod.CAPACITY_TYPE_LABEL_KEY)
                for v in z.values:
                    vocab.value_id(labels_mod.TOPOLOGY_ZONE, v)
                for v in c.values:
                    vocab.value_id(labels_mod.CAPACITY_TYPE_LABEL_KEY, v)
        for en in existing_nodes:
            # ExistingNode models (scheduling/inflight.py); their requirement
            # keys come from concrete node labels. Zone / capacity-type
            # values are interned so nodes index into the domain axis.
            vocab.observe_keys(en.requirements)
            _observe_node_domains(vocab, en)
        cache["static_observed"] = True
    else:
        for en in existing_nodes:
            vocab.observe_keys(en.requirements)
            _observe_node_domains(vocab, en)

    K, V1 = vocab.padded_shape()
    static_names = cache.get("static_names")
    if static_names is None:
        static_names = cache["static_names"] = res.resource_names(
            [it.capacity for it in instance_types]
            + ([daemon_overhead[nct] for nct in templates] if daemon_overhead else [])
        )
    extras = [
        n
        for n in res.resource_names([g.requests for g in groups])
        if n not in static_names
    ]
    resource_names = static_names + extras if extras else static_names
    R = len(resource_names)
    G, T, P, N = len(groups), len(instance_types), len(templates), len(existing_nodes)

    # content-shared node rows (see the existing-nodes section below) are
    # keyed on non-hostname label shapes; an interned hostname value (a pod
    # node-selector naming a node) disables sharing for this encode
    hn_kid = vocab.key_ids.get(labels_mod.HOSTNAME)
    hn_interned = bool(vocab.values[hn_kid]) if hn_kid is not None else False

    delta = None
    if cluster is not None:
        delta = cluster.begin(
            vocab, K, V1, resource_names, groups, existing_nodes,
            daemon_overhead, pool_limits, hn_interned,
        )
        if delta.reused:
            # content-hash fast path: nothing row-relevant changed since
            # the previous encode — prior arrays verbatim, fresh metadata
            return cluster.reused_snapshot(
                groups, templates, instance_types, existing_nodes
            )

    # -- groups -----------------------------------------------------------
    p_tol_reuse = None
    if delta is not None and delta.groups_shape_unchanged:
        # every group SHAPE tag (requests, requirement content,
        # tolerations, no-topology) matched the prior encode: the G-side
        # arrays are byte-identical by construction, so share them;
        # count-only churn (the steady-state reconcile shape) rebuilds
        # just the [G] count vector
        ps = cluster._prior_snap
        g_count = (
            ps.g_count
            if delta.groups_unchanged
            else np.array([g.count for g in groups], dtype=np.int32)
        )
        g_req = ps.g_req
        g_def, g_neg, g_mask = ps.g_def, ps.g_neg, ps.g_mask
        g_hcap, g_haff = ps.g_hcap, ps.g_haff
        g_dmode, g_dkey, g_dskew = ps.g_dmode, ps.g_dkey, ps.g_dskew
        g_dmin0, g_dprior, g_dreg = ps.g_dmin0, ps.g_dprior, ps.g_dreg
        g_drank = ps.g_drank
        g_hstg, g_hscap, g_dtg = ps.g_hstg, ps.g_hscap, ps.g_dtg
        g_hself = ps.g_hself
        g_hcontrib, g_dcontrib = ps.g_hcontrib, ps.g_dcontrib
        dd0, dtg_key = ps.dd0, ps.dtg_key
        shared_h_descs = []
        JH = g_hcontrib.shape[1]
        p_tol_reuse = ps.p_tol
    else:
        g_count, g_req, g_def, g_neg, g_mask, g_hcap, g_haff, \
            g_dmode, g_dkey, g_dskew, g_dmin0, g_dprior, g_dreg, g_drank, \
            g_hstg, g_hscap, g_dtg, g_hself, g_hcontrib, g_dcontrib, \
            dd0, dtg_key, shared_h_descs, JH = _encode_groups(
                groups, vocab, cluster, resource_names, K, V1, R, G
            )

    # -- instance types + templates (static side, cached per padding) -----
    static_key = (K, V1, tuple(resource_names))
    static = cache.get(static_key)
    if static is None:
        t_alloc = np.stack(
            [quantize_capacity(it.allocatable(), resource_names) for it in instance_types]
        ) if T else np.zeros((0, R), np.float32)
        t_cap = np.stack(
            [quantize_capacity(it.capacity, resource_names) for it in instance_types]
        ) if T else np.zeros((0, R), np.float32)
        # synthetic volume-attach columns: fresh claims have no CSINode, so
        # their capacity is the no-limit sentinel (volumeusage.py: limits
        # only apply to existing nodes); node columns are filled per encode
        # below from the live attach ledger
        for ri, rn in enumerate(resource_names):
            if rn.startswith(VOL_RES_PREFIX):
                if T:
                    t_alloc[:, ri] = VOL_UNLIMITED
                    t_cap[:, ri] = VOL_UNLIMITED
        t_def = np.zeros((T, K), bool)
        t_mask = np.ones((T, K, V1), bool)
        for i, it in enumerate(instance_types):
            t_def[i], _, t_mask[i] = vocab.encode(it.requirements, K, V1)

        O = _next_pow2(max((len(it.offerings) for it in instance_types), default=1))
        o_avail = np.zeros((T, O), bool)
        o_zone = np.full((T, O), -1, np.int32)
        o_ct = np.full((T, O), -1, np.int32)
        o_price = np.full((T, O), np.inf, np.float32)
        t_price = np.full((T,), np.inf, np.float32)
        for i, it in enumerate(instance_types):
            for j, o in enumerate(it.offerings):
                o_avail[i, j] = o.available
                o_price[i, j] = o.price
                z = o.requirements.get(labels_mod.TOPOLOGY_ZONE)
                c = o.requirements.get(labels_mod.CAPACITY_TYPE_LABEL_KEY)
                if not z.complement and len(z.values) == 1:
                    o_zone[i, j] = vocab.value_id(
                        labels_mod.TOPOLOGY_ZONE, next(iter(z.values))
                    )
                if not c.complement and len(c.values) == 1:
                    o_ct[i, j] = vocab.value_id(
                        labels_mod.CAPACITY_TYPE_LABEL_KEY, next(iter(c.values))
                    )
                if o.available and o.price < t_price[i]:
                    t_price[i] = o.price

        p_def = np.zeros((P, K), bool)
        p_neg = np.zeros((P, K), bool)
        p_mask = np.ones((P, K, V1), bool)
        p_daemon = np.zeros((P, R), np.float32)
        p_limit = np.full((P, R), np.inf, np.float32)
        p_has_limit = np.zeros((P,), bool)
        p_titype_ok = np.zeros((P, T), bool)
        type_index = {it.name: i for i, it in enumerate(instance_types)}
        for i, nct in enumerate(templates):
            p_def[i], p_neg[i], p_mask[i] = vocab.encode(nct.requirements, K, V1)
            if daemon_overhead and nct in daemon_overhead:
                p_daemon[i] = quantize_requests(daemon_overhead[nct], resource_names)
            limits = (pool_limits or {}).get(nct.node_pool_name)
            if limits:
                p_has_limit[i] = True
                # remaining-limit accounting is in capacity units (floor)
                for ri, rn in enumerate(resource_names):
                    if rn in limits:
                        p_limit[i, ri] = limits[rn] // _unit_divisor(rn)
            for it in nct.instance_type_options:
                p_titype_ok[i, type_index[it.name]] = True

        # dense minValues tables (ISSUE 10): distinct-value counting over
        # the per-key catalog value universe replaces the host-side
        # serialization the driver used to force for reachable minValues
        # pools. Values get their own per-key index (NOT the shared vocab:
        # provider-side values land in the overflow slot there, which
        # cannot count distinct values).
        mv_keys = sorted(
            {
                r.key
                for nct in templates
                for r in nct.requirements
                if r.min_values is not None
            }
        )
        MV = len(mv_keys)
        mv_vals: List[Dict[str, int]] = []
        for key in mv_keys:
            vals: Dict[str, int] = {}
            for it in instance_types:
                for v in sorted(it.requirements.get(key).values_list()):
                    vals.setdefault(v, len(vals))
            mv_vals.append(vals)
        W = _next_pow2(max((len(v) for v in mv_vals), default=1), floor=1)
        p_mvmin = np.zeros((P, max(MV, 0)), np.int32)
        t_mvoh = np.zeros((T, max(MV, 0), W), bool)
        for j, key in enumerate(mv_keys):
            for i, nct in enumerate(templates):
                r = (
                    nct.requirements.get(key)
                    if nct.requirements.has(key)
                    else None
                )
                if r is not None and r.min_values is not None:
                    p_mvmin[i, j] = r.min_values
            for t, it in enumerate(instance_types):
                for v in it.requirements.get(key).values_list():
                    t_mvoh[t, j, mv_vals[j][v]] = True

        static = cache[static_key] = (
            t_alloc, t_cap, t_def, t_mask, t_price,
            o_avail, o_zone, o_ct, o_price,
            p_def, p_neg, p_mask, p_daemon, p_limit, p_has_limit, p_titype_ok,
            p_mvmin, t_mvoh,
        )
    (t_alloc, t_cap, t_def, t_mask, t_price,
     o_avail, o_zone, o_ct, o_price,
     p_def, p_neg, p_mask, p_daemon, p_limit, p_has_limit, p_titype_ok,
     p_mvmin, t_mvoh) = static

    # -- template/group tolerance (depends on this solve's groups) --------
    if p_tol_reuse is not None:
        p_tol = p_tol_reuse
    else:
        p_tol = np.zeros((P, max(G, 1)), bool)
        for i, nct in enumerate(templates):
            for gi, g in enumerate(groups):
                p_tol[i, gi] = (
                    taints_mod.tolerates(nct.taints, g.pods[0].spec.tolerations)
                    is None
                )

    # -- existing nodes ---------------------------------------------------
    n_avail = np.zeros((N, R), np.float32)
    n_base = np.zeros((N, R), np.float32)
    n_def = np.zeros((N, K), bool)
    n_mask = np.ones((N, K, V1), bool)
    n_tol = np.zeros((N, max(G, 1)), bool)
    n_hcnt = np.zeros((N, max(G, 1)), np.int32)
    n_dzone = np.full((N,), -1, np.int32)
    n_dct = np.full((N,), -1, np.int32)
    nh_cnt0 = np.zeros((N, JH), np.int32)
    existing_names = []
    # content-shared node rows: fleets are homogeneous (a 2k-node cluster
    # snapshot typically carries a handful of distinct label shapes), so
    # the mask rows are computed once per distinct requirement content and
    # copied per node. The hostname requirement is excluded from the key:
    # hostname values are provider-side and encode to the OVERFLOW slot,
    # identical across nodes — UNLESS some hostname value has been interned
    # (a pod node-selector naming a node), which disables sharing for this
    # encode (hn_interned was derived above, before the delta fast path).
    # Caches are per-call: the vocab is stable here (all observation
    # happened above); cross-call reuse is the _enc_rows stash's job —
    # or the ClusterEncoding banks' when a ``cluster`` is leased.
    row_cache: Dict[tuple, tuple] = {}
    tol_cache: Dict[tuple, np.ndarray] = {}
    # groups with hostname-topology priors, walked per node; everything
    # else in the per-node group loop is the tolerance row (cached by
    # taint content below)
    topo_gis = [
        gi
        for gi, g in enumerate(groups)
        if g.topo is not None and (g.topo.host_counts or g.topo.haff_prior)
    ]
    # synthetic volume-attach columns (node side): remaining CSINode attach
    # slots per driver. Overwritten AFTER any cached-row retrieval — the
    # per-node row stashes/banks key on capacity+requests content, not the
    # volume ledger, so the columns are recomputed per encode (cheap) and
    # staleness is impossible.
    vol_cols = [
        (ri, rn[len(VOL_RES_PREFIX):])
        for ri, rn in enumerate(resource_names)
        if rn.startswith(VOL_RES_PREFIX)
    ]
    for i, en in enumerate(existing_nodes):
        # `en` is a scheduling.inflight.ExistingNode (carries the remaining
        # daemon requests and cached availability)
        existing_names.append(en.name)
        # per-node rows stash on the StateNode snapshot object:
        # consolidation's binary search re-encodes the SAME frozen snapshot
        # nodes once per probe, and these per-node Python/vocab walks
        # dominated the probe's encode. Safe because cluster.nodes() hands
        # each solve fresh deep copies (stale stashes die with their
        # snapshot), node label requirements are positive-only (rows are
        # stable under vocab growth at fixed K/V1), and the tag pins the
        # vocab instance, array shapes, and the daemon remainder.
        if cluster is not None and cluster._banks_on:
            # delta path: quantized rows are recomputed (cheap, and their
            # content is part of the node tag), the mask rows ride the
            # cross-solve content bank
            n_avail[i] = quantize_capacity(en.cached_available, resource_names)
            n_base[i] = quantize_requests(en.requests, resource_names)

            def _mask_rows(en=en):
                ndef, _, nmask = vocab.encode(en.requirements, K, V1)
                return (
                    ndef, nmask,
                    _node_domain_id(vocab, en, labels_mod.TOPOLOGY_ZONE),
                    _node_domain_id(
                        vocab, en, labels_mod.CAPACITY_TYPE_LABEL_KEY
                    ),
                )

            (n_def[i], n_mask[i], n_dzone[i],
             n_dct[i]) = cluster.node_mask_rows(i, _mask_rows)
            sn = None
            cached = tag = None
        else:
            sn = getattr(en, "state_node", None)
            tag = (
                vocab.serial, K, V1, tuple(resource_names),
                tuple(sorted(en.requests.items())),
            )
            cached = getattr(sn, "_enc_rows", None) if sn is not None else None
        if cached is not None and cached[0] == tag:
            (n_avail[i], n_base[i], n_def[i], n_mask[i], n_dzone[i],
             n_dct[i]) = cached[1]
        elif tag is not None:
            n_avail[i] = quantize_capacity(en.cached_available, resource_names)
            n_base[i] = quantize_requests(en.requests, resource_names)
            ck = None
            rows = None
            if not hn_interned:
                ck = tuple(
                    sorted(
                        (
                            r.key, r.complement, tuple(sorted(r.values)),
                            r.greater_than, r.less_than,
                        )
                        for r in en.requirements
                        if r.key != labels_mod.HOSTNAME
                    )
                ) + (en.requirements.has(labels_mod.HOSTNAME),)
                rows = row_cache.get(ck)
            if rows is None:
                n_def[i], _, n_mask[i] = vocab.encode(en.requirements, K, V1)
                n_dzone[i] = _node_domain_id(
                    vocab, en, labels_mod.TOPOLOGY_ZONE
                )
                n_dct[i] = _node_domain_id(
                    vocab, en, labels_mod.CAPACITY_TYPE_LABEL_KEY
                )
                if ck is not None:
                    row_cache[ck] = (
                        n_def[i].copy(), n_mask[i].copy(),
                        n_dzone[i], n_dct[i],
                    )
            else:
                n_def[i], n_mask[i], n_dzone[i], n_dct[i] = rows
            if sn is not None:
                sn._enc_rows = (
                    tag,
                    (n_avail[i].copy(), n_base[i].copy(), n_def[i].copy(),
                     n_mask[i].copy(), n_dzone[i], n_dct[i]),
                )
        for ri, drv in vol_cols:
            vu = getattr(en, "volume_usage", None)
            limit = (getattr(en, "volume_limits", None) or {}).get(drv)
            if limit is None:
                n_avail[i, ri] = VOL_UNLIMITED
            else:
                used = vu.attached_count(drv) if vu is not None else 0
                n_avail[i, ri] = max(limit - used, 0)
            n_base[i, ri] = 0.0
        if shared_h_descs:
            hostname = (
                en.state_node.hostname() if hasattr(en, "state_node") else en.name
            )
            for j, desc in enumerate(shared_h_descs):
                nh_cnt0[i, j] = desc.counts.get(hostname, 0)
        if G:

            def _trow(en=en):
                return np.fromiter(
                    (
                        taints_mod.tolerates(
                            en.cached_taints, g.pods[0].spec.tolerations
                        )
                        is None
                        for g in groups
                    ),
                    bool,
                    G,
                )

            if cluster is not None:
                # cross-solve tolerance bank, keyed by taint content under
                # the current group-toleration epoch (begin() cleared it
                # if the group axis changed)
                n_tol[i, :G] = cluster.tol_row(i, _trow)
            else:
                tkey = tuple(
                    (t.key, t.value, t.effect) for t in en.cached_taints
                )
                trow = tol_cache.get(tkey)
                if trow is None:
                    trow = _trow()
                    tol_cache[tkey] = trow
                n_tol[i, :G] = trow
        for gi in topo_gis:
            g = groups[gi]
            # hostname domains are the node's hostname label (node name
            # as fallback), mirroring Topology._count_domains. For haff
            # groups the row holds the affinity matching-pod priors
            # (the cap/affinity combo is demoted, so no overlap).
            domain = (
                en.state_node.hostname()
                if hasattr(en, "state_node")
                else en.name
            )
            n_hcnt[i, gi] = (
                g.topo.haff_prior.get(domain, 0)
                if g.topo.haff
                else g.topo.host_counts.get(domain, 0)
            )

    if p_tol_reuse is not None and cluster is not None:
        # group shapes unchanged: the index is a pure function of the
        # reused g_def/g_neg/g_mask rows, so the prior arrays are exact
        ps = cluster._prior_snap
        gk_g, gk_k, gk_w, goff_idx = ps.gk_g, ps.gk_k, ps.gk_w, ps.goff_idx
    else:
        gk_g, gk_k, gk_w, goff_idx = build_segment_index(
            g_def, g_neg, g_mask, zone_kid, ct_kid
        )

    snap = EncodedSnapshot(
        vocab=vocab,
        resource_names=resource_names,
        groups=groups,
        templates=templates,
        instance_types=instance_types,
        existing_names=existing_names,
        g_count=g_count,
        g_req=g_req,
        g_def=g_def,
        g_neg=g_neg,
        g_mask=g_mask,
        g_hcap=g_hcap,
        g_haff=g_haff,
        n_hcnt=n_hcnt,
        g_dmode=g_dmode,
        g_dkey=g_dkey,
        g_dskew=g_dskew,
        g_dmin0=g_dmin0,
        g_dprior=g_dprior,
        g_dreg=g_dreg,
        g_drank=g_drank,
        n_dzone=n_dzone,
        n_dct=n_dct,
        g_hstg=g_hstg,
        g_hscap=g_hscap,
        g_dtg=g_dtg,
        g_hself=g_hself,
        g_hcontrib=g_hcontrib,
        g_dcontrib=g_dcontrib,
        nh_cnt0=nh_cnt0,
        dd0=dd0,
        dtg_key=dtg_key,
        t_alloc=t_alloc,
        t_cap=t_cap,
        t_def=t_def,
        t_mask=t_mask,
        t_price=t_price,
        o_avail=o_avail,
        o_zone=o_zone,
        o_ct=o_ct,
        o_price=o_price,
        p_def=p_def,
        p_neg=p_neg,
        p_mask=p_mask,
        p_daemon=p_daemon,
        p_limit=p_limit,
        p_has_limit=p_has_limit,
        p_titype_ok=p_titype_ok,
        p_tol=p_tol,
        p_mvmin=p_mvmin,
        t_mvoh=t_mvoh,
        n_avail=n_avail,
        n_base=n_base,
        n_def=n_def,
        n_mask=n_mask,
        n_tol=n_tol,
        zone_kid=zone_kid,
        ct_kid=ct_kid,
        well_known=vocab.well_known_mask(K),
        gk_g=gk_g,
        gk_k=gk_k,
        gk_w=gk_w,
        goff_idx=goff_idx,
    )
    if cluster is not None:
        cluster.finish(snap)
    return snap


def class_partition(snap: "EncodedSnapshot", min_mean_size: float = 0.0):
    """Partition the (FFD-sorted, possibly padded) group axis into
    contiguous feasibility classes for ops/packing.py:pack_classed.

    With ``min_mean_size`` > 0 (the driver's auto-routing threshold), the
    partition bails out with None right after the vectorized signature
    pass when even the signature-run count proves the mean class size
    below the threshold — dkey splits and padding-class exclusion only
    INCREASE the class count, so this is a safe upper bound, and the
    rejected shapes (every group its own class, e.g. consolidation
    probes) skip the per-run Python walk entirely.

    Two adjacent groups share a class when every class-invariant input the
    kernel's head tables derive from is identical: requests (g_req),
    requirement masks (g_def/g_neg/g_mask), template tolerations (p_tol
    column), and node tolerations (n_tol column). A run additionally
    breaks when its dynamic (domain-keyed) members would mix axes — the
    head's per-domain tables are built for ONE axis per class.

    Returns (class_start, class_len, class_dyn, class_dkey, inv_idx, lmax)
    as numpy arrays / int, with the class axis padded to a power of two and
    lmax the power-of-two member-capacity bucket. Classes whose members
    are all count-0 padding get len 0 (the kernel skips them whole).
    """
    G = len(snap.g_count)
    # vectorized adjacent-equality over every class-invariant input: this
    # runs on the solve hot path for EVERY routed batch (including ones
    # the heuristic then sends to pack()), so no per-group Python loop
    same = np.zeros((G,), bool)
    if G > 1:
        same[1:] = (
            (snap.g_req[1:] == snap.g_req[:-1]).all(axis=1)
            & (snap.g_def[1:] == snap.g_def[:-1]).all(axis=1)
            & (snap.g_neg[1:] == snap.g_neg[:-1]).all(axis=1)
            & (snap.g_mask[1:] == snap.g_mask[:-1]).all(axis=(1, 2))
            & (snap.p_tol[:, 1:] == snap.p_tol[:, :-1]).all(axis=0)
        )
        if snap.n_tol.size:
            same[1:] &= (snap.n_tol[:, 1:] == snap.n_tol[:, :-1]).all(axis=0)
    sig_starts = np.flatnonzero(~same)
    if min_mean_size > 0:
        n_real_groups = len(snap.groups)
        if (
            not len(sig_starts)
            or n_real_groups / len(sig_starts) < min_mean_size
        ):
            return None
    dyn_g = np.asarray(snap.g_dmode) > 0
    dk_g = np.where(dyn_g, np.asarray(snap.g_dkey), -1)
    starts: List[int] = []
    lens: List[int] = []
    dyns: List[bool] = []
    dkeys: List[int] = []
    for ri, s in enumerate(sig_starts):
        e = sig_starts[ri + 1] if ri + 1 < len(sig_starts) else G
        # split the run wherever a dynamic member's axis conflicts with
        # the run's current one (the head's per-domain tables serve a
        # single axis per class); conflicts are rare, so the split walk
        # touches only the offending runs
        while s < e:
            dk_run = dk_g[s:e]
            dyn_idx = np.flatnonzero(dk_run >= 0)
            if dyn_idx.size:
                first_dk = dk_run[dyn_idx[0]]
                conflicts = dyn_idx[dk_run[dyn_idx] != first_dk]
                cut = int(conflicts[0]) if conflicts.size else e - s
            else:
                first_dk = -1
                cut = e - s
            starts.append(int(s))
            lens.append(int(cut))
            dyns.append(bool(dyn_idx.size and dyn_idx[0] < cut))
            dkeys.append(int(first_dk))
            s += cut
    # classes of pure padding (all counts 0) are skipped whole; their
    # original spans still map groups for inv_idx below
    spans = list(lens)
    for ci in range(len(starts)):
        s, l = starts[ci], lens[ci]
        if not snap.g_count[s : s + l].any():
            lens[ci] = 0
    n_real = len(starts)
    lmax = _next_pow2(max(lens) if lens else 1, floor=1)
    C = _next_pow2(n_real, floor=1)
    class_start = np.zeros((C,), np.int32)
    class_len = np.zeros((C,), np.int32)
    class_dyn = np.zeros((C,), bool)
    class_dkey = np.zeros((C,), np.int32)
    class_start[:n_real] = starts
    class_len[:n_real] = lens
    class_dyn[:n_real] = dyns
    class_dkey[:n_real] = np.maximum(dkeys, 0)
    # group gi of class ci at member offset j reads buffer row ci*lmax + j;
    # len-0 (padding) classes point at their cond-skipped zero rows, which
    # is correct for count-0 groups
    spans_arr = np.asarray(spans, np.int64)
    ci_of_g = np.repeat(np.arange(n_real, dtype=np.int64), spans_arr)
    j_of_g = np.arange(G, dtype=np.int64) - np.repeat(
        np.asarray(starts, np.int64), spans_arr
    )
    inv_idx = (ci_of_g * lmax + np.minimum(j_of_g, lmax - 1)).astype(np.int32)
    return class_start, class_len, class_dyn, class_dkey, inv_idx, lmax


def build_groups(pods: Sequence[Pod]) -> List[PodGroup]:
    """Group tensorizable pods into equivalence classes, FFD-ordered."""
    groups, rest = partition_and_group(pods)
    assert not rest, "build_groups expects pre-filtered tensorizable pods"
    return groups


def ffd_sort_key(g: "PodGroup"):
    """FFD pack-order key over groups: cpu desc, then memory desc
    (queue.go:76-112). The kernel scan processes groups in this order, and
    the shared-constraint admission guard in _resolve_topology reasons about
    pack order with this same key — keep them identical."""
    return (
        -g.requests.get(res.CPU, 0),
        -g.requests.get(res.MEMORY, 0),
    )


def partition_and_group(
    pods: Sequence[Pod],
    topology=None,
    merge_bootstrap_affinity: bool = True,
    volume_shapes: Optional[Dict[str, tuple]] = None,
) -> Tuple[List[PodGroup], List[Pod]]:
    """One pass over the batch: route non-tensorizable pods to the host
    oracle and group the rest into equivalence classes, FFD-ordered
    (queue.go:76-112). Fused because both checks walk the same 50k specs.

    With a ``topology`` (scheduling.topology.Topology, already updated with
    every pending pod), pods whose topology constraints the kernel models
    are admitted too, then re-checked globally:

    - a constraint's selector must match only its own group's pending pods
      (self-selecting) or none at all — cross-group selection serializes
      through the oracle;
    - any oracle-routed pod whose topology selectors match a tensorized
      group demotes that group (the oracle cannot see TPU placements);
    - inverse anti-affinity from already-bound cluster pods demotes the
      groups it selects (their placements are gated node-by-node).
    """
    by_key: Dict[tuple, PodGroup] = {}
    rest: List[Pod] = []
    allow_topo = topology is not None
    # label keys referenced by any pending forward constraint's selector:
    # constraint-FREE pods must additionally group on (namespace, these
    # labels) so selector matching — and hence the contributor role in the
    # shared-constraint carries — is uniform per group. Empty for
    # constraint-free batches, preserving the hot-path key shape.
    sel_keys = None
    if allow_topo and topology.topology_groups:
        keys = set()
        for tg in topology.topology_groups.values():
            sel = tg.selector
            if sel is None:
                continue
            keys.update(sel.match_labels)
            keys.update(e.key for e in sel.match_expressions)
        if keys:
            sel_keys = frozenset(keys)
    # fused per-pod check + key build: this loop walks every spec in a 50k
    # batch, so the common no-constraint shape takes one attribute sweep
    # (is_tensorizable + group_key stay the semantic reference and serve
    # the uncommon shapes)
    rest_append = rest.append
    get_group = by_key.get
    # routing verdicts memoize on the pod object, validated against the
    # store's resource_version (client.update bumps it, invalidating the
    # entry): the provisioner re-walks long-pending pods every batch and
    # consolidation's binary search re-walks the same reschedulable pods
    # once per probe. Oracle-side relaxation mutates pods WITHOUT a store
    # update, but only ever pods already cached non-tensorizable — a stale
    # verdict there keeps them oracle-routed (slower, never wrong).
    gk_attr = "_gk_cache" if allow_topo else "_gk_cache_nt"
    for pod in pods:
        if pod.spec.volumes:
            # volume routing is BATCH-dependent (cross-pod volume sharing
            # and already-attached volumes break the dense ledger), so the
            # verdict comes from the driver's per-solve resolution map and
            # is never memoized on the pod
            spec0 = pod.spec
            vs = volume_shapes.get(pod.uid) if volume_shapes else None
            if vs is None or not is_tensorizable(
                pod, allow_topology=allow_topo, allow_volumes=True
            ):
                rest_append(pod)
                continue
            key = group_key(pod) + ("__vol__", vs[0])
            if sel_keys and not (
                spec0.topology_spread_constraints
                or spec0.pod_anti_affinity
                or spec0.pod_affinity
            ):
                key = key + _sel_signature(pod, sel_keys)
            g = get_group(key)
            if g is None:
                req = dict(spec0.requests)
                for rn, rv in vs[1].items():
                    req[rn] = req.get(rn, 0) + rv
                by_key[key] = PodGroup([pod], pod_requirements(pod), req)
            else:
                g.pods.append(pod)
            continue
        cached = getattr(pod, gk_attr, None)
        key = None
        if (
            cached is not None
            and cached[0] == pod.metadata.resource_version
            and cached[1] == sel_keys
        ):
            key = cached[2]
            if key == _NOT_TENSORIZABLE:
                rest_append(pod)
                continue
        if key is None:
            spec = pod.spec
            affinity = spec.node_affinity
            if (
                spec.topology_spread_constraints
                or spec.pod_anti_affinity
                or spec.pod_affinity
                or spec.preferred_pod_affinity
                or spec.preferred_pod_anti_affinity
                or spec.host_ports
                or spec.volumes
            ):
                if not is_tensorizable(pod, allow_topology=allow_topo):
                    object.__setattr__(
                        pod, gk_attr,
                        (pod.metadata.resource_version, sel_keys,
                         _NOT_TENSORIZABLE),
                    )
                    rest_append(pod)
                    continue
                key = group_key(pod)
            elif affinity is not None:
                if not is_tensorizable(pod, allow_topology=allow_topo):
                    object.__setattr__(
                        pod, gk_attr,
                        (pod.metadata.resource_version, sel_keys,
                         _NOT_TENSORIZABLE),
                    )
                    rest_append(pod)
                    continue
                key = group_key(pod)
                if sel_keys:
                    key = key + _sel_signature(pod, sel_keys)
            else:
                # constraint-free fast shape: selector/tolerations only
                sel = spec.node_selector
                tol = spec.tolerations
                key = (
                    frozenset(spec.requests.items()),
                    frozenset(sel.items()) if sel else (),
                    (),
                    frozenset(
                        (t.key, t.operator, t.value, t.effect) for t in tol
                    ) if tol else (),
                )
                if sel_keys:
                    key = key + _sel_signature(pod, sel_keys)
            object.__setattr__(
                pod, gk_attr, (pod.metadata.resource_version, sel_keys, key)
            )
        g = get_group(key)
        if g is None:
            by_key[key] = PodGroup(
                [pod], pod_requirements(pod), dict(pod.spec.requests)
            )
        else:
            g.pods.append(pod)
    groups = list(by_key.values())
    if allow_topo and (topology.topology_groups or topology.inverse_topology_groups):
        # Constraint-free batches skip the cross-group resolution entirely:
        # an empty forward-group map means no pending pod owns a topology
        # constraint (Topology.update registered every pending pod before
        # this call), and an empty inverse map means no bound pod's
        # anti-affinity can gate placements — so there is nothing to demote
        # and no TopoSpec to build.
        groups, demoted = _resolve_topology(
            groups, rest, topology,
            merge_bootstrap_affinity=merge_bootstrap_affinity,
        )
        rest.extend(demoted)
    # FFD order over groups: cpu desc, then memory desc (queue.go:76-112)
    groups.sort(key=ffd_sort_key)
    return groups, rest


def _pod_constraint_selectors(pod: Pod):
    """(namespaces, selector) for every topology constraint on the pod,
    including preferred terms (they own TopologyGroups too)."""
    spec = pod.spec
    ns = pod.metadata.namespace
    for tsc in spec.topology_spread_constraints:
        yield {ns}, tsc.label_selector
    terms = list(spec.pod_affinity) + list(spec.pod_anti_affinity)
    terms += [wt.term for wt in spec.preferred_pod_affinity]
    terms += [wt.term for wt in spec.preferred_pod_anti_affinity]
    for term in terms:
        yield (set(term.namespaces) if term.namespaces else {ns}), term.label_selector


def _resolve_topology(
    groups: List[PodGroup], rest: List[Pod], topology,
    merge_bootstrap_affinity: bool = True,
) -> Tuple[List[PodGroup], List[Pod]]:
    """Global cross-group checks + TopoSpec construction (see
    partition_and_group docstring). Returns (kept groups, demoted pods)."""
    # constraints folded STATICALLY into group requirements this pass
    # (gates, affinity-with-priors): recorded on the topology so the
    # scenario-batched axis can decline when a candidate node's bound pods
    # would move counts a static fold already baked in
    topology.kernel_static_folds = []
    # distinct (namespace, labels) -> owning group indices (-1 = oracle side)
    _empty = frozenset()
    label_owners: Dict[tuple, set] = {}

    def _owner_key(p: Pod) -> tuple:
        labels = p.metadata.labels
        return (
            p.metadata.namespace,
            frozenset(labels.items()) if labels else _empty,
        )

    for gi, g in enumerate(groups):
        for p in g.pods:
            label_owners.setdefault(_owner_key(p), set()).add(gi)
    for p in rest:
        label_owners.setdefault(_owner_key(p), set()).add(-1)

    def matched_owners(namespaces: set, selector) -> set:
        out: set = set()
        if selector is None:
            return out  # nil selector selects nothing (labels.Nothing())
        for (ns, labels_fs), owners in label_owners.items():
            if ns in namespaces and selector.matches(dict(labels_fs)):
                out |= owners
        return out

    demote: set = set()

    # oracle-routed pods' constraints demote any tensorized group they
    # select: the oracle cannot see TPU placements. Demotion is transitive —
    # a demoted group's own constraints become oracle-side too — so iterate
    # to a fixpoint.
    seen_sigs = set()

    def demote_by_selectors(pod: Pod) -> None:
        for namespaces, selector in _pod_constraint_selectors(pod):
            sig = (frozenset(namespaces), selector.key() if selector else None)
            if sig in seen_sigs:
                continue
            seen_sigs.add(sig)
            demote.update(
                gi for gi in matched_owners(namespaces, selector) if gi >= 0
            )

    for p in rest:
        demote_by_selectors(p)

    # inverse anti-affinity owned by anyone outside the tensorized groups
    # (bound cluster pods, or pending pods already oracle-routed) gates
    # placements node-by-node in the oracle — demote every group it selects,
    # including constraint-free ones (their labels may match the selector).
    group_uids = [{p.uid for p in g.pods} for g in groups]
    all_uids = set().union(*group_uids) if group_uids else set()
    for tg in topology.inverse_topology_groups.values():
        if tg.owners - all_uids:
            demote.update(
                gi
                for gi in matched_owners(tg.namespaces, tg.selector)
                if gi >= 0
            )

    uid2gi: Dict[str, int] = {}
    for gi, uids in enumerate(group_uids):
        for uid in uids:
            uid2gi[uid] = gi
    # tg identity -> tg: constraints whose owners span groups (or select
    # beyond their own group) resolve in a second pass (shared carries)
    shared_pending: Dict[int, object] = {}
    group_specs: Dict[int, TopoSpec] = {}

    for gi, g in enumerate(groups):
        if gi in demote:
            continue
        rep = g.pods[0]
        if not (
            rep.spec.topology_spread_constraints
            or rep.spec.pod_anti_affinity
            or rep.spec.pod_affinity
        ):
            continue
        uids = group_uids[gi]
        owned = list(topology.owned_topologies(rep.uid))
        constraints = []  # (cap, counts) per hostname constraint
        spec = TopoSpec()
        group_specs[gi] = spec
        for tg in owned:
            # a TopologyGroup shared across groups (or selecting beyond its
            # own group) is deferred to the shared-constraint pass
            matched = matched_owners(tg.namespaces, tg.selector)
            if not tg.owners <= uids or matched - {gi}:
                shared_pending.setdefault(id(tg), tg)
                continue
            self_sel = tg.selects(rep)
            if tg.key == labels_mod.HOSTNAME:
                if tg.type is TopologyType.POD_AFFINITY:
                    # hostname co-location: the whole group pins to ONE
                    # entity (topologygroup.go:277-324 hostname case).
                    # Admit the self-selecting group-private shape; gate
                    # affinity (owner not selected — its candidates never
                    # grow) stays host-side, as does a second hostname
                    # affinity on the same group.
                    if not self_sel or spec.haff:
                        demote.add(gi)
                        break
                    prior = {d: c for d, c in tg.domains.items() if c > 0}
                    if prior:
                        # prior counts come from cluster pods, but the
                        # kernel's candidate rows are the solve's state
                        # nodes — a prior on a node outside the snapshot
                        # (cordoned/deleting) would silently degrade to
                        # the bootstrap; the oracle pins candidates to the
                        # prior node, so demote instead
                        known = set()
                        for sn in getattr(topology, "_state_nodes", ()):
                            hn = (
                                sn.hostname()
                                if hasattr(sn, "hostname")
                                else getattr(sn, "name", None)
                            )
                            if hn:
                                known.add(hn)
                        if not set(prior) <= known:
                            demote.add(gi)
                            break
                    spec.haff = True
                    spec.haff_prior = prior
                    continue
                if self_sel:
                    # self-selecting: the skew bound is a per-entity cap of
                    # maxSkew (anti: 1) minus pods already counted on the node
                    cap = (
                        tg.max_skew
                        if tg.type is TopologyType.SPREAD
                        else 1  # anti-affinity: only empty domains accept
                    )
                    constraints.append(
                        (cap, {d: c for d, c in tg.domains.items() if c > 0})
                    )
                    spec.src_h.append(tg)
                else:
                    # non-self-selecting: placements never change the counts,
                    # so the constraint is a binary per-node gate — blocked
                    # when the prior already exceeds the allowance (spread:
                    # > maxSkew, anti: > 0), unlimited otherwise. Encoded as
                    # an infinite effective prior on blocked nodes under an
                    # infinite cap.
                    threshold = (
                        tg.max_skew if tg.type is TopologyType.SPREAD else 0
                    )
                    constraints.append(
                        (
                            HCAP_NONE,
                            {
                                d: HCAP_NONE
                                for d, c in tg.domains.items()
                                if c > threshold
                            },
                        )
                    )
            elif (
                tg.key in DOMAIN_KEYS
                and tg.type is not TopologyType.POD_ANTI_AFFINITY
            ):
                # pod-admissible universe: the min (and every selection)
                # ranges over registered domains the pod itself admits
                # (topologygroup.go:231-251: candidate ∈ self.domains,
                # min over pod_domains)
                pod_dom = (
                    g.requirements.get(tg.key)
                    if g.requirements.has(tg.key)
                    else Requirement(tg.key, Operator.EXISTS)
                )
                counts = {
                    d: c for d, c in tg.domains.items() if pod_dom.has(d)
                }
                if tg.type is TopologyType.SPREAD:
                    min0 = (
                        tg.min_domains is not None
                        and len(counts) < tg.min_domains
                    )
                    m = (
                        0
                        if min0
                        else (min(counts.values()) if counts else MAX_SKEW_UNBOUNDED)
                    )
                    if self_sel:
                        if spec.dmode != DMODE_NONE:
                            demote.add(gi)  # one dynamic constraint per group
                            break
                        spec.dmode = DMODE_SPREAD
                        spec.dkey = tg.key
                        spec.dskew = tg.max_skew
                        spec.dmin0 = min0
                        spec.dprior = counts
                        spec.dreg = frozenset(counts)
                        spec.src_d = tg
                    else:
                        # static gate: placements never move the counts, so
                        # admissible domains are exactly those within skew
                        # today — intersect them into the group requirement
                        # (the oracle adds the same IN set per placement,
                        # topology.go:220-242)
                        allowed = [
                            d for d, c in counts.items() if c - m <= tg.max_skew
                        ]
                        g.requirements.add(
                            Requirement(tg.key, Operator.IN, allowed)
                        )
                        topology.kernel_static_folds.append(tg)
                else:  # POD_AFFINITY on zone / capacity-type
                    nonempty = [d for d, c in counts.items() if c > 0]
                    if nonempty:
                        # compatible pods already placed: a static
                        # nonempty-domain gate (topologygroup.go:277-290)
                        g.requirements.add(
                            Requirement(tg.key, Operator.IN, nonempty)
                        )
                        topology.kernel_static_folds.append(tg)
                    elif self_sel:
                        if spec.dmode != DMODE_NONE:
                            demote.add(gi)
                            break
                        # bootstrap: the whole group pins to one viable
                        # domain (topologygroup.go:291-324)
                        spec.dmode = DMODE_AFFINITY
                        spec.dkey = tg.key
                        spec.dprior = counts
                        spec.dreg = frozenset(counts)
                        spec.src_d = tg
                    else:
                        # no compatible placed pods and no bootstrap right:
                        # unsatisfiable (the oracle returns DoesNotExist)
                        g.requirements.add(
                            Requirement(tg.key, Operator.IN, [])
                        )
                        topology.kernel_static_folds.append(tg)
            else:
                # zone/ct anti-affinity and custom topology keys serialize
                # through the host oracle
                demote.add(gi)
                break
        if gi in demote:
            continue
        # fold hostname constraints: fresh-entity cap = min cap_i; a node's
        # residual is min_i (cap_i - prior_i), stored back as an effective
        # prior so the kernel's single (cap - prior) recovers it
        if spec.haff and (constraints or spec.dmode != DMODE_NONE):
            # the single-entity pin composing with hostname caps or a
            # domain-dynamic constraint shares kernel state (n_hcnt rows /
            # quota machinery) — serialize the combo through the oracle
            demote.add(gi)
            continue
        if constraints:
            spec.host_cap = min(c for c, _ in constraints)
            spec.host_nsrc = len(constraints)
            # sorted: host_counts insertion order is content-ordered, not
            # hash-ordered (its fold key already sorts items; this keeps
            # any future iteration deterministic too)
            for d in sorted({d for _, counts in constraints for d in counts}):
                residual = min(c - counts.get(d, 0) for c, counts in constraints)
                spec.host_counts[d] = spec.host_cap - max(residual, 0)
        g.topo = spec

    # -- shared constraints: one TopologyGroup spanning several groups -----
    # (e.g. a Deployment's anti-affinity across request shapes, or the
    # reference benchmark's cross-selecting spread classes). Tensorized via
    # kernel carries when counting stays fully inside the tensorized
    # groups: every owner pod grouped and no oracle-routed pod matches the
    # selector. Three per-group roles fall out of the oracle's semantics:
    #
    # - SELF owner (tg.selects(rep)): gated by the counts AND counted —
    #   DMODE_SPREAD/AFFINITY (or the hostname per-entity cap) plus the
    #   carry self-update.
    # - GATE owner (owns the constraint, not selected by it): gated by
    #   counts other groups' placements evolve, never counted —
    #   DMODE_GATE_* (or the hostname gate threshold, g_hself=False).
    # - CONTRIBUTOR (selected, doesn't own): counted, never gated —
    #   contrib_h/contrib_d rows; the kernel adds its placements to the
    #   carry by the record() rule (single-domain entities only,
    #   scheduling/topology.py:491-498).
    partners: Dict[int, set] = {}  # gi -> co-parties of any shared constraint

    def _filter_free(tg) -> bool:
        """Kernel carry counting is node-filter-blind; only constraints
        whose filter matches every node qualify for cross-group counting
        (topologynodefilter.go:26-97 zero-value shape)."""
        nf = tg.node_filter
        if nf.taint_policy == "Honor":
            return False
        return all(len(r.values()) == 0 for r in nf.requirements)

    for tg in shared_pending.values():
        owner_gis = set()
        oracle_owner = False
        for uid in tg.owners:
            gi = uid2gi.get(uid)
            if gi is None:
                oracle_owner = True  # an owner pod routed to the oracle
            else:
                owner_gis.add(gi)
        matched = matched_owners(tg.namespaces, tg.selector)
        contrib_gis = {gi for gi in matched - owner_gis if gi >= 0}
        oracle_matched = -1 in matched
        reps = {gi: groups[gi].pods[0] for gi in owner_gis}
        self_gis = {gi for gi, rep in reps.items() if tg.selects(rep)}
        gate_gis = owner_gis - self_gis
        # the original all-self, exactly-self-matching shape
        plain = not contrib_gis and not gate_gis

        def _admit() -> Optional[Tuple[str, object, Optional[int]]]:
            if oracle_owner or not owner_gis or oracle_matched:
                return None
            if not plain and not _filter_free(tg):
                return None
            if tg.key == labels_mod.HOSTNAME:
                if tg.type is TopologyType.POD_AFFINITY:
                    return None
                if tg.type is TopologyType.POD_ANTI_AFFINITY and not plain:
                    # Required anti-affinity is enforced symmetrically: the
                    # oracle's inverse gating (topology.go:509-525) blocks
                    # any SELECTED pod from entities where an owner already
                    # landed. The kernel gates only owners, so a selected-
                    # but-ungated placement AFTER an owner could co-locate.
                    # Admit only when FFD order makes that impossible:
                    # contributors pack strictly before every owner, and
                    # self owners strictly before gate owners (gate-owner
                    # placements are uncounted, so a later self owner would
                    # not see them in the carry). Ties are rejected — the
                    # post-sort order of equal keys is build-order-dependent.
                    def _ffd_key(gi: int):
                        return ffd_sort_key(groups[gi])

                    if contrib_gis and max(
                        _ffd_key(gi) for gi in contrib_gis
                    ) >= min(_ffd_key(gi) for gi in owner_gis):
                        return None
                    if (
                        gate_gis
                        and self_gis
                        and max(_ffd_key(gi) for gi in self_gis)
                        >= min(_ffd_key(gi) for gi in gate_gis)
                    ):
                        return None
                cap = tg.max_skew if tg.type is TopologyType.SPREAD else 1
                # gate threshold: blocked when the entity's count already
                # EXCEEDS the allowance (spread: > maxSkew with min 0;
                # anti: > 0), no count contribution
                thresh = tg.max_skew if tg.type is TopologyType.SPREAD else 0
                return (
                    "h",
                    SharedHostTG(
                        cap=cap,
                        counts={d: c for d, c in tg.domains.items() if c > 0},
                        tg=tg,
                    ),
                    thresh,
                )
            if (
                tg.key in DOMAIN_KEYS
                and tg.type is not TopologyType.POD_ANTI_AFFINITY
            ):
                # the min/selection universe must be identical across the
                # sharing groups (it is pod-admissibility-dependent)
                universes = set()
                for gi in owner_gis:
                    gr = groups[gi].requirements
                    pod_dom = (
                        gr.get(tg.key)
                        if gr.has(tg.key)
                        else Requirement(tg.key, Operator.EXISTS)
                    )
                    universes.add(
                        frozenset(d for d in tg.domains if pod_dom.has(d))
                    )
                if len(universes) != 1:
                    return None
                universe = next(iter(universes))
                counts = {d: tg.domains[d] for d in universe}
                if tg.type is TopologyType.SPREAD:
                    min0 = (
                        tg.min_domains is not None
                        and len(counts) < tg.min_domains
                    )
                    return (
                        "d",
                        SharedDomainTG(
                            key=tg.key,
                            mode=DMODE_SPREAD,
                            skew=tg.max_skew,
                            min0=min0,
                            prior=counts,
                            reg=frozenset(counts),
                            tg=tg,
                        ),
                        None,
                    )
                nonempty = [d for d, c in counts.items() if c > 0]
                if nonempty and plain:
                    # compatible pods already placed and no contributor can
                    # grow the options: a STATIC gate to all nonempty
                    # domains (topologygroup.go:277-290) — no carry. With
                    # contributors the options evolve mid-solve, so the
                    # dynamic follow rule in the kernel applies instead.
                    return ("gate", (tg.key, nonempty), None)
                return (
                    "d",
                    SharedDomainTG(
                        key=tg.key,
                        mode=DMODE_AFFINITY,
                        prior=counts,
                        reg=frozenset(counts),
                        tg=tg,
                    ),
                    None,
                )
            return None

        admitted = _admit()
        if admitted is not None:
            kind, desc, thresh = admitted
            # admitted flips to None when ANY owner fails; order never escapes
            # analysis: sanctioned[DET1101] check-only loop
            for gi in owner_gis:
                spec = group_specs.get(gi)
                if spec is None or gi in demote:
                    admitted = None
                    break
                if kind == "h" and spec.shared_h is not None:
                    admitted = None  # one shared hostname constraint/group
                    break
                if kind == "d" and (
                    spec.shared_d is not None
                    or spec.dmode != DMODE_NONE
                    or spec.haff
                ):
                    admitted = None  # one domain-dynamic per group
                    break
            if admitted is not None:
                if kind == "gate":
                    # analysis: sanctioned[DET1101] one keyed add per owner
                    for gi in owner_gis:
                        key, allowed = desc
                        groups[gi].requirements.add(
                            Requirement(key, Operator.IN, allowed)
                        )
                    topology.kernel_static_folds.append(tg)
                    # static gate: no carry, no partner coupling
                else:
                    # analysis: sanctioned[DET1101] per-owner writes commute
                    for gi in owner_gis:
                        spec = group_specs[gi]
                        is_self = gi in self_gis
                        if kind == "h":
                            spec.shared_h = desc
                            spec.h_self = is_self
                            spec.h_capval = desc.cap if is_self else thresh
                        else:
                            spec.shared_d = desc
                            spec.dmode = (
                                desc.mode
                                if is_self
                                else (
                                    DMODE_GATE_SPREAD
                                    if desc.mode == DMODE_SPREAD
                                    else DMODE_GATE_AFF
                                )
                            )
                            spec.dkey = desc.key
                            spec.dskew = desc.skew
                            spec.dmin0 = desc.min0
                            spec.dprior = desc.prior
                            spec.dreg = desc.reg
                    # one append per contributor's own list, so the cross-gi
                    # analysis: sanctioned[DET1101] order is unobservable
                    for gi in contrib_gis:
                        g = groups[gi]
                        if g.topo is None:
                            g.topo = TopoSpec()
                        if kind == "h":
                            g.topo.contrib_h.append(desc)
                        else:
                            g.topo.contrib_d.append(desc)
                    parties = owner_gis | contrib_gis
                    # partners is read by keyed .get() only, so its
                    # analysis: sanctioned[DET1101] insertion order never escapes
                    for gi in parties:
                        partners.setdefault(gi, set()).update(parties - {gi})
        if admitted is None:
            demote.update(owner_gis)

    # transitive closure: a demoted group's constraints join the oracle
    # side, and a demoted group drags every partner of its shared
    # constraints with it (split counting would be wrong)
    pending = set(demote)
    while pending:
        gi = pending.pop()
        before = set(demote)
        for p in groups[gi].pods:
            demote_by_selectors(p)
        demote.update(partners.get(gi, ()))
        pending |= demote - before

    # -- bootstrap-affinity group merge -------------------------------------
    # Indistinguishable DMODE_AFFINITY groups (identical shape/requirements/
    # domain universe, zero priors, no shared constraints or carries) all
    # bootstrap to the SAME domain when no existing node can host them and
    # the offering availability is static: d_fresh is the rank-min over
    # fresh-feasible registered domains, which none of their placements move
    # (topologygroup.go:291-324 run per group with identical inputs). The
    # reference's diverse benchmark mix creates ~1 such group per pod
    # (random self-affinity labels); merging collapses them to one scan
    # step each per shape. Gated off by the driver when existing nodes or a
    # reservation ledger make availability state-dependent.
    merged: set = set()
    if merge_bootstrap_affinity and not getattr(topology, "_state_nodes", ()):
        # single-group shared-affinity FAMILIES are mergeable across
        # families: the merge key pins (shape, requirements, universe), so
        # every merged member computes the SAME static d_fresh — d_fresh is
        # shape-dependent (fresh_ok_d is built from the group's own
        # type_ok row, ops/packing.py), which is exactly why families with
        # a second, differently-shaped sibling are excluded: the sibling
        # reads the family carry the merged-away member would have
        # written, and its own d_fresh may differ. Contributor-fed descs
        # (options evolve from outside the family) and priors (the family
        # follows its prior domain) are excluded too. Contributor descs
        # are collected from EVERY group's topo — constraint-free
        # contributor groups never enter group_specs.
        contrib_descs = set()
        for g in groups:
            if g.topo is not None:
                for d in g.topo.contrib_d:
                    contrib_descs.add(id(d))
        fam: Dict[int, List[int]] = {}
        for gj, sp in group_specs.items():
            if sp.shared_d is not None:
                fam.setdefault(id(sp.shared_d), []).append(gj)

        def _family_ok(spec) -> bool:
            if spec.shared_d is None:
                return True
            did = id(spec.shared_d)
            if did in contrib_descs:
                return False
            if any(spec.shared_d.prior.values()):
                return False
            return len(fam[did]) == 1

        by_merge_key: Dict[tuple, int] = {}
        for gi, g in enumerate(groups):
            if gi in demote:
                continue
            spec = group_specs.get(gi)
            if (
                spec is None
                or spec.dmode != DMODE_AFFINITY
                or any(spec.dprior.values())
                or spec.shared_h is not None
                or spec.contrib_h
                or spec.contrib_d
                or spec.host_cap is not None
                or spec.haff
                or not _family_ok(spec)
            ):
                continue
            key = (
                tuple(sorted(g.requests.items())),
                repr(g.requirements),
                spec.dkey,
                frozenset(spec.dreg),
            )
            prim = by_merge_key.get(key)
            if prim is None:
                by_merge_key[key] = gi
            else:
                groups[prim].pods.extend(g.pods)
                merged.add(gi)

    kept = [
        g for gi, g in enumerate(groups)
        if gi not in demote and gi not in merged
    ]
    # sorted: the demoted-pod list escapes to the oracle side; hash-order
    # here would reorder oracle processing across PYTHONHASHSEED twins
    demoted_pods = [p for gi in sorted(demote) for p in groups[gi].pods]
    return kept, demoted_pods
