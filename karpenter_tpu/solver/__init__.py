from .driver import TpuSolver, SolverConfig

__all__ = ["TpuSolver", "SolverConfig"]
