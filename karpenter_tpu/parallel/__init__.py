from .mesh import make_mesh, sharded_solve_fn, snapshot_shardings

__all__ = ["make_mesh", "sharded_solve_fn", "snapshot_shardings"]
