"""Multi-host sharding for the batch solver: a region's pending pods in
one dispatch.

The scaling axes of this domain map onto a 3-D device mesh:

- ``scenario`` — consolidation's what-if axis (S). The PR-2 scenario batch
  is embarrassingly parallel (each scenario is an independent solve over
  one shared encoding), so it is the LEADING mesh dimension: a
  consolidation search's whole probe set fans out across hosts and still
  costs <= 2 dispatches.
- ``data`` — the segment live-pair axis (L). The group axis itself CANNOT
  shard: the packing scan is sequential over groups, and the measured
  r05 layout (G over 'data') paid collectives on every scan step — 8x1
  ran 12x slower than single-device (hack/mesh_scaling.py, PARITY.md
  "multi-chip scaling measurements"). The r06 re-factorization moves the
  group-parallel WORK onto the PR-13 segment index instead: the live
  (group, key) pairs (gk_*) shard over 'data', the segment contractions
  run shard-local, and one segment_sum all-reduce per feasibility stage
  folds them back into replicated [G, ...] tables — family-parallel
  batching of exactly the fragmented spread-singleton shapes the index
  was built for. Group- and node-major arrays stay REPLICATED so the
  scan's per-step state never crosses the mesh (pinned structurally by
  tests/test_parallel.py::test_scan_body_has_no_collectives).
- ``model`` — instance types (T). The (K x V1) mask reductions and the
  offering contractions partition over types; per-step [*, T] scan state
  updates are elementwise over T, so type sharding stays scan-local
  (within 1.6x at 8 chips in the r05 measurement).

GSPMD inserts the ICI collectives at the stage boundaries inside one
jitted program; the warm path (solver/residency.py) stages per-shard
device buffers against these same specs, so REUSE/row-delta outcomes
survive a mesh exactly as they do on one device.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np

AXIS_SCENARIO = "scenario"
AXIS_DATA = "data"
AXIS_MODEL = "model"
MESH_AXES = (AXIS_SCENARIO, AXIS_DATA, AXIS_MODEL)

# Per-argument partition specs for EncodedSnapshot.solve_args /
# SOLVE_ARG_NAMES, as tuples of mesh-axis names (None = replicated dim;
# a missing tail is replicated). THE fixed r06 layout — the residency
# store, the padding, the scenario axis, and the SHP6xx shard-divisibility
# check all read this table.
#
#   replicated  g_* / p_* / n_* — scan-carried or scan-read state
#   'model'     t_* / o_* / a_tzc / a_res[T@1] / p_titype_ok[T@1] / t_mvoh
#   'data'      gk_g / gk_k / gk_w — the compacted live-pair axis
ARG_SPECS: Dict[str, Tuple[Optional[str], ...]] = {
    "g_count": (), "g_req": (), "g_def": (), "g_neg": (), "g_mask": (),
    "g_hcap": (), "g_haff": (),
    "g_dmode": (), "g_dkey": (), "g_dskew": (), "g_dmin0": (),
    "g_dprior": (), "g_dreg": (), "g_drank": (),
    "g_hstg": (), "g_hscap": (), "g_dtg": (),
    "g_hself": (), "g_hcontrib": (), "g_dcontrib": (),
    "p_def": (), "p_neg": (), "p_mask": (), "p_daemon": (),
    "p_limit": (), "p_has_limit": (), "p_tol": (),
    "p_titype_ok": (None, AXIS_MODEL),
    "t_def": (AXIS_MODEL,), "t_mask": (AXIS_MODEL,),
    "t_alloc": (AXIS_MODEL,), "t_cap": (AXIS_MODEL,),
    "o_avail": (AXIS_MODEL,), "o_zone": (AXIS_MODEL,),
    "o_ct": (AXIS_MODEL,),
    "a_tzc": (AXIS_MODEL,), "res_cap0": (), "a_res": (None, AXIS_MODEL),
    "n_def": (), "n_mask": (), "n_avail": (), "n_base": (), "n_tol": (),
    "n_hcnt": (),
    "n_dzone": (), "n_dct": (),
    "nh_cnt0": (), "dd0": (), "dtg_key": (),
    "well_known": (),
    "p_mvmin": (), "t_mvoh": (AXIS_MODEL,),
    "gk_g": (AXIS_DATA,), "gk_k": (AXIS_DATA,), "gk_w": (AXIS_DATA,),
    "goff_idx": (),
}


def make_mesh(
    n_devices: Optional[int] = None,
    data: Optional[int] = None,
    scenario: Optional[int] = None,
):
    """Build a ('scenario', 'data', 'model') mesh over the first n devices.

    Defaults are measured, not assumed (hack/mesh_scaling.py, the r06
    re-measurement): the plain solve puts every device on 'data' — the
    segment live-pair axis is the only single-solve factorization whose
    compiled scan body carries ZERO collectives (the sharded feasibility
    stage folds into replicated tables once, at the scan boundary).
    'model' (type sharding) is opt-in HBM headroom for catalogs too large
    for one chip — its T-shaped scan state pays small per-step
    collectives (within 1.6x at 8 chips, r05). 'scenario' is taken by the
    scenario dispatch path itself via :func:`scenario_mesh`.
    """
    import jax

    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(
            f"requested {n} devices but only {len(devices)} available"
        )
    devices = np.asarray(devices[:n])
    scenario = scenario or 1
    if data is None:
        data = n // scenario
    if n % (scenario * data):
        raise ValueError(
            f"{n} devices do not factor as scenario={scenario} x data={data}"
            " x model"
        )
    model = n // (scenario * data)
    return jax.sharding.Mesh(
        devices.reshape(scenario, data, model), MESH_AXES
    )


# derived scenario-major meshes, keyed by (base mesh, scenario dim): the
# SAME devices re-factorized so consolidation's embarrassingly-parallel
# axis gets them (a Mesh is hashable; the jit caches key on it)
_SCENARIO_MESHES: Dict[tuple, object] = {}


def scenario_mesh(mesh, s: int):
    """Re-factorize ``mesh``'s devices scenario-major for a batch of ``s``
    scenarios: the scenario axis takes the largest device count that
    divides ``s`` (S is pow2-bucketed with floor 8, so a pow2 device
    count lands fully on the scenario axis); any remainder stays on
    'data' (the collective-free segment axis). The base mesh's 'model'
    dimension is PRESERVED, never folded into 'scenario': model sharding
    exists as HBM headroom for catalogs too large for one chip, and
    replicating the type tables across a scenario-major re-factorization
    would OOM exactly the configs that opted into it."""
    import jax

    model = int(mesh.devices.shape[MESH_AXES.index(AXIS_MODEL)])
    navail = int(np.prod(mesh.devices.shape)) // model
    sdim = 1
    while (
        sdim * 2 <= navail
        and s % (sdim * 2) == 0
        and navail % (sdim * 2) == 0
    ):
        sdim *= 2
    key = (mesh, sdim)
    out = _SCENARIO_MESHES.get(key)
    if out is None:
        out = _SCENARIO_MESHES[key] = jax.sharding.Mesh(
            mesh.devices.reshape(sdim, navail // sdim, model), MESH_AXES
        )
    return out


def dense_mesh(mesh):
    """Re-factorize for the DENSE (non-sparse-segment) kernel: 'data'
    shards only the compacted live-pair index (gk_*), which the dense and
    tiled feasibility paths never read — left as-is, a data-major mesh
    would run the identical replicated program on every device (zero
    speedup plus GSPMD overhead). Fold 'data' into 'model' so the [T, *]
    type/offering tables shard instead (the r05-measured dense layout,
    within 1.6x at 8 chips). The 'scenario' dimension is preserved."""
    import jax

    sdim, ddim, mdim = (int(x) for x in mesh.devices.shape)
    if ddim == 1:
        return mesh
    key = (mesh, "dense")
    out = _SCENARIO_MESHES.get(key)
    if out is None:
        out = _SCENARIO_MESHES[key] = jax.sharding.Mesh(
            mesh.devices.reshape(sdim, 1, ddim * mdim), MESH_AXES
        )
    return out


def _named(mesh, spec: Tuple[Optional[str], ...]):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*spec))


def arg_shardings(mesh) -> Dict[str, object]:
    """NamedSharding per SOLVE_ARG_NAMES entry (the residency store's
    staging specs — what `snapshot_shardings` serves positionally)."""
    return {name: _named(mesh, spec) for name, spec in ARG_SPECS.items()}


def snapshot_shardings(mesh) -> Tuple:
    """in_shardings for solve_core's argument list, positionally aligned
    with EncodedSnapshot.solve_args / SOLVE_ARG_NAMES via ARG_SPECS."""
    from ..solver.encode import SOLVE_ARG_NAMES

    return tuple(_named(mesh, ARG_SPECS[n]) for n in SOLVE_ARG_NAMES)


def scenario_shardings(mesh, batch_topo: bool = False) -> Tuple:
    """in_shardings for the scenario-batched dispatch: the per-scenario
    stacks (g_count, n_tol — plus the four topology prior arrays under
    ``batch_topo``) gain a leading 'scenario' axis; every shared arg
    keeps its snapshot spec. Replicated base specs make the stacked spec
    exactly ('scenario',): each scenario shard owns its scenarios' rows
    and the solve inside a shard is the single-device program."""
    from ..ops.solve import SCENARIO_BATCHED_ARGS, SCENARIO_TOPO_BATCHED_ARGS
    from ..solver.encode import SOLVE_ARG_NAMES

    stacked = SCENARIO_TOPO_BATCHED_ARGS if batch_topo else SCENARIO_BATCHED_ARGS
    out = []
    for name in SOLVE_ARG_NAMES:
        spec = ARG_SPECS[name]
        if name in stacked:
            spec = (AXIS_SCENARIO,) + spec
        out.append(_named(mesh, spec))
    return tuple(out)


# jitted sharded programs keyed by (mesh, statics): a jax.jit wrapper owns
# its own trace cache, so handing the same wrapper back for repeat solves is
# what makes the driver's mesh path amortize compilation the way the
# single-device jit does
_SHARDED_FNS = {}


def _replicated_out(mesh):
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def sharded_solve_fn(
    mesh, nmax: int, zone_kid: int, ct_kid: int, has_domains: bool = True,
    has_contrib: bool = False, tile_feasibility: bool = False,
    wf_iters: int = 32, sparse_groups: bool = False,
):
    """The full solve step jitted over the mesh (unpacked outputs — the
    measurement/test surface). Sharded inputs per ARG_SPECS, replicated
    outputs; XLA/GSPMD inserts the ICI collectives."""
    import jax

    from ..ops.solve import solve_core

    key = (
        "solve", mesh, nmax, zone_kid, ct_kid, has_domains, has_contrib,
        tile_feasibility, wf_iters, sparse_groups,
    )
    fn = _SHARDED_FNS.get(key)
    if fn is None:
        fn = _SHARDED_FNS[key] = jax.jit(
            partial(
                solve_core,
                nmax=nmax,
                zone_kid=zone_kid,
                ct_kid=ct_kid,
                has_domains=has_domains,
                has_contrib=has_contrib,
                tile_feasibility=tile_feasibility,
                wf_iters=wf_iters,
                sparse_groups=sparse_groups,
                # replicate the feasibility tables at the scan boundary:
                # GSPMD otherwise carries them sharded into the while loop
                # and the scan pays an all-gather per step (the measured
                # r05 regression; see ops/solve.py:_solve_with)
                table_sharding=_replicated_out(mesh),
            ),
            in_shardings=snapshot_shardings(mesh),
            out_shardings=_replicated_out(mesh),
        )
    return fn


def sharded_solve_packed_fn(mesh, fills_dtype, **statics):
    """The wire-packed solve over the mesh — the driver's production
    dispatch: outputs match the single-device queued path bit-for-bit
    (uint8-packed type masks, narrowed fills), so decode, the relax
    merge contract, and the single blessed drain are shared."""
    import jax

    from ..ops.solve import solve_core_packed

    key = ("packed", mesh, fills_dtype) + tuple(sorted(statics.items()))
    fn = _SHARDED_FNS.get(key)
    if fn is None:
        fn = _SHARDED_FNS[key] = jax.jit(
            partial(
                solve_core_packed, fills_dtype=fills_dtype,
                table_sharding=_replicated_out(mesh), **statics,
            ),
            in_shardings=snapshot_shardings(mesh),
            out_shardings=_replicated_out(mesh),
        )
    return fn


def sharded_scenarios_fn(mesh, fills_dtype, batch_topo: bool, **statics):
    """The scenario-batched dispatch over the mesh: the vmapped solve with
    the stacked args sharded on the leading 'scenario' axis. One program,
    S scenarios, the whole region's what-if set in one dispatch."""
    import jax

    from ..ops.solve import solve_scenarios_core_packed

    key = (
        ("scenarios", mesh, fills_dtype, batch_topo)
        + tuple(sorted(statics.items()))
    )
    fn = _SHARDED_FNS.get(key)
    if fn is None:
        fn = _SHARDED_FNS[key] = jax.jit(
            partial(
                solve_scenarios_core_packed,
                fills_dtype=fills_dtype,
                batch_topo=batch_topo,
                # the scan-boundary replication constraint matters here
                # too: whenever the scenario re-factorization retains
                # data>1 (devices > scenario bucket), the sharded
                # feasibility tables must fold BEFORE the packing scan
                # or every step pays the r05 all-gather
                table_sharding=_replicated_out(mesh),
                **statics,
            ),
            in_shardings=scenario_shardings(mesh, batch_topo),
            out_shardings=_replicated_out(mesh),
        )
    return fn


_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "all-to-all", "collective-permute",
    "reduce-scatter", "collective-broadcast",
    # async forms (TPU/GPU lowerings after collective scheduling): count
    # the -start ops — each moves the data once; the paired -done ops are
    # deliberately absent so an async pair isn't counted twice
    "all-reduce-start", "all-gather-start", "all-to-all-start",
    "collective-permute-start", "reduce-scatter-start",
)


def scan_collective_report(compiled_text: str) -> Dict[str, object]:
    """Structural audit of a compiled sharded program: which collective
    ops sit INSIDE while-loop bodies (the packing scan lowers to while;
    a collective there is paid once PER SCAN STEP — the r05 regression
    shape) versus outside them (stage-boundary collectives, paid once per
    solve). Parses the post-partitioning HLO text from
    ``fn.lower(*args).compile().as_text()``; dispatch STRUCTURE, not
    wall-clock, so CPU CI can pin the layout without timing flake
    (tests/test_parallel.py::test_scan_body_has_no_collectives)."""
    comp_ops: Dict[str, list] = {}
    current = None
    for line in compiled_text.splitlines():
        if line and not line[0].isspace() and line.rstrip().endswith("{"):
            head = line.split("(", 1)[0].strip()
            name = head.split()[-1].lstrip("%")
            current = name
            comp_ops[current] = []
        elif current is not None and line.strip() and line.strip() != "}":
            comp_ops[current].append(line)

    import re

    ref_re = re.compile(
        r"(?:body|condition|to_apply|calls)=%([\w./-]+)"
        r"|branch_computations=\{([^}]*)\}"
    )

    def refs_of(line: str) -> list:
        out = []
        for m in ref_re.finditer(line):
            if m.group(1):
                out.append(m.group(1))
            elif m.group(2):
                out.extend(
                    t.strip().lstrip("%") for t in m.group(2).split(",")
                )
        return out

    scan_roots = set()
    total = 0
    for name, lines in comp_ops.items():
        for line in lines:
            s = line.strip()
            op = s.split("=", 1)[-1].strip() if "=" in s else s
            if any(op.startswith(f"{c}(") or f" {c}(" in f" {op}"
                   for c in _COLLECTIVE_OPS):
                total += 1
            if " while(" in s or s.startswith("while("):
                scan_roots.update(refs_of(s))

    # transitive closure over computations reachable from scan bodies
    seen = set()
    frontier = list(scan_roots)
    while frontier:
        name = frontier.pop()
        if name in seen or name not in comp_ops:
            continue
        seen.add(name)
        for line in comp_ops[name]:
            frontier.extend(refs_of(line))

    offenders = []
    in_scan = 0
    in_scan_scalar = 0
    for name in seen:
        for line in comp_ops.get(name, ()):
            s = line.strip()
            op = s.split("=", 1)[-1].strip() if "=" in s else s
            if any(op.startswith(f"{c}(") or f" {c}(" in f" {op}"
                   for c in _COLLECTIVE_OPS):
                in_scan += 1
                # a SCALAR (pred[]/s32[]) collective is loop trip-count
                # sync — the scenario axis's "are all shards done" vote,
                # O(1) bytes — distinct from per-step DATA movement (the
                # r05 regression gathered whole table rows every step)
                shape = op.split(" ", 1)[0]
                if shape.endswith("[]"):
                    in_scan_scalar += 1
                else:
                    offenders.append((name, s[:160]))
    return {
        "computations": len(comp_ops),
        "scan_computations": len(seen),
        "collectives_total": total,
        "collectives_in_scan": in_scan,
        "collectives_in_scan_scalar": in_scan_scalar,
        "collectives_in_scan_data": in_scan - in_scan_scalar,
        "offenders": offenders,
    }


def pad_axis(arr, axis: int, mult: int, fill=0):
    """Pad ``arr``'s ``axis`` up to a multiple of ``mult`` (shard-divisible
    after the encoder's pow2 bucketing; a pow2 axis >= the shard count is
    already divisible and returns unchanged)."""
    size = arr.shape[axis]
    target = ((size + mult - 1) // mult) * mult
    if target == size:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - size)
    return np.pad(arr, widths, constant_values=fill)


def pad_args_for_mesh(args, mesh):
    """Pad solve_core's argument tuple (EncodedSnapshot.solve_args order) so
    every sharded axis divides its mesh dimension: the T axis (types,
    offerings, availability) to a multiple of 'model', the segment
    live-pair axis L to a multiple of 'data'. Group- and node-major arrays
    are replicated in the r06 layout and need no padding. Padded types
    stay infeasible (p_titype_ok False, no offerings); padded live pairs
    carry weight 0 (a zero segment_sum contribution) and repeat group 0 in
    gk_g, so results are unchanged."""
    from ..solver.encode import SOLVE_ARG_NAMES

    model = mesh.devices.shape[MESH_AXES.index(AXIS_MODEL)]
    data = mesh.devices.shape[MESH_AXES.index(AXIS_DATA)]
    byname = dict(zip(SOLVE_ARG_NAMES, args))

    for name in ("t_def", "t_mask", "t_alloc", "t_cap",
                 "o_avail", "o_zone", "o_ct", "a_tzc", "t_mvoh"):
        byname[name] = pad_axis(byname[name], 0, model)
    byname["a_res"] = pad_axis(byname["a_res"], 1, model)
    # padded types stay infeasible for every template
    byname["p_titype_ok"] = pad_axis(byname["p_titype_ok"], 1, model)
    # the segment index names REAL group rows; L-axis padding appends
    # weight-0 pairs on group 0 — segment_sum ignores them exactly
    byname["gk_g"] = pad_axis(byname["gk_g"], 0, data)
    byname["gk_k"] = pad_axis(byname["gk_k"], 0, data)
    byname["gk_w"] = pad_axis(byname["gk_w"], 0, data)
    return tuple(byname[name] for name in SOLVE_ARG_NAMES)


