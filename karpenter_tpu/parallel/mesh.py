"""Multi-chip sharding for the batch solver.

The scaling axes of this domain map onto a 2-D device mesh:

- ``data`` — pod groups (G). The feasibility tables are embarrassingly
  parallel over groups; this is the data-parallel axis.
- ``model`` — instance types (T). The (K x V1) mask reductions and the
  offering contractions partition over types; this is the tensor-parallel
  axis. The reference has no distributed backend at all (SURVEY.md §5) —
  its analog of "scale" is pruning; here the dense tables shard across
  chips and XLA inserts the all-gathers where the packing scan consumes
  cross-type reductions over ICI.

The packing scan itself is sequential over groups (the simulation's
inherent dependence, SURVEY.md §7.4.1); its per-step state is small, so it
runs effectively replicated while the heavy feasibility math stays sharded.
GSPMD handles the resharding at the boundary inside one jitted program.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np


def make_mesh(n_devices: Optional[int] = None, data: Optional[int] = None):
    """Build a ('data', 'model') mesh over the first n devices."""
    import jax

    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(
            f"requested {n} devices but only {len(devices)} available"
        )
    devices = np.asarray(devices[:n])
    if data is None:
        # measured, not assumed (hack/mesh_scaling.py, 50k x 800 over the
        # virtual mesh): the packing scan is sequential over groups, so
        # sharding the G axis forces collectives on every scan step —
        # 8x1 ran 12x slower than single-device while 1x8 stayed within
        # 1.6x. Pure model (type) sharding is the only factorization that
        # keeps the sequential scan local; the data axis exists for
        # embarrassingly-parallel multi-solve workloads, opt-in via
        # ``data``.
        data = 1
    model = n // data
    return jax.sharding.Mesh(devices.reshape(data, model), ("data", "model"))


def snapshot_shardings(mesh) -> Tuple:
    """in_shardings for solve_core's argument list (ops/solve.py), sharding
    group-major arrays over 'data' and type-major arrays over 'model'."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    S = lambda *spec: NamedSharding(mesh, P(*spec))
    rep = S()
    g = S("data")
    t = S("model")
    return (
        g,  # g_count [G]
        g,  # g_req [G, R]
        g,  # g_def [G, K]
        g,  # g_neg [G, K]
        g,  # g_mask [G, K, V1]
        g,  # g_hcap [G]
        g,  # g_haff [G]
        g,  # g_dmode [G]
        g,  # g_dkey [G]
        g,  # g_dskew [G]
        g,  # g_dmin0 [G]
        g,  # g_dprior [G, V1]
        g,  # g_dreg [G, V1]
        g,  # g_drank [G, V1]
        g,  # g_hstg [G]
        g,  # g_hscap [G]
        g,  # g_dtg [G]
        g,  # g_hself [G]
        g,  # g_hcontrib [G, JH]
        g,  # g_dcontrib [G, JD]
        rep,  # p_def
        rep,  # p_neg
        rep,  # p_mask
        rep,  # p_daemon
        rep,  # p_limit
        rep,  # p_has_limit
        S(None, "data"),  # p_tol [P, G]
        S(None, "model"),  # p_titype_ok [P, T]
        t,  # t_def [T, K]
        t,  # t_mask [T, K, V1]
        t,  # t_alloc [T, R]
        t,  # t_cap [T, R]
        t,  # o_avail [T, O]
        t,  # o_zone [T, O]
        t,  # o_ct [T, O]
        t,  # a_tzc [T, V1, V1]
        rep,  # res_cap0 [NRES]
        S(None, "model"),  # a_res [NRES, T, V1, V1]
        rep,  # n_def [N, K]
        rep,  # n_mask
        rep,  # n_avail
        rep,  # n_base
        S(None, "data"),  # n_tol [N, G]
        S(None, "data"),  # n_hcnt [N, G]
        rep,  # n_dzone [N]
        rep,  # n_dct [N]
        rep,  # nh_cnt0 [N, JH]
        rep,  # dd0 [JD, V1]
        rep,  # dtg_key [JD]
        rep,  # well_known [K]
        rep,  # p_mvmin [P, MV]
        S("model"),  # t_mvoh [T, MV, W]
        rep,  # gk_g [L]
        rep,  # gk_k [L]
        rep,  # gk_w [L]
        rep,  # goff_idx [LZ]
    )


# jitted sharded programs keyed by (mesh, statics): a jax.jit wrapper owns
# its own trace cache, so handing the same wrapper back for repeat solves is
# what makes the driver's mesh path amortize compilation the way the
# single-device jit does
_SHARDED_FNS = {}


def sharded_solve_fn(
    mesh, nmax: int, zone_kid: int, ct_kid: int, has_domains: bool = True,
    has_contrib: bool = False, tile_feasibility: bool = False,
    wf_iters: int = 32, sparse_groups: bool = False,
):
    """The full solve step jitted over the mesh. Group/type-sharded inputs,
    replicated outputs; XLA/GSPMD inserts the ICI collectives."""
    import jax

    from ..ops.solve import solve_core

    key = (
        mesh, nmax, zone_kid, ct_kid, has_domains, has_contrib,
        tile_feasibility, wf_iters, sparse_groups,
    )
    fn = _SHARDED_FNS.get(key)
    if fn is None:
        fn = _SHARDED_FNS[key] = jax.jit(
            partial(
                solve_core,
                nmax=nmax,
                zone_kid=zone_kid,
                ct_kid=ct_kid,
                has_domains=has_domains,
                has_contrib=has_contrib,
                tile_feasibility=tile_feasibility,
                wf_iters=wf_iters,
                sparse_groups=sparse_groups,
            ),
            in_shardings=snapshot_shardings(mesh),
            out_shardings=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()
            ),
        )
    return fn


def pad_args_for_mesh(args, mesh):
    """Pad solve_core's argument tuple (EncodedSnapshot.solve_args order) so
    the sharded axes divide the mesh: the G axis (groups and the [*, G]
    tables) to a multiple of 'data', the T axis (types, offerings,
    availability) to a multiple of 'model'. Padded groups have count 0 (the
    kernel's skip-step branch retires them); padded types stay infeasible
    (p_titype_ok False, no offerings), so results are unchanged."""
    data = mesh.devices.shape[0]
    model = mesh.devices.shape[1]
    (
        g_count, g_req, g_def, g_neg, g_mask, g_hcap, g_haff,
        g_dmode, g_dkey, g_dskew, g_dmin0, g_dprior, g_dreg, g_drank,
        g_hstg, g_hscap, g_dtg,
        g_hself, g_hcontrib, g_dcontrib,
        p_def, p_neg, p_mask, p_daemon, p_limit, p_has_limit, p_tol,
        p_titype_ok,
        t_def, t_mask, t_alloc, t_cap,
        o_avail, o_zone, o_ct, a_tzc, res_cap0, a_res,
        n_def, n_mask, n_avail, n_base, n_tol, n_hcnt, n_dzone, n_dct,
        nh_cnt0, dd0, dtg_key,
        well_known,
        p_mvmin, t_mvoh,
        gk_g, gk_k, gk_w, goff_idx,
    ) = args

    def pad_axis(arr, axis, mult, fill=0):
        size = arr.shape[axis]
        target = ((size + mult - 1) // mult) * mult
        if target == size:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, target - size)
        return np.pad(arr, widths, constant_values=fill)

    g_count = pad_axis(g_count, 0, data)  # padded groups have count 0
    g_req = pad_axis(g_req, 0, data)
    g_def = pad_axis(g_def, 0, data)
    g_neg = pad_axis(g_neg, 0, data)
    g_mask = pad_axis(g_mask, 0, data, fill=1)
    g_hcap = pad_axis(g_hcap, 0, data)  # count-0 pads never place anyway
    g_haff = pad_axis(g_haff, 0, data)
    for_g = lambda a: pad_axis(a, 0, data)
    g_dmode, g_dkey, g_dskew, g_dmin0 = map(
        for_g, (g_dmode, g_dkey, g_dskew, g_dmin0)
    )
    g_dprior, g_dreg, g_drank = map(for_g, (g_dprior, g_dreg, g_drank))
    # slot ids pad with -1 (0 is a real slot); caps pad with the no-cap value
    g_hstg = pad_axis(g_hstg, 0, data, fill=-1)
    g_dtg = pad_axis(g_dtg, 0, data, fill=-1)
    g_hscap = pad_axis(g_hscap, 0, data, fill=2**30)
    g_hself = pad_axis(g_hself, 0, data, fill=1)
    g_hcontrib = pad_axis(g_hcontrib, 0, data)
    g_dcontrib = pad_axis(g_dcontrib, 0, data)
    p_tol = pad_axis(p_tol, 1, data)
    n_tol = pad_axis(n_tol, 1, data)
    n_hcnt = pad_axis(n_hcnt, 1, data)

    for_t = lambda a: pad_axis(a, 0, model)
    t_def, t_mask, t_alloc, t_cap = map(for_t, (t_def, t_mask, t_alloc, t_cap))
    o_avail, o_zone, o_ct, a_tzc = map(for_t, (o_avail, o_zone, o_ct, a_tzc))
    a_res = pad_axis(a_res, 1, model)  # padded types have no reservations
    p_titype_ok = pad_axis(p_titype_ok, 1, model)  # padded types stay infeasible
    t_mvoh = pad_axis(t_mvoh, 0, model)  # padded types offer no mv values

    return (
        g_count, g_req, g_def, g_neg, g_mask, g_hcap, g_haff,
        g_dmode, g_dkey, g_dskew, g_dmin0, g_dprior, g_dreg, g_drank,
        g_hstg, g_hscap, g_dtg,
        g_hself, g_hcontrib, g_dcontrib,
        p_def, p_neg, p_mask, p_daemon, p_limit, p_has_limit, p_tol,
        p_titype_ok,
        t_def, t_mask, t_alloc, t_cap,
        o_avail, o_zone, o_ct, a_tzc, res_cap0, a_res,
        n_def, n_mask, n_avail, n_base, n_tol, n_hcnt, n_dzone, n_dct,
        nh_cnt0, dd0, dtg_key,
        well_known,
        p_mvmin, t_mvoh,
        # the segment index names REAL group rows; G-axis padding appends
        # neutral rows with no live pairs, so the index is already valid
        gk_g, gk_k, gk_w, goff_idx,
    )
