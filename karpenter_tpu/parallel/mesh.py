"""Multi-chip sharding for the batch solver.

The scaling axes of this domain map onto a 2-D device mesh:

- ``data`` — pod groups (G). The feasibility tables are embarrassingly
  parallel over groups; this is the data-parallel axis.
- ``model`` — instance types (T). The (K x V1) mask reductions and the
  offering contractions partition over types; this is the tensor-parallel
  axis. The reference has no distributed backend at all (SURVEY.md §5) —
  its analog of "scale" is pruning; here the dense tables shard across
  chips and XLA inserts the all-gathers where the packing scan consumes
  cross-type reductions over ICI.

The packing scan itself is sequential over groups (the simulation's
inherent dependence, SURVEY.md §7.4.1); its per-step state is small, so it
runs effectively replicated while the heavy feasibility math stays sharded.
GSPMD handles the resharding at the boundary inside one jitted program.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np


def make_mesh(n_devices: Optional[int] = None, data: Optional[int] = None):
    """Build a ('data', 'model') mesh over the first n devices."""
    import jax

    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(
            f"requested {n} devices but only {len(devices)} available"
        )
    devices = np.asarray(devices[:n])
    if data is None:
        # favor the model axis: type-sharding keeps the big masks local
        data = 1
        for cand in (2, 4, 8):
            if n % cand == 0 and cand * cand <= n:
                data = cand
    model = n // data
    return jax.sharding.Mesh(devices.reshape(data, model), ("data", "model"))


def snapshot_shardings(mesh) -> Tuple:
    """in_shardings for solve_core's argument list (ops/solve.py), sharding
    group-major arrays over 'data' and type-major arrays over 'model'."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    S = lambda *spec: NamedSharding(mesh, P(*spec))
    rep = S()
    g = S("data")
    t = S("model")
    return (
        g,  # g_count [G]
        g,  # g_req [G, R]
        g,  # g_def [G, K]
        g,  # g_neg [G, K]
        g,  # g_mask [G, K, V1]
        g,  # g_hcap [G]
        g,  # g_dmode [G]
        g,  # g_dkey [G]
        g,  # g_dskew [G]
        g,  # g_dmin0 [G]
        g,  # g_dprior [G, V1]
        g,  # g_dreg [G, V1]
        g,  # g_drank [G, V1]
        g,  # g_hstg [G]
        g,  # g_hscap [G]
        g,  # g_dtg [G]
        g,  # g_hself [G]
        g,  # g_hcontrib [G, JH]
        g,  # g_dcontrib [G, JD]
        rep,  # p_def
        rep,  # p_neg
        rep,  # p_mask
        rep,  # p_daemon
        rep,  # p_limit
        rep,  # p_has_limit
        S(None, "data"),  # p_tol [P, G]
        S(None, "model"),  # p_titype_ok [P, T]
        t,  # t_def [T, K]
        t,  # t_mask [T, K, V1]
        t,  # t_alloc [T, R]
        t,  # t_cap [T, R]
        t,  # o_avail [T, O]
        t,  # o_zone [T, O]
        t,  # o_ct [T, O]
        t,  # a_tzc [T, V1, V1]
        rep,  # res_cap0 [NRES]
        S(None, "model"),  # a_res [NRES, T, V1, V1]
        rep,  # n_def [N, K]
        rep,  # n_mask
        rep,  # n_avail
        rep,  # n_base
        S(None, "data"),  # n_tol [N, G]
        S(None, "data"),  # n_hcnt [N, G]
        rep,  # n_dzone [N]
        rep,  # n_dct [N]
        rep,  # nh_cnt0 [N, JH]
        rep,  # dd0 [JD, V1]
        rep,  # dtg_key [JD]
        rep,  # well_known [K]
    )


def sharded_solve_fn(
    mesh, nmax: int, zone_kid: int, ct_kid: int, has_domains: bool = True,
    has_contrib: bool = False,
):
    """The full solve step jitted over the mesh. Group/type-sharded inputs,
    replicated outputs; XLA/GSPMD inserts the ICI collectives."""
    import jax

    from ..ops.solve import solve_core

    return jax.jit(
        partial(
            solve_core,
            nmax=nmax,
            zone_kid=zone_kid,
            ct_kid=ct_kid,
            has_domains=has_domains,
            has_contrib=has_contrib,
        ),
        in_shardings=snapshot_shardings(mesh),
        out_shardings=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()
        ),
    )
