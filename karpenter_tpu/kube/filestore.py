"""File-backed store: the second backend behind the Client seam.

The reference's controllers speak to ANY apiserver through client-go
(operator.go:105-223; its tests boot a real envtest apiserver,
pkg/test/environment.go:138-197). The in-process store (kube/store.py) is
this framework's default backend; this module proves the Client surface is
a genuine seam by providing a second implementation with *apiserver-like*
semantics the in-process store cannot check:

- every object round-trips through serialization on each CRUD — readers
  get fresh copies, so nothing in the control plane can depend on shared
  object references (the failure mode a real wire protocol would expose);
- all durable state lives on disk — a new FileClient over the same
  directory resumes the cluster (the checkpoint/resume story: the store IS
  the checkpoint, matching the reference's level-triggered recovery).

tests/test_housekeeping.py runs its controller suite over both backends;
tests/test_filestore.py covers the persistence/restart semantics.
"""

from __future__ import annotations

import os
import pickle
import threading
from contextlib import contextmanager
from typing import Optional

from .clock import Clock
from .store import Client, Event


def _fs_escape(part: str) -> str:
    return part.replace("/", "_SL_").replace(":", "_CO_")


class FileClient(Client):
    """Client with write-through pickle persistence and copy semantics."""

    def __init__(self, clock: Optional[Clock] = None, root: str = None):
        super().__init__(clock)
        if root is None:
            raise ValueError("FileClient requires a root directory")
        self._root = root
        self._tls = threading.local()
        os.makedirs(root, exist_ok=True)
        self._load()

    # -- persistence ------------------------------------------------------

    def _path(self, key) -> str:
        kind, ns, name = key
        return os.path.join(
            self._root, _fs_escape(kind),
            f"{_fs_escape(ns)}__{_fs_escape(name)}.pkl",
        )

    def _load(self) -> None:
        for kind in sorted(os.listdir(self._root)):
            kdir = os.path.join(self._root, kind)
            if not os.path.isdir(kdir):
                continue
            for fname in sorted(os.listdir(kdir)):
                with open(os.path.join(kdir, fname), "rb") as fh:
                    obj = pickle.load(fh)
                key = self._key(obj)
                self._objects[key] = obj
                self._by_uid[obj.metadata.uid] = key
                self._index_insert(key, obj)
                self._rv = max(self._rv, obj.metadata.resource_version or 0)

    def _sync(self, key) -> None:
        """Write-through: the stored object's file mirrors the dict."""
        path = self._path(key)
        obj = self._objects.get(key)
        if obj is None:
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            pickle.dump(obj, fh)
        os.replace(tmp, path)

    @staticmethod
    def _copy(obj):
        # serialization round-trip, not copy.deepcopy: this is the point of
        # the backend — anything unpicklable or reference-dependent fails
        # HERE rather than at a future process boundary
        return pickle.loads(pickle.dumps(obj))

    # -- Client overrides -------------------------------------------------

    @contextmanager
    def _atomic(self):
        """Dict mutation + disk sync under one lock, watcher notification
        AFTER release. Without the lock, two racing writers can persist
        the OLDER version last (a restart would resume a state no watcher
        ever saw); but the base Client deliberately notifies OUTSIDE its
        lock — informer handlers take their own locks and also call back
        into client reads, so notifying under the store lock is a classic
        ABBA deadlock. Events buffer (with their under-lock snapshot
        copies) and emit on exit."""
        if getattr(self._tls, "pending", None) is not None:
            yield  # nested: the outermost frame owns emission
            return
        buf: list = []
        self._tls.pending = buf
        try:
            with self._lock:
                yield
        finally:
            self._tls.pending = None
        for ev in buf:
            for handler in list(self._watchers):
                # one fresh copy PER handler: watchers must not observe
                # each other's mutations either
                handler(Event(ev.type, ev.kind, self._copy(ev.object)))

    def _notify(self, event: Event) -> None:
        buf = getattr(self._tls, "pending", None)
        snapshot = Event(event.type, event.kind, self._copy(event.object))
        if buf is not None:
            buf.append(snapshot)
            return
        # safe despite running under the base class's lock on paths like
        # Client.create -> _notify: every FileClient CRUD enters through
        # _atomic, which installs the TLS pending buffer BEFORE taking the
        # lock, so under the lock this fallback is unreachable (the branch
        # above buffers). It only fires for lock-free notify paths.
        for handler in list(self._watchers):
            # analysis: ignore[LCK202] TLS pending buffer set before lock acquisition makes this branch lock-free
            handler(Event(snapshot.type, snapshot.kind, self._copy(snapshot.object)))

    def create(self, obj):
        stored = self._copy(obj)
        with self._atomic():
            super().create(stored)
            self._sync(self._key(stored))
        # the caller's handle gets the server-stamped metadata, like a
        # client receiving the created object back
        obj.metadata.resource_version = stored.metadata.resource_version
        obj.metadata.creation_timestamp = stored.metadata.creation_timestamp
        return obj

    def get(self, kind, name: str, namespace: str = "default"):
        return self._copy(super().get(kind, name, namespace))

    def get_by_uid(self, uid: str):
        return self._copy(super().get_by_uid(uid))

    def list(self, kind, namespace=None, predicate=None,
             label_selector=None, field_selector=None):
        out = [
            self._copy(o)
            for o in super().list(
                kind, namespace,
                label_selector=label_selector, field_selector=field_selector,
            )
        ]
        if predicate is not None:
            out = [o for o in out if predicate(o)]
        return out

    def update(self, obj):
        stored = self._copy(obj)
        with self._atomic():
            super().update(stored)
            self._sync(self._key(stored))
        obj.metadata.resource_version = stored.metadata.resource_version
        return obj

    def delete(self, obj, grace_period: Optional[float] = None):
        with self._atomic():
            stored = super().delete(obj, grace_period)
            self._sync(self._key(stored))
        return self._copy(stored)

    def remove_finalizer(self, obj, finalizer: str) -> None:
        key = self._key(obj)
        with self._atomic():
            super().remove_finalizer(obj, finalizer)
            self._sync(key)
