"""Lease-based leader election (reference: operator.go:137-141).

The reference delegates to controller-runtime's coordination/v1 Lease
machinery for active/passive HA; this is the same protocol over the
in-process store: one Lease object per election name, acquired when free or
expired, renewed while held. Non-leader operators keep their watch-fed
caches warm but skip reconciling (``Operator.step`` gates on ``is_leader``).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field

from ..api.objects import ObjectMeta
from .store import AlreadyExistsError, ConflictError


@dataclass
class Lease:
    """coordination/v1 Lease analog."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    renew_time: float = 0.0
    lease_duration_seconds: float = 15.0

    @property
    def name(self) -> str:
        return self.metadata.name


class LeaderElector:
    """Acquire-or-renew loop over a named Lease.

    ``try_acquire`` is called once per operator step: it renews when held,
    steals when the previous holder's lease expired, and reports standby
    otherwise — the lease-duration/renew-deadline shape of
    client-go's leaderelection package.
    """

    def __init__(
        self,
        client,
        name: str = "karpenter-leader-election",
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        identity: str = "",
    ):
        self._client = client
        self._name = name
        self._namespace = namespace
        self._duration = lease_duration
        self.identity = identity or f"operator-{uuid.uuid4().hex[:8]}"

    def _get(self):
        for lease in self._client.list(Lease):
            if (
                lease.metadata.name == self._name
                and lease.metadata.namespace == self._namespace
            ):
                return lease
        return None

    def try_acquire(self) -> bool:
        now = self._client.clock.now()
        lease = self._get()
        if lease is None:
            lease = Lease(
                metadata=ObjectMeta(name=self._name, namespace=self._namespace),
                holder_identity=self.identity,
                renew_time=now,
                lease_duration_seconds=self._duration,
            )
            try:
                self._client.create(lease)
                return True
            except AlreadyExistsError:
                return False  # lost the race; stand by until next step
        if lease.holder_identity == self.identity:
            lease.renew_time = now
            self._update(lease)
            return True
        if now - lease.renew_time > lease.lease_duration_seconds:
            # previous holder went dark: steal the lease
            lease.holder_identity = self.identity
            lease.renew_time = now
            return self._update(lease)
        return False

    def _update(self, lease) -> bool:
        try:
            self._client.update(lease)
            return True
        except (ConflictError, KeyError):
            return False
