"""Injectable clock, mirroring the reference's use of k8s.io/utils/clock.

Controllers never call time.time() directly; tests drive a TestClock the way
the reference's suites drive clock.FakeClock.
"""

from __future__ import annotations

import abc
import time


class Clock(abc.ABC):
    @abc.abstractmethod
    def now(self) -> float:
        ...

    @abc.abstractmethod
    def sleep(self, seconds: float) -> None:
        ...

    def since(self, t: float) -> float:
        return self.now() - t


class RealClock(Clock):
    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class TestClock(Clock):
    __test__ = False  # not a pytest class

    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self._now += seconds

    def step(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t
