from .clock import Clock, RealClock, TestClock
from .filestore import FileClient
from .store import Client, Event, NotFoundError, ConflictError, AlreadyExistsError

__all__ = [
    "Clock",
    "RealClock",
    "TestClock",
    "Client",
    "FileClient",
    "Event",
    "NotFoundError",
    "ConflictError",
    "AlreadyExistsError",
]
