from .clock import Clock, RealClock, TestClock
from .store import Client, Event, NotFoundError, ConflictError, AlreadyExistsError

__all__ = [
    "Clock",
    "RealClock",
    "TestClock",
    "Client",
    "Event",
    "NotFoundError",
    "ConflictError",
    "AlreadyExistsError",
]
