"""In-process typed object store standing in for the kube-apiserver.

The reference is level-triggered against a real apiserver: all durable state
lives in CRs, caches are rebuilt from watches, deletion is a two-phase
finalizer dance. This store reproduces those semantics in-process:

- objects are keyed by (kind, namespace, name) and carry resource versions;
- ``delete`` stamps ``deletion_timestamp`` when finalizers are present and
  only removes the object once the last finalizer is gone;
- watchers receive ADDED/MODIFIED/DELETED events synchronously, which is what
  the informer controllers in controllers/state consume.

Objects are stored by reference (single process); callers mutate copies and
``update`` them, mirroring client-go's update-by-replacement.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .. import faults
from .clock import Clock, RealClock

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ConflictError(ValueError):
    pass


@dataclass
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    object: object


def kind_of(obj) -> str:
    return type(obj).__name__


class Client:
    """Typed in-memory object store with watch + finalizer semantics."""

    def __init__(
        self, clock: Optional[Clock] = None, fault_injection: bool = True
    ):
        self._clock = clock or RealClock()
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._by_uid: Dict[str, Tuple[str, str, str]] = {}
        self._watchers: List[Callable[[Event], None]] = []
        self._lock = threading.RLock()
        self._rv = 0
        # fault_injection=False exempts this store from the chaos seams:
        # scratch stores (the solver's shipped-cluster-view rebuild in
        # solver/service.py) model plain memory, not an apiserver — a
        # store-chaos plan must not crash the very fallback path that
        # exists to survive the injected outage
        self._fault_injection = fault_injection

    # -- watch ------------------------------------------------------------

    def watch(self, handler: Callable[[Event], None]) -> None:
        self._watchers.append(handler)

    def _notify(self, event: Event) -> None:
        for handler in list(self._watchers):
            handler(event)

    # -- helpers ----------------------------------------------------------

    def _key(self, obj) -> Tuple[str, str, str]:
        meta = obj.metadata
        return (kind_of(obj), getattr(meta, "namespace", "default"), meta.name)

    def _bump(self, obj) -> None:
        self._rv += 1
        obj.metadata.resource_version = self._rv

    # -- CRUD -------------------------------------------------------------

    def create(self, obj):
        # chaos seam: a real apiserver returns transient 409s/timeouts;
        # fault plans inject ConflictError/latency here (faults/)
        if self._fault_injection:
            faults.hit(faults.STORE_CREATE, kind=kind_of(obj))
        with self._lock:
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self._clock.now()
            self._bump(obj)
            self._objects[key] = obj
            self._by_uid[obj.metadata.uid] = key
        self._notify(Event(ADDED, key[0], obj))
        return obj

    def get(self, kind, name: str, namespace: str = "default"):
        kind_name = kind if isinstance(kind, str) else kind.__name__
        with self._lock:
            obj = self._objects.get((kind_name, namespace, name))
        if obj is None:
            raise NotFoundError(f"{kind_name} {namespace}/{name} not found")
        return obj

    def get_by_uid(self, uid: str):
        with self._lock:
            key = self._by_uid.get(uid)
            if key is None:
                raise NotFoundError(f"uid {uid} not found")
            return self._objects[key]

    def try_get(self, kind, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind, namespace: Optional[str] = None, predicate=None) -> List:
        kind_name = kind if isinstance(kind, str) else kind.__name__
        with self._lock:
            out = [
                o
                for (k, ns, _), o in self._objects.items()
                if k == kind_name and (namespace is None or ns == namespace)
            ]
        if predicate is not None:
            out = [o for o in out if predicate(o)]
        return out

    def update(self, obj):
        if self._fault_injection:
            faults.hit(faults.STORE_UPDATE, kind=kind_of(obj))
        with self._lock:
            key = self._key(obj)
            if key not in self._objects:
                raise NotFoundError(f"{key} not found")
            self._bump(obj)
            self._objects[key] = obj
        self._notify(Event(MODIFIED, key[0], obj))
        return obj

    def update_status(self, obj):
        # Single-store process: status updates are plain updates.
        return self.update(obj)

    def delete(self, obj, grace_period: Optional[float] = None):
        """Two-phase delete honoring finalizers (apiserver semantics)."""
        if self._fault_injection:
            faults.hit(faults.STORE_DELETE, kind=kind_of(obj))
        with self._lock:
            key = self._key(obj)
            stored = self._objects.get(key)
            if stored is None:
                raise NotFoundError(f"{key} not found")
            if stored.metadata.finalizers:
                if stored.metadata.deletion_timestamp is None:
                    stored.metadata.deletion_timestamp = self._clock.now()
                    self._bump(stored)
                    event = Event(MODIFIED, key[0], stored)
                else:
                    return stored
            else:
                del self._objects[key]
                self._by_uid.pop(stored.metadata.uid, None)
                event = Event(DELETED, key[0], stored)
        self._notify(event)
        return stored

    def remove_finalizer(self, obj, finalizer: str) -> None:
        """Drop a finalizer; completes deletion if it was the last one and the
        object was marked deleted."""
        with self._lock:
            key = self._key(obj)
            stored = self._objects.get(key)
            if stored is None:
                return
            if finalizer in stored.metadata.finalizers:
                stored.metadata.finalizers.remove(finalizer)
            if not stored.metadata.finalizers and stored.metadata.deletion_timestamp is not None:
                del self._objects[key]
                self._by_uid.pop(stored.metadata.uid, None)
                event = Event(DELETED, key[0], stored)
            else:
                self._bump(stored)
                event = Event(MODIFIED, key[0], stored)
        self._notify(event)

    def deleted(self, obj) -> bool:
        return obj.metadata.deletion_timestamp is not None

    # -- checkpoint (sim/twin.py) -----------------------------------------

    def export_objects(self) -> dict:
        """Deep-copied objects in insertion order plus the resource-version
        counter — the store side of a twin checkpoint. Insertion order IS
        part of cluster state here: list() serves it, and the reconcile
        roster's iteration (and therefore replay determinism) follows it."""
        with self._lock:
            return {
                "rv": self._rv,
                "objects": [copy.deepcopy(o) for o in self._objects.values()],
            }

    def import_objects(self, state: dict) -> None:
        """Restore an export_objects() dump into an EMPTY store. No watch
        events fire — informer consumers (controllers/state.Cluster) are
        constructed AFTER the import and ingest via their LIST pass, the
        same recovery shape a live informer has after a restart."""
        with self._lock:
            if self._objects:
                raise ValueError("import_objects requires an empty store")
            for obj in state["objects"]:
                stored = copy.deepcopy(obj)
                key = self._key(stored)
                self._objects[key] = stored
                self._by_uid[stored.metadata.uid] = key
            self._rv = int(state["rv"])

    @property
    def clock(self) -> Clock:
        return self._clock
