"""In-process typed object store standing in for the kube-apiserver.

The reference is level-triggered against a real apiserver: all durable state
lives in CRs, caches are rebuilt from watches, deletion is a two-phase
finalizer dance. This store reproduces those semantics in-process:

- objects are keyed by (kind, namespace, name) and carry resource versions;
- ``delete`` stamps ``deletion_timestamp`` when finalizers are present and
  only removes the object once the last finalizer is gone;
- watchers receive ADDED/MODIFIED/DELETED events synchronously, which is what
  the informer controllers in controllers/state consume.

Objects are stored by reference (single process); callers mutate copies and
``update`` them, mirroring client-go's update-by-replacement.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .. import faults
from .clock import Clock, RealClock

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ConflictError(ValueError):
    pass


@dataclass
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    object: object


def kind_of(obj) -> str:
    return type(obj).__name__


# indexed field selectors per kind: selector name -> extractor. The store
# maintains an exact index over these on every CRUD, so hot sweeps (the
# twin's informer rebuilds, per-node pod lookups at 100k-node scale) read
# the index instead of scanning every object of every kind.
_FIELD_EXTRACTORS: Dict[str, Dict[str, Callable[[object], Optional[str]]]] = {
    "Pod": {"spec.nodeName": lambda o: o.spec.node_name},
}

_EMPTY: frozenset = frozenset()


class Client:
    """Typed in-memory object store with watch + finalizer semantics.

    Reads are indexed: objects bucket per kind, and label values plus the
    ``_FIELD_EXTRACTORS`` fields maintain exact inverted indexes —
    ``list(kind, label_selector=..., field_selector=...)`` touches only
    matching objects (insertion-ordered, same as a full scan would
    return). The indexes are maintained on create/update/delete; mutating
    a stored object's labels WITHOUT ``update()`` is outside the store's
    contract (callers mutate copies and update them — module docstring)
    and leaves the index stale exactly like a real informer cache.
    """

    def __init__(
        self, clock: Optional[Clock] = None, fault_injection: bool = True
    ):
        self._clock = clock or RealClock()
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._by_uid: Dict[str, Tuple[str, str, str]] = {}
        # per-kind bucket + label/field inverted indexes; _indexed records
        # (insertion seq, indexed terms) per key so de-indexing is exact
        # even when the caller mutated the stored object before update()
        self._by_kind: Dict[str, Dict[Tuple[str, str, str], object]] = {}
        self._label_idx: Dict[tuple, set] = {}
        self._field_idx: Dict[tuple, set] = {}
        self._indexed: Dict[Tuple[str, str, str], Tuple[int, list]] = {}
        self._ins_seq = 0
        self._watchers: List[Callable[[Event], None]] = []
        self._lock = threading.RLock()
        self._rv = 0
        # fault_injection=False exempts this store from the chaos seams:
        # scratch stores (the solver's shipped-cluster-view rebuild in
        # solver/service.py) model plain memory, not an apiserver — a
        # store-chaos plan must not crash the very fallback path that
        # exists to survive the injected outage
        self._fault_injection = fault_injection

    # -- watch ------------------------------------------------------------

    def watch(self, handler: Callable[[Event], None]) -> None:
        self._watchers.append(handler)

    def _notify(self, event: Event) -> None:
        for handler in list(self._watchers):
            handler(event)

    # -- helpers ----------------------------------------------------------

    def _key(self, obj) -> Tuple[str, str, str]:
        meta = obj.metadata
        return (kind_of(obj), getattr(meta, "namespace", "default"), meta.name)

    def _bump(self, obj) -> None:
        self._rv += 1
        obj.metadata.resource_version = self._rv

    # -- index maintenance (call under self._lock) -------------------------

    def _index_insert(self, key, obj) -> None:
        kind = key[0]
        self._by_kind.setdefault(kind, {})[key] = obj
        terms: list = []
        labels = getattr(obj.metadata, "labels", None) or {}
        for k, v in labels.items():
            t = ("l", kind, k, v)
            self._label_idx.setdefault(t, set()).add(key)
            terms.append(t)
        for field, fn in _FIELD_EXTRACTORS.get(kind, {}).items():
            try:
                # analysis: ignore[LCK202] module-local pure attribute extractor, not a caller-registered callback — cannot reenter the store
                v = fn(obj)
            except AttributeError:
                v = None
            if v:
                t = ("f", kind, field, v)
                self._field_idx.setdefault(t, set()).add(key)
                terms.append(t)
        seq = self._indexed[key][0] if key in self._indexed else None
        if seq is None:
            self._ins_seq += 1
            seq = self._ins_seq
        self._indexed[key] = (seq, terms)

    def _index_drop(self, key, keep_seq: bool = False) -> None:
        entry = self._indexed.get(key)
        if entry is None:
            return
        seq, terms = entry
        for t in terms:
            d = self._label_idx if t[0] == "l" else self._field_idx
            s = d.get(t)
            if s is not None:
                s.discard(key)
                if not s:
                    del d[t]
        if keep_seq:
            # re-index of a replaced object: keep its insertion position
            # so selector results stay insertion-ordered like a full scan
            self._indexed[key] = (seq, [])
        else:
            del self._indexed[key]
            bucket = self._by_kind.get(key[0])
            if bucket is not None:
                bucket.pop(key, None)

    # -- CRUD -------------------------------------------------------------

    def create(self, obj):
        # chaos seam: a real apiserver returns transient 409s/timeouts;
        # fault plans inject ConflictError/latency here (faults/)
        if self._fault_injection:
            faults.hit(faults.STORE_CREATE, kind=kind_of(obj))
        with self._lock:
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self._clock.now()
            self._bump(obj)
            self._objects[key] = obj
            self._by_uid[obj.metadata.uid] = key
            self._index_insert(key, obj)
        self._notify(Event(ADDED, key[0], obj))
        return obj

    def get(self, kind, name: str, namespace: str = "default"):
        kind_name = kind if isinstance(kind, str) else kind.__name__
        with self._lock:
            obj = self._objects.get((kind_name, namespace, name))
        if obj is None:
            raise NotFoundError(f"{kind_name} {namespace}/{name} not found")
        return obj

    def get_by_uid(self, uid: str):
        with self._lock:
            key = self._by_uid.get(uid)
            if key is None:
                raise NotFoundError(f"uid {uid} not found")
            return self._objects[key]

    def try_get(self, kind, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(
        self,
        kind,
        namespace: Optional[str] = None,
        predicate=None,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List:
        """LIST a kind, optionally narrowed by exact-match selectors.

        ``label_selector``/``field_selector`` read the inverted indexes —
        cost is proportional to the MATCH, not the kind's population (the
        100k-node twin's informer-rebuild wall). Field selectors must name
        an indexed field (``_FIELD_EXTRACTORS``); unknown fields raise
        rather than silently full-scanning. Results keep the insertion
        order a full scan would return."""
        kind_name = kind if isinstance(kind, str) else kind.__name__
        with self._lock:
            if label_selector or field_selector:
                sets = []
                for k, v in (label_selector or {}).items():
                    sets.append(
                        self._label_idx.get(("l", kind_name, k, v), _EMPTY)
                    )
                for f, v in (field_selector or {}).items():
                    if f not in _FIELD_EXTRACTORS.get(kind_name, {}):
                        raise ValueError(
                            f"field selector {f!r} is not indexed for"
                            f" {kind_name} (see store._FIELD_EXTRACTORS)"
                        )
                    sets.append(
                        self._field_idx.get(("f", kind_name, f, v), _EMPTY)
                    )
                ordered = sorted(sets, key=len)
                keys = set(ordered[0])
                for s in ordered[1:]:
                    keys &= s
                out = [
                    self._objects[k2]
                    for k2 in sorted(keys, key=lambda k2: self._indexed[k2][0])
                    if namespace is None or k2[1] == namespace
                ]
            else:
                out = [
                    o
                    for (_, ns, _), o in self._by_kind.get(
                        kind_name, {}
                    ).items()
                    if namespace is None or ns == namespace
                ]
        if predicate is not None:
            out = [o for o in out if predicate(o)]
        return out

    def _reindex_stored(self, obj) -> None:
        """Re-derive the stored object's index terms from its CURRENT
        content. Callers that mutate the stored reference in place and
        then hit an injected conflict (the chaos seams below raise BEFORE
        the index maintenance runs) would otherwise leave the inverted
        indexes describing the pre-mutation object while a full scan sees
        the mutation — the index==scan invariant the selector reads are
        built on."""
        with self._lock:
            key = self._key(obj)
            stored = self._objects.get(key)
            if stored is not None:
                self._index_drop(key, keep_seq=True)
                self._index_insert(key, stored)

    def update(self, obj):
        if self._fault_injection:
            try:
                faults.hit(faults.STORE_UPDATE, kind=kind_of(obj))
            except Exception:
                self._reindex_stored(obj)
                raise
        with self._lock:
            key = self._key(obj)
            if key not in self._objects:
                raise NotFoundError(f"{key} not found")
            self._bump(obj)
            # de-index on the terms recorded at insert time (exact even
            # when the caller mutated the stored object before update),
            # keeping the insertion seq so list order matches a full scan
            self._index_drop(key, keep_seq=True)
            self._objects[key] = obj
            self._index_insert(key, obj)
        self._notify(Event(MODIFIED, key[0], obj))
        return obj

    def update_status(self, obj):
        # Single-store process: status updates are plain updates.
        return self.update(obj)

    def delete(self, obj, grace_period: Optional[float] = None):
        """Two-phase delete honoring finalizers (apiserver semantics)."""
        if self._fault_injection:
            try:
                faults.hit(faults.STORE_DELETE, kind=kind_of(obj))
            except Exception:
                # same healing as update(): the caller may have mutated
                # the stored reference before the injected failure
                self._reindex_stored(obj)
                raise
        with self._lock:
            key = self._key(obj)
            stored = self._objects.get(key)
            if stored is None:
                raise NotFoundError(f"{key} not found")
            if stored.metadata.finalizers:
                if stored.metadata.deletion_timestamp is None:
                    stored.metadata.deletion_timestamp = self._clock.now()
                    self._bump(stored)
                    event = Event(MODIFIED, key[0], stored)
                else:
                    return stored
            else:
                del self._objects[key]
                self._by_uid.pop(stored.metadata.uid, None)
                self._index_drop(key)
                event = Event(DELETED, key[0], stored)
        self._notify(event)
        return stored

    def remove_finalizer(self, obj, finalizer: str) -> None:
        """Drop a finalizer; completes deletion if it was the last one and the
        object was marked deleted."""
        with self._lock:
            key = self._key(obj)
            stored = self._objects.get(key)
            if stored is None:
                return
            if finalizer in stored.metadata.finalizers:
                stored.metadata.finalizers.remove(finalizer)
            if not stored.metadata.finalizers and stored.metadata.deletion_timestamp is not None:
                del self._objects[key]
                self._by_uid.pop(stored.metadata.uid, None)
                self._index_drop(key)
                event = Event(DELETED, key[0], stored)
            else:
                self._bump(stored)
                event = Event(MODIFIED, key[0], stored)
        self._notify(event)

    def deleted(self, obj) -> bool:
        return obj.metadata.deletion_timestamp is not None

    # -- checkpoint (sim/twin.py) -----------------------------------------

    def export_objects(self) -> dict:
        """Deep-copied objects in insertion order plus the resource-version
        counter — the store side of a twin checkpoint. Insertion order IS
        part of cluster state here: list() serves it, and the reconcile
        roster's iteration (and therefore replay determinism) follows it."""
        with self._lock:
            return {
                "rv": self._rv,
                "objects": [copy.deepcopy(o) for o in self._objects.values()],
            }

    def import_objects(self, state: dict) -> None:
        """Restore an export_objects() dump into an EMPTY store. No watch
        events fire — informer consumers (controllers/state.Cluster) are
        constructed AFTER the import and ingest via their LIST pass, the
        same recovery shape a live informer has after a restart."""
        with self._lock:
            if self._objects:
                raise ValueError("import_objects requires an empty store")
            for obj in state["objects"]:
                stored = copy.deepcopy(obj)
                key = self._key(stored)
                self._objects[key] = stored
                self._by_uid[stored.metadata.uid] = key
                self._index_insert(key, stored)
            self._rv = int(state["rv"])

    @property
    def clock(self) -> Clock:
        return self._clock
