"""Pod classification helpers (reference: pkg/utils/pod/scheduling.go)."""

from __future__ import annotations

from ..api import labels as labels_mod
from ..api.objects import Pod


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Succeeded", "Failed")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)


def is_preempting(pod: Pod) -> bool:
    return bool(pod.status.nominated_node_name)


def is_owned_by_daemonset(pod: Pod, daemonset_uids) -> bool:
    return any(uid in daemonset_uids for uid in pod.metadata.owner_uids)


def is_provisionable(pod: Pod) -> bool:
    """Unschedulable pending pods the provisioner should act on."""
    return (
        not is_scheduled(pod)
        and not is_preempting(pod)
        and not is_terminal(pod)
        and not is_terminating(pod)
        and pod.status.phase == "Pending"
    )


def is_reschedulable(pod: Pod) -> bool:
    """Pods that must be able to land elsewhere when a node is disrupted."""
    return not is_terminal(pod) and not is_terminating(pod) and not is_owned_by_node(pod)


def is_owned_by_node(pod: Pod) -> bool:
    # static/mirror pods: owner is the node itself; approximated by annotation
    return pod.metadata.annotations.get("kubernetes.io/config.source") == "file"


def is_disruptable(pod: Pod) -> bool:
    return pod.metadata.annotations.get(labels_mod.DO_NOT_DISRUPT_ANNOTATION_KEY) != "true"


def is_active(pod: Pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)
